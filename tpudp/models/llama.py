"""LLaMA-family decoder — beyond-parity model family (round 5).

The reference has no sequence models at all (its surface is the VGG/CIFAR
DP ladder, `src/Part 1/model.py`); tpudp already goes beyond it with GPT-2
(learned positions, LayerNorm, GELU, tied head).  This module adds the
other dominant decoder lineage so the framework demonstrably generalizes
across architecture families rather than special-casing one:

  * **RoPE** (rotary position embedding) — positions enter as a rotation
    of q/k instead of a learned table, so context length is not baked
    into the parameters and attention scores depend only on RELATIVE
    position (pinned by tests/test_llama.py::test_rope_is_relative).
  * **RMSNorm** (no mean subtraction, no bias) in fp32, like the GPT-2
    module's LayerNorm policy.
  * **SwiGLU MLP** (gate ⊙ silu, then down-projection), bias-free Dense
    throughout, untied output head — the LLaMA parameterization.
  * **GQA** (grouped-query attention): ``num_kv_heads < num_heads``
    shrinks the KV projections (and a decode cache) by the group factor;
    KV heads are broadcast to query heads before the attention op, so the
    same pluggable backends (`dense`/`flash`/`ring`) serve GQA unchanged.

Composes with the existing machinery, not beside it: attention goes
through ``tpudp.ops.attention.multihead_attention`` (so ``attn_impl='ring'``
+ ``seq_axis`` gives sequence-parallel long-context training, with RoPE
positions offset per sequence shard exactly like GPT-2's learned
positions), and ``tpudp.parallel.tensor.llama_tp_rules`` gives the
Megatron-style GSPMD sharding.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpudp.mesh import axis_is_bound as _axis_is_bound


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32_000
    max_seq_len: int = 2048  # documentation/decode bound; RoPE needs no table
    num_layers: int = 8
    num_heads: int = 8
    num_kv_heads: int | None = None  # None -> MHA; < num_heads -> GQA
    d_model: int = 512
    mlp_hidden: int | None = None  # None -> LLaMA's 8/3*d rounded up to 128
    rope_theta: float = 10_000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.float32
    attn_impl: str = "dense"  # 'dense' | 'flash' | 'ring'
    seq_axis: str | None = None  # mesh axis for ring attention

    def __post_init__(self):
        if self.attn_impl not in ("dense", "flash", "ring"):
            raise ValueError(
                f"unknown attn_impl {self.attn_impl!r}; "
                "choose from 'dense', 'flash', 'ring'")
        if self.num_kv_heads is not None and not (
                0 < self.num_kv_heads <= self.num_heads):
            raise ValueError(
                f"num_kv_heads {self.num_kv_heads} must be in "
                f"[1, num_heads={self.num_heads}]")
        kv = self.kv_heads
        if self.num_heads % kv:
            raise ValueError(
                f"num_heads {self.num_heads} not divisible by "
                f"num_kv_heads {kv} (GQA groups must be equal-sized)")
        if self.d_model % self.num_heads:
            raise ValueError(
                f"d_model {self.d_model} not divisible by num_heads "
                f"{self.num_heads}")
        if (self.d_model // self.num_heads) % 2:
            raise ValueError("RoPE needs an even head dim")

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def hidden(self) -> int:
        if self.mlp_hidden is not None:
            return self.mlp_hidden
        # LLaMA's 2/3 * 4d rule, rounded up to a multiple of 128 (lane
        # width — keeps the SwiGLU matmuls MXU-tileable).
        h = (8 * self.d_model) // 3
        return ((h + 127) // 128) * 128


def llama_small(**overrides) -> "Llama":
    return Llama(LlamaConfig(**overrides))


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray,
               theta: float = 10_000.0) -> jnp.ndarray:
    """Rotate ``x`` (B, T, H, Dh) by position-dependent angles.

    ``positions`` is ``(T,)`` (every batch row at the same positions —
    training and the generate/beam decode) or ``(B, T)`` (per-row
    positions — the serve engine's slot arena, where each slot sits at a
    different depth).  Rotate-half convention: the head dim is split in
    two halves that form the (real, imag) parts of Dh/2 complex pairs;
    pair ``i`` turns by ``positions / theta**(2i/Dh)``.  Computed in fp32
    (angles at large positions lose precision in bf16) and cast back to
    ``x.dtype``.
    """
    half = x.shape[-1] // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32)
                                * 2.0 / x.shape[-1]))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    cos = jnp.cos(angles)[..., None, :]  # (T, 1, half) or (B, T, 1, half)
    sin = jnp.sin(angles)[..., None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 positions: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        b, t, d = x.shape
        h, kv = cfg.num_heads, cfg.kv_heads
        dh = d // h
        q = nn.Dense(h * dh, use_bias=False, dtype=cfg.dtype, name="wq")(x)
        k = nn.Dense(kv * dh, use_bias=False, dtype=cfg.dtype, name="wk")(x)
        v = nn.Dense(kv * dh, use_bias=False, dtype=cfg.dtype, name="wv")(x)
        q = apply_rope(q.reshape(b, t, h, dh), positions, cfg.rope_theta)
        k = apply_rope(k.reshape(b, t, kv, dh), positions, cfg.rope_theta)
        v = v.reshape(b, t, kv, dh)
        if kv != h:
            # Broadcast each KV head to its query group, so every
            # attention backend (dense/flash/ring) serves GQA unchanged.
            k = jnp.repeat(k, h // kv, axis=2)
            v = jnp.repeat(v, h // kv, axis=2)
        from tpudp.ops.attention import multihead_attention

        out = multihead_attention(q, k, v, causal=True, impl=cfg.attn_impl,
                                  dtype=cfg.dtype, seq_axis=cfg.seq_axis)
        return nn.Dense(d, use_bias=False, dtype=cfg.dtype,
                        name="wo")(out.reshape(b, t, d))


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 positions: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        rms = lambda name: nn.RMSNorm(epsilon=cfg.rms_eps,
                                      dtype=jnp.float32, name=name)
        x = x + LlamaAttention(cfg, name="attn")(rms("rms_attn")(x),
                                                 positions)
        hdn = rms("rms_mlp")(x)
        gate = nn.Dense(cfg.hidden, use_bias=False, dtype=cfg.dtype,
                        name="gate")(hdn)
        up = nn.Dense(cfg.hidden, use_bias=False, dtype=cfg.dtype,
                      name="up")(hdn)
        down = nn.Dense(cfg.d_model, use_bias=False, dtype=cfg.dtype,
                        name="down")(nn.silu(gate) * up)
        return x + down


def _rms(p: dict, x: jnp.ndarray, eps: float) -> jnp.ndarray:
    """Exactly the training model's RMSNorm (flax apply on the raw
    subtree) so decode can never drift numerically from LlamaBlock's."""
    return nn.RMSNorm(epsilon=eps, dtype=jnp.float32).apply(
        {"params": p}, x)


def _dense_nb(p: dict, x: jnp.ndarray, dtype) -> jnp.ndarray:
    return x.astype(dtype) @ p["kernel"].astype(dtype)


def embed_tokens(cfg: LlamaConfig, params: dict,
                 tokens: jnp.ndarray) -> jnp.ndarray:
    """Raw-param twin of the embedding stage of :meth:`Llama.__call__`
    (wte lookup only — positions enter via RoPE inside the blocks)."""
    return params["wte"]["embedding"].astype(cfg.dtype)[tokens]


def lm_head(cfg: LlamaConfig, params: dict, x: jnp.ndarray) -> jnp.ndarray:
    """Raw-param twin of the output stage (final RMSNorm + untied head)."""
    x = _rms(params["rms_f"], x, cfg.rms_eps)
    return _dense_nb(params["lm_head"], x.astype(cfg.dtype),
                     cfg.dtype).astype(jnp.float32)


def block_decode(cfg: LlamaConfig, p: dict, x: jnp.ndarray,
                 k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                 pos: jnp.ndarray, paged=None):
    """One LLaMA block on ``(batch, cur, d)`` new tokens at absolute
    positions ``pos .. pos+cur-1``, reading/writing a GQA-width KV cache
    ``(batch, max_len, kv_heads, head_dim)`` — the cache is ``kv_heads /
    num_heads`` the size of an MHA cache, GQA's whole point at decode
    time.  ``pos`` is a scalar (whole batch at one depth) or a
    ``(batch,)`` vector of per-row depths (tpudp.serve's slot arena);
    the scalar path compiles to the program it always did.  Mirrors
    LlamaBlock exactly (the greedy-parity test referees).

    The serve engine's PAGED mode (``Engine(kv_pages=N)``) runs this
    same function with ``paged`` set (a ``generate._PagedKV`` store —
    pages allocate at GQA width, so the grouped-attention memory
    saving carries over to the pool): K/V write as single-token page
    commits and attention reads THROUGH the block table inside the
    contraction (``tpudp.ops.paged_attention``'s grouped einsum family
    — the blockwise twins of the einsums below), never materializing
    the ``(batch, max_len, kv_heads, head_dim)`` view.  Identical
    stored values ⇒ bit-identical attention out, which is what keeps
    paged reads ≡ dense reads
    (tests/test_paged.py::test_paged_llama_gqa_parity)."""
    b, cur, d = x.shape
    h, kv = cfg.num_heads, cfg.kv_heads
    dh = d // h
    max_len = k_cache.shape[1] if paged is None else None
    pos = jnp.asarray(pos)
    per_row = bool(pos.ndim)
    # (cur,) shared positions, or (b, cur) per-row — apply_rope and the
    # visibility mask below broadcast either shape.
    positions = (pos[:, None] + jnp.arange(cur)) if per_row \
        else pos + jnp.arange(cur)

    hN = _rms(p["rms_attn"], x, cfg.rms_eps)
    attn = p["attn"]
    q = apply_rope(_dense_nb(attn["wq"], hN, cfg.dtype).reshape(b, cur, h,
                                                                dh),
                   positions, cfg.rope_theta)
    k = apply_rope(_dense_nb(attn["wk"], hN, cfg.dtype).reshape(b, cur, kv,
                                                                dh),
                   positions, cfg.rope_theta)
    v = _dense_nb(attn["wv"], hN, cfg.dtype).reshape(b, cur, kv, dh)
    from jax import lax

    if paged is not None:
        # Gather-free paged KV (write-before-attend order preserved —
        # the dense branch updates its cache before reading it too).
        paged.write(k, v)
        out = paged.attend(q)
    else:
        if per_row:
            from tpudp.models.generate import update_cache_rows

            k_cache = update_cache_rows(k_cache, k, pos)
            v_cache = update_cache_rows(v_cache, v, pos)
        else:
            k_cache = lax.dynamic_update_slice(k_cache, k, (0, pos, 0, 0))
            v_cache = lax.dynamic_update_slice(v_cache, v, (0, pos, 0, 0))

        # Grouped attention over the KV-width cache: query head j
        # attends KV head j // group (exactly the training path's
        # jnp.repeat semantics — q's head axis reshaped (kv, group)
        # keeps that mapping) WITHOUT materializing an MHA-width copy
        # of the cache, so the GQA memory saving holds during attention
        # too, not just in the cache buffer.  Same op/dtype sequence as
        # ops.attention's dense path (einsum in cfg.dtype, fp32
        # softmax) so bf16 rounding matches training exactly; the
        # per-pair dot products are identical to the repeat formulation.
        g = h // kv
        qg = q.reshape(b, cur, kv, g, dh)
        scale = dh ** -0.5
        if per_row:
            # One attention per window position (same rationale as the
            # GPT-2 twin): XLA's width-1 and width-W contractions
            # reduce in different blockings, so only the vmapped
            # per-position form keeps a speculative k+1-token verify
            # window bit-identical to k+1 single-token decodes
            # (tpudp.serve's exact-parity contract).
            def _attend(qj, pj):  # qj (b, kv, g, dh), pj (b,)
                lg = jnp.einsum("bkgd,bmkd->bkgm", qj, k_cache) * scale
                vis = jnp.arange(max_len)[None, None, None, :] \
                    <= pj[:, None, None, None]
                lg = jnp.where(vis, lg, jnp.finfo(lg.dtype).min)
                pr = jax.nn.softmax(lg.astype(jnp.float32),
                                    axis=-1).astype(cfg.dtype)
                return jnp.einsum("bkgm,bmkd->bkgd", pr, v_cache)

            out = jax.vmap(_attend, in_axes=(1, 1),
                           out_axes=1)(qg, positions)
        else:
            logits = jnp.einsum("bqkgd,bmkd->bkgqm", qg, k_cache) * scale
            visible = jnp.arange(max_len) <= positions[..., None]
            logits = jnp.where(visible[None, None, None], logits,
                               jnp.finfo(logits.dtype).min)
            probs = jax.nn.softmax(logits.astype(jnp.float32),
                                   axis=-1).astype(cfg.dtype)
            out = jnp.einsum("bkgqm,bmkd->bqkgd", probs, v_cache)
    x = x + _dense_nb(attn["wo"], out.reshape(b, cur, d), cfg.dtype)

    hN = _rms(p["rms_mlp"], x, cfg.rms_eps)
    gate = nn.silu(_dense_nb(p["gate"], hN, cfg.dtype))
    x = x + _dense_nb(p["down"],
                      gate * _dense_nb(p["up"], hN, cfg.dtype), cfg.dtype)
    return x, k_cache, v_cache


def block_tree(cfg: LlamaConfig, p: dict, x: jnp.ndarray,
               k_cache: jnp.ndarray, v_cache: jnp.ndarray,
               pos0: jnp.ndarray, positions: jnp.ndarray, anc: tuple,
               paged=None):
    """One LLaMA block over a speculative token TREE of ``T+1`` nodes —
    the NO-WRITE twin of :func:`block_decode`'s per-row path (see
    ``generate._block_tree`` for the scheme).  Sibling nodes share a
    logical position, so the window K/V never enter the cache: each
    node attends the committed cache (positions ``< pos0``) jointly
    with its in-window ancestors-or-self (``anc``, the tree shape's
    static ``(T+1, T+1)`` matrix) under one fp32 softmax.  RoPE rotates
    q/k at ``positions = pos0 + depth`` — node positions decouple from
    storage.  Returns ``(x, k_win, v_win)``; the caller commits the
    accepted path's K/V only."""
    b, T1, d = x.shape
    h, kv = cfg.num_heads, cfg.kv_heads
    dh = d // h
    max_len = k_cache.shape[1] if paged is None else None

    hN = _rms(p["rms_attn"], x, cfg.rms_eps)
    attn = p["attn"]
    q = apply_rope(_dense_nb(attn["wq"], hN, cfg.dtype).reshape(b, T1, h,
                                                                dh),
                   positions, cfg.rope_theta)
    k = apply_rope(_dense_nb(attn["wk"], hN, cfg.dtype).reshape(b, T1, kv,
                                                                dh),
                   positions, cfg.rope_theta)
    v = _dense_nb(attn["wv"], hN, cfg.dtype).reshape(b, T1, kv, dh)

    if paged is not None:
        # Kernelized paged tree read (generate._TreePagedKV → the tree
        # kernel, GQA handled by the kernel's grouped row layout): the
        # window K/V ride as kernel operands, never entering the pages.
        out = paged.attend(q, k, v)
    else:
        g = h // kv
        qg = q.reshape(b, T1, kv, g, dh)
        scale = dh ** -0.5
        kk = jnp.concatenate([k_cache, k], axis=1)  # (b, max_len+T1, kv, dh)
        vv = jnp.concatenate([v_cache, v], axis=1)
        cache_vis = jnp.arange(max_len)[None, :] < pos0[:, None]  # (b, M)
        anc_m = jnp.asarray(anc, bool)

        def _attend(qj, ancj):  # qj (b, kv, g, dh), ancj (T1,)
            lg = jnp.einsum("bkgd,bmkd->bkgm", qj, kk) * scale
            vis = jnp.concatenate(
                [cache_vis, jnp.broadcast_to(ancj[None], (b, T1))], axis=1)
            lg = jnp.where(vis[:, None, None, :], lg,
                           jnp.finfo(lg.dtype).min)
            pr = jax.nn.softmax(lg.astype(jnp.float32),
                                axis=-1).astype(cfg.dtype)
            return jnp.einsum("bkgm,bmkd->bkgd", pr, vv)

        out = jax.vmap(_attend, in_axes=(1, 0), out_axes=1)(qg, anc_m)
    x = x + _dense_nb(attn["wo"], out.reshape(b, T1, d), cfg.dtype)

    hN = _rms(p["rms_mlp"], x, cfg.rms_eps)
    gate = nn.silu(_dense_nb(p["gate"], hN, cfg.dtype))
    x = x + _dense_nb(p["down"],
                      gate * _dense_nb(p["up"], hN, cfg.dtype), cfg.dtype)
    return x, k, v


class Llama(nn.Module):
    """Decoder-only LM: ``(B, T) int tokens -> (B, T, vocab) fp32 logits``.

    ``train`` is accepted for Trainer compatibility (no dropout; train and
    eval paths are identical).  Untied output head (``lm_head``), per the
    LLaMA parameterization — the chunked-vocab-loss hook (GPT-2's
    ``return_hidden``) is intentionally absent here; use GPT-2 for the
    tied-head long-vocab path.
    """

    config: LlamaConfig

    @nn.compact
    def __call__(self, tokens: jnp.ndarray,
                 train: bool = False) -> jnp.ndarray:
        del train
        cfg = self.config
        b, t = tokens.shape
        positions = jnp.arange(t)
        if (cfg.attn_impl == "ring" and cfg.seq_axis is not None
                and _axis_is_bound(cfg.seq_axis)):
            # Sequence-sharded: this device holds one contiguous block;
            # RoPE must rotate by GLOBAL positions (same offset rule as
            # GPT-2's learned positions).
            from jax import lax

            positions = positions + lax.axis_index(cfg.seq_axis) * t
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                     name="wte")(tokens)
        for i in range(cfg.num_layers):
            x = LlamaBlock(cfg, name=f"h_{i}")(x, positions)
        x = nn.RMSNorm(epsilon=cfg.rms_eps, dtype=jnp.float32,
                       name="rms_f")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                          name="lm_head")(x.astype(cfg.dtype))
        return logits.astype(jnp.float32)

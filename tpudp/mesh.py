"""Device-mesh and multi-host bootstrap.

TPU-native replacement for the reference's process-group layer
(``src/Part 2a/main.py:148-153``: MASTER_ADDR/MASTER_PORT env vars +
``dist.init_process_group('gloo', rank, world_size)``).  In the SPMD world
there is no process group: a single :class:`jax.sharding.Mesh` spans every
device, collectives ride the ICI/DCN fabric, and multi-host rendezvous is
``jax.distributed.initialize`` whose coordinator address plays the role of
the reference's ``--master`` flag.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def initialize_distributed(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    port: int = 6585,
) -> None:
    """Multi-host rendezvous — the ``--master``/``--rank`` analogue.

    Maps the reference CLI (``src/Part 2a/main.py:158-165``: ``--master``,
    ``--num-nodes``, ``--rank``; hardcoded port 6585 at ``:172``) onto
    ``jax.distributed.initialize``.  On a single host (all arguments None)
    this is a no-op: one process already sees every local device.
    """
    if coordinator is None and num_processes in (None, 1):
        return
    _enable_cpu_cross_process_collectives()
    jax.distributed.initialize(
        coordinator_address=f"{coordinator}:{port}",
        num_processes=num_processes,
        process_id=process_id,
    )


def _enable_cpu_cross_process_collectives() -> None:
    """Multi-process runs on the CPU backend (the dry-run/soak rungs:
    N OS processes, each with virtual CPU devices) need a cross-process
    collectives implementation — the default ``'none'`` computes only
    intra-process and a 2-process psum silently reduces half the mesh.
    Select gloo unless a non-CPU platform was EXPLICITLY requested
    (those bring their own fabric): an unset platform on a CPU-only
    machine auto-selects the cpu backend, and skipping it there would
    leave the silent half-mesh psum in place.  The option only
    configures the CPU backend, so setting it under a TPU auto-select
    is inert.  Best-effort (older jax has no such option)."""
    import os

    platforms = str(getattr(jax.config, "jax_platforms", None)
                    or os.environ.get("JAX_PLATFORMS", "") or "")
    if platforms and "cpu" not in platforms:
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):  # option or backend absent
        pass


def make_mesh(num_devices: int | None = None, axis_name: str = DATA_AXIS) -> Mesh:
    """Build a 1-D data-parallel mesh over (the first ``num_devices``) devices.

    The mesh is the TPU-native "world": its size is the reference's
    ``world_size`` (``--num-nodes``), and the ``data`` axis is the axis all
    sync strategies reduce over.
    """
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices but only {len(devices)} available"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def axis_is_bound(axis_name: str | None) -> bool:
    """True when tracing inside shard_map/pmap with this named axis bound.
    Model init happens outside any mapped context — axis-aware layers (ring
    attention, MoE all_to_all) use this to fall back to their dense path so
    ``model.init`` works without a mesh (param shapes are identical)."""
    if axis_name is None:
        return False
    try:
        jax.lax.axis_index(axis_name)
        return True
    except NameError:
        return False


def make_mesh_nd(shape: dict[str, int], devices=None) -> Mesh:
    """Build an N-D mesh from ``{axis_name: size}`` (insertion-ordered).

    Multi-axis analogue of :func:`make_mesh` for the DPxTP / DPxSP / DPxPP
    rungs — e.g. ``make_mesh_nd({"data": 2, "model": 4})``.  Axis order
    matters on real hardware: put the fastest-communicating axis (tensor/
    sequence parallel) innermost so its collectives ride the shortest ICI
    links.
    """
    explicit = devices is not None
    if devices is None:
        devices = jax.devices()
    n = int(np.prod(list(shape.values())))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    if n < len(devices) and not explicit:
        import warnings

        warnings.warn(
            f"make_mesh_nd({shape}) uses {n} of {len(devices)} devices; the "
            f"other {len(devices) - n} idle. Pass devices= explicitly to "
            "silence.", stacklevel=2)
    grid = np.asarray(devices[:n]).reshape(tuple(shape.values()))
    return Mesh(grid, tuple(shape.keys()))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a global batch: split along the leading (batch) axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for replicated state (params, optimizer state)."""
    return NamedSharding(mesh, P())

"""The tpudp hazard rules — one per failure class this repo has paid for.

Each rule documents, in its ``summary``/docstring, the runtime failure
it front-runs; docs/ANALYSIS.md carries a before/after example per
rule.  Repo knowledge (which functions are scheduler hot paths, which
callables donate which arguments) lives in the config tables below so
the linter enforces the invariants even when a file stops advertising
them; fixture files opt in through markers instead
(``# tpudp: hot-path`` on the def line or the line above it,
``# tpudp: compile-once-module`` / ``# tpudp: collective-module`` in
the file's first lines).

Rules are pure stdlib — see tpudp/analysis/core.py.
"""

from __future__ import annotations

import ast
import re

from .core import Module, Rule, mentions, ordered_walk

# -- repo-aware configuration -----------------------------------------

#: Scheduler/step hot paths: host code on the per-token / per-step
#: critical path, where an unannounced host sync is a latency
#: regression (ROADMAP "kill the per-token host round-trip").  Keyed by
#: repo-relative path → def qualnames.
HOT_PATHS = {
    "tpudp/serve/engine.py": {
        "Engine.step", "Engine._run_prefill_chunk", "Engine._run_decode",
        "Engine._run_decode_fused", "Engine._run_verify",
        "Engine._run_spec_fused", "Engine._run_verify_tree",
        "Engine._gather_drafts", "Engine._gather_tree_drafts",
        "Engine._commit",
    },
    "tpudp/train.py": {
        "Trainer.train_epoch", "Trainer.evaluate",
    },
}

#: Dotted roots that are device-resident state inside hot paths (taint
#: seeds for the host-sync rule), per file.
DEVICE_ROOTS = {
    "tpudp/serve/engine.py": {"self._keys"},
    "tpudp/train.py": {"self.state"},
}

#: Calls whose results are device values (taint seeds): the engine/
#: trainer step-program attributes plus anything reached through the
#: fault-seam wrapper ``self._device(kind, fn, *args)``.
DEVICE_CALL_ATTRS = {
    "_device", "train_step", "eval_step", "fwd_step", "decode_step",
    "verify_step", "prefill_step", "fused_step", "decode_paged",
    "verify_paged", "prefill_paged", "fused_paged", "fused_spec_step",
    "fused_spec_paged", "tree_step", "tree_paged", "copy_block_in",
    "copy_block_out", "_sample_row",
}

#: Known donating callables (attribute or bare name) → donated
#: positional indices.  Mirrors the ``donate_argnums`` at their build
#: sites; locally-defined jit functions are additionally discovered
#: from their own decorators.  The second index on the serve step
#: programs is the OBS_DEVICE_COUNTERS accumulator (tpudp.obs) — tiny,
#: but donated like the arena, so a read of the stale counters buffer
#: after a step is the same class of bug as a stale-cache read.
DONATING = {
    "decode_step": (0, 8), "verify_step": (0, 9), "prefill_step": (0,),
    "fused_step": (0, 11), "train_step": (0,), "copy_block_in": (0,),
    "copy_block_out": (1,),
    # Paged twins (Engine(kv_pages=N)): the shared page pool donates in
    # the dense arena's place (the block table never does — it is
    # host-authoritative and uploaded per call).
    "decode_paged": (0, 9), "verify_paged": (0, 10),
    "prefill_paged": (0,), "fused_paged": (0, 12),
    # On-device speculation (ISSUE 16): the fused speculative window and
    # the tree-verify window donate the target arena/pool + the obs
    # counters; the draft model's KV is carry-local scratch, never an
    # argument, so it has no donation row.
    "fused_spec_step": (0, 12), "fused_spec_paged": (0, 13),
    "tree_step": (0, 9), "tree_paged": (0, 10),
}

#: Pass-through wrappers: ``self._device("kind", fn, *args)`` runs
#: ``fn(*args)`` — the donating callee sits at arg 1, its args start
#: at 2.
DEVICE_WRAPPERS = {"_device": (1, 2)}

#: Modules whose jitted programs must bump TRACE_COUNTS (the serve
#: compile-once discipline); fixtures opt in with
#: ``# tpudp: compile-once-module``.
COMPILE_ONCE_PREFIXES = ("tpudp/serve/",)

#: Modules whose Pallas kernels must belong to a pinned trace-audit
#: program family: every ``pl.pallas_call`` site must sit inside a
#: program that bumps TRACE_COUNTS itself, or inside a wrapper marked
#: ``# tpudp: kernel-program(<name>)`` where <name> is a registered
#: program (tpudp/analysis/programs.py TRACE_COUNTER_PROGRAMS values).
#: The training-side flash/ring kernels are deliberately OUT of scope —
#: they sit behind explicit attn_impl opt-ins, not the serving hot
#: path's default dispatch.  Fixtures opt in with
#: ``# tpudp: kernel-module``.
KERNEL_SCOPE_PREFIXES = ("tpudp/serve/", "tpudp/ops/paged_attention.py")

KERNEL_PROGRAM_RE = re.compile(r"#\s*tpudp:\s*kernel-program\(([\w.\-]+)\)")

#: Modules where host-side ordering feeds collectives/checkpoint
#: protocols, so unordered filesystem listings are a cross-host
#: divergence hazard; fixtures opt in with ``# tpudp: collective-module``.
COLLECTIVE_MODULE_PREFIXES = (
    "tpudp/parallel/", "tpudp/resilience.py", "tpudp/mesh.py",
    "tpudp/utils/consistency.py", "tpudp/utils/checkpoint.py",
)

#: lax collectives (post-alias-resolution dotted names).
COLLECTIVE_CALLS = {
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
    "jax.lax.ppermute", "jax.lax.pshuffle", "jax.lax.all_gather",
    "jax.lax.all_to_all", "jax.lax.psum_scatter",
}

#: Repo/runtime cross-process protocol helpers — every host must reach
#: these together (matched by terminal name).
COLLECTIVE_HELPERS = {
    "gather_host_values", "gather_host_blobs", "all_hosts_ok",
    "coordinated_any",
    "commit_after_all_hosts", "broadcast_one_to_all",
    "verify_across_processes", "process_allgather",
    "sync_global_devices", "_vote", "_coordinated_recover",
}

#: Nondeterministic-at-trace-time call prefixes (resolved roots).
NONDET_PREFIXES = (
    "time.", "numpy.random.", "random.", "datetime.", "uuid.",
    "secrets.", "os.urandom", "os.getpid", "os.times",
)

#: Per-host-divergent condition sources for the collective rule.
DIVERGENT_PREFIXES = (
    "os.", "time.", "glob.", "random.", "numpy.random.", "socket.",
    "shutil.", "tempfile.", "pathlib.",
)
DIVERGENT_ATTRS = {"process_index", "exists", "isfile", "isdir",
                   "listdir", "errno", "pid", "getmtime", "stat"}
DIVERGENT_BUILTINS = {"open", "input"}

#: Host-sync call spellings.
SYNC_FUNCS = {"float", "int", "bool", "complex"}
SYNC_DOTTED = {"numpy.asarray", "numpy.array", "jax.device_get"}
SYNC_METHODS = {"item", "tolist", "block_until_ready"}

#: tpudp.obs recorder API split (the obs-in-hot-path rule): the
#: ALLOCATION-FREE calls sanctioned on designated hot paths vs the
#: convenient allocating forms that belong off them.
OBS_FAST_METHODS = {"begin", "end", "count"}
OBS_ALLOC_METHODS = {"span", "event"}


def _hot_functions(mod: Module):
    """Defs designated as scheduler/step hot paths in this module —
    via the repo table or a ``# tpudp: hot-path`` marker on/above the
    def line."""
    table = set()
    for path, quals in HOT_PATHS.items():
        if mod.rel.endswith(path):
            table = quals
            break
    for fn, qual in mod.functions.items():
        if qual in table:
            yield fn
            continue
        start = fn.lineno
        if fn.decorator_list:
            start = fn.decorator_list[0].lineno
        if any("tpudp: hot-path" in mod.comments.get(line, "")
               for line in range(max(1, start - 1), fn.lineno + 1)):
            yield fn


def _in_scope(mod: Module, prefixes, marker: str) -> bool:
    if marker in mod.markers:
        return True
    return any(mod.rel.endswith(p) if p.endswith(".py")
               else p in mod.rel for p in prefixes)


def _assign_targets(node):
    """Raw dotted target paths of an Assign/AugAssign/For/With."""
    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    flat = []
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            flat.extend(t.elts)
        else:
            flat.append(t)
    return flat


class TraceNondeterminism(Rule):
    """Wall clocks, host RNGs, and process identity inside traced code.

    A value drawn from ``time.*``/``np.random``/``random`` during
    tracing is frozen into the jaxpr as a constant: the program is no
    longer a function of its inputs, replays differently across
    processes (host-divergent constants feed host-divergent collectives
    on a pod), and defeats bit-exact trajectory replay.  Use
    ``jax.random`` with explicit keys, or compute the value on the host
    and pass it as an argument.
    """

    name = "trace-nondeterminism"
    summary = ("host clock/RNG/process-identity call inside traced code "
               "— becomes a trace-time constant")

    def check(self, mod: Module):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if mod.traced_kind(node) is None:
                continue
            dotted = mod.dotted(node.func)
            if dotted is None:
                continue
            if dotted.startswith("jax."):
                continue
            if any(dotted == p.rstrip(".") or dotted.startswith(p)
                   for p in NONDET_PREFIXES):
                yield self.finding(
                    mod, node,
                    f"{dotted}() inside traced code freezes a "
                    f"host-nondeterministic value into the jaxpr; pass it "
                    f"in as an argument or use jax.random")


class UnorderedIteration(Rule):
    """Unordered iteration feeding trace constants or host protocols.

    Iterating a ``set`` during tracing bakes an interpreter-dependent
    order into the program (PYTHONHASHSEED changes it run to run), so
    two hosts can trace different programs from identical sources —
    the exact recompile/collective-mismatch class PR 7's vote protocol
    exists to survive.  In coordination modules the same applies to
    unsorted ``os.listdir`` results feeding checkpoint walks.
    """

    name = "unordered-iteration"
    summary = ("iteration order is interpreter-dependent (set iteration "
               "in traced code / unsorted os.listdir in a coordination "
               "module)")

    def _is_set_expr(self, mod, node):
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            return mod.dotted(node.func) in ("set", "frozenset")
        return False

    def check(self, mod: Module):
        for node in ast.walk(mod.tree):
            iters = []
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters = [node.iter]
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp, ast.DictComp)):
                iters = [g.iter for g in node.generators]
            for it in iters:
                if (self._is_set_expr(mod, it)
                        and mod.traced_kind(node) is not None):
                    yield self.finding(
                        mod, it,
                        "set iteration order is interpreter-dependent; "
                        "inside traced code it bakes a per-process order "
                        "into the program — sort it first")
        if _in_scope(mod, COLLECTIVE_MODULE_PREFIXES, "collective-module"):
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Call)
                        and mod.dotted(node.func) == "os.listdir"):
                    # any enclosing sorted() within the statement
                    # normalizes the order (incl. comprehensions fed to
                    # sorted)
                    cur, in_sorted = mod.parents.get(node), False
                    while cur is not None and not isinstance(cur, ast.stmt):
                        if (isinstance(cur, ast.Call)
                                and mod.dotted(cur.func) == "sorted"):
                            in_sorted = True
                            break
                        cur = mod.parents.get(cur)
                    if in_sorted:
                        continue
                    yield self.finding(
                        mod, node,
                        "os.listdir order is filesystem-dependent; in a "
                        "cross-host coordination module wrap it in "
                        "sorted() so every host walks the same order")


class TracedBranch(Rule):
    """Python ``if``/``while`` on a traced value.

    Inside a jitted function, ``if x > 0:`` forces ``x`` concrete at
    trace time: either it raises ``ConcretizationTypeError``, or — when
    the branch input happens to be weakly typed — it silently
    specializes the program to one branch and recompiles when the value
    flips shape-class.  Branch with ``lax.cond``/``jnp.where``, or mark
    the argument static.
    """

    name = "traced-branch"
    summary = ("Python control flow on a traced value — trace error or "
               "silent per-value specialization/recompile")

    def check(self, mod: Module):
        for fn in mod.functions:
            params = mod.traced_params(fn)
            if not params:
                continue
            tainted = set(params)
            for node in ordered_walk(fn):
                if isinstance(node, ast.Assign):
                    hit = mentions(mod, node.value, tainted)
                    for t in _assign_targets(node):
                        dotted = mod.raw_dotted(t)
                        if dotted is None:
                            continue
                        if hit:
                            tainted.add(dotted)
                        else:
                            tainted.discard(dotted)
                elif isinstance(node, (ast.If, ast.While)):
                    if mentions(mod, node.test, tainted):
                        kind = ("while" if isinstance(node, ast.While)
                                else "if")
                        yield self.finding(
                            mod, node,
                            f"Python `{kind}` on a traced value in "
                            f"jitted `{fn.name}` — use lax.cond/"
                            f"jnp.where or a static argument")


class HostSync(Rule):
    """Device→host synchronization where it stalls the pipeline.

    Two scopes.  (1) Traced code: ``float()``/``np.asarray()``/
    ``.item()`` on a traced value fails at trace time — flagged here so
    review catches it before the first trace.  (2) Designated
    scheduler/step hot paths: each sync is a full round trip per call
    under async dispatch; every *intentional* one (the window-edge
    loss fetch, the per-token commit) must carry a visible
    ``lint-ok(host-sync)`` so new ones can't slip in as a diff nobody
    notices (the on-device decode loop exists to delete the suppressed
    ones).
    """

    name = "host-sync"
    summary = ("device→host sync (.item()/float()/np.asarray/"
               "device_get) in traced code or a scheduler hot path")

    def _sync_call(self, mod, node, tainted):
        """(description, node) when ``node`` is a sync op on a tainted
        value."""
        if not isinstance(node, ast.Call):
            return None
        dotted = mod.dotted(node.func)
        if dotted in SYNC_DOTTED and node.args:
            if dotted == "jax.device_get" or mentions(
                    mod, node.args[0], tainted):
                return dotted
        if (isinstance(node.func, ast.Name)
                and node.func.id in SYNC_FUNCS and node.args
                and mentions(mod, node.args[0], tainted)):
            return f"{node.func.id}()"
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_METHODS
                and mentions(mod, node.func.value, tainted)):
            return f".{node.func.attr}()"
        return None

    def _scan(self, mod, fn, tainted, where):
        reported: set[int] = set()

        def assign_one(target, value):
            dotted = mod.raw_dotted(target)
            # flag every sync nested ANYWHERE in the value with the
            # PRE-assignment taint — `x = max(float(x), 1.0)` must fire
            # even though the assignment itself untaints `x` (the later
            # generic Call visit would see the already-cleared taint)
            desc = None
            for sub in ast.walk(value):
                if not isinstance(sub, ast.Call) or id(sub) in reported:
                    continue
                sub_desc = self._sync_call(mod, sub, tainted)
                if sub_desc is not None:
                    reported.add(id(sub))
                    out.append(self.finding(
                        mod, sub,
                        f"{sub_desc} forces a device→host sync {where}"))
                    if sub is value:
                        desc = sub_desc
            if dotted is None:
                return
            if desc is not None:
                # the sync itself was flagged; its result is a host
                # value — don't re-flag downstream reads
                tainted.discard(dotted)
            elif (mentions(mod, value, tainted)
                    or self._device_value(mod, value)):
                tainted.add(dotted)
            else:
                tainted.discard(dotted)

        out: list = []
        for node in ordered_walk(fn):
            if isinstance(node, ast.Assign):
                targets = _assign_targets(node)
                # pairwise tuple semantics: `a, b = float(x), y` syncs
                # into `a` only
                if (len(node.targets) == 1
                        and isinstance(node.targets[0], (ast.Tuple, ast.List))
                        and isinstance(node.value, (ast.Tuple, ast.List))
                        and len(targets) == len(node.value.elts)):
                    for t, v in zip(targets, node.value.elts):
                        assign_one(t, v)
                else:
                    for t in targets:
                        assign_one(t, node.value)
            elif isinstance(node, ast.Call) and id(node) not in reported:
                desc = self._sync_call(mod, node, tainted)
                if desc is not None:
                    reported.add(id(node))
                    out.append(self.finding(
                        mod, node,
                        f"{desc} forces a device→host sync {where}"))
        yield from out

    def _device_value(self, mod, node) -> bool:
        """Calls that mint device values (hot-path taint seeds)."""
        if not isinstance(node, ast.Call):
            return any(self._device_value(mod, c)
                       for c in ast.iter_child_nodes(node))
        dotted = mod.dotted(node.func)
        if dotted and (dotted.startswith("jax.numpy.")
                       or dotted.startswith("jax.random.")):
            return True
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in DEVICE_CALL_ATTRS):
            return True
        if (isinstance(node.func, ast.Name)
                and node.func.id in DEVICE_CALL_ATTRS):
            return True
        return False

    def check(self, mod: Module):
        hot = set(_hot_functions(mod))
        for fn in mod.functions:
            if fn in hot:
                roots = set()
                for path, seeds in DEVICE_ROOTS.items():
                    if mod.rel.endswith(path):
                        roots = set(seeds)
                yield from self._scan(
                    mod, fn, roots,
                    f"on the `{fn.name}` hot path — one round trip per "
                    f"call under async dispatch")
            else:
                params = mod.traced_params(fn)
                if params:
                    yield from self._scan(
                        mod, fn, set(params),
                        f"inside traced `{fn.name}` — this fails at "
                        f"trace time")


class UseAfterDonation(Rule):
    """Reading a buffer after passing it to a donating program.

    ``donate_argnums`` hands the buffer to XLA to overwrite in place;
    the Python reference left behind points at deleted memory, and
    touching it raises ``RuntimeError: Array has been deleted`` — but
    only on backends that actually alias (TPU), so CPU tests pass while
    the pod run crashes.  Rebind the result before the next read, and
    refresh the variable inside loops.
    """

    name = "use-after-donation"
    summary = ("buffer read after being donated to a jitted program "
               "(donate_argnums) — deleted on aliasing backends")

    def _donating_targets(self, mod: Module):
        """name → donated indices for defs in this module with
        donate_argnums decorators."""
        local = {}
        for fn in mod.functions:
            _, _, donated = mod._jit_decorator_info(fn)
            if donated:
                local[fn.name] = donated
        return local

    def _call_donations(self, mod, node, local):
        """Yield (donated_arg_expr, label) for a donating call."""
        func = node.func
        name = None
        if isinstance(func, ast.Attribute):
            name = func.attr
        elif isinstance(func, ast.Name):
            name = func.id
        if name in DEVICE_WRAPPERS and len(node.args) >= 2:
            fn_pos, arg_start = DEVICE_WRAPPERS[name]
            inner = node.args[fn_pos]
            iname = None
            if isinstance(inner, ast.Attribute):
                iname = inner.attr
            elif isinstance(inner, ast.Name):
                iname = inner.id
            donated = local.get(iname, DONATING.get(iname))
            if donated:
                for idx in donated:
                    pos = arg_start + idx
                    if pos < len(node.args):
                        yield node.args[pos], iname
            return
        donated = local.get(name, DONATING.get(name)) if name else None
        if donated:
            for idx in donated:
                if idx < len(node.args):
                    yield node.args[idx], name

    def check(self, mod: Module):
        local = self._donating_targets(mod)
        for fn in mod.functions:
            if mod.traced_kind(fn) in ("root", "combinator", "nested"):
                continue  # inside a trace, "donation" is the caller's jit
            # positions of loads/stores of every dotted path in fn
            events = []  # (line, col, kind, dotted)
            for node in ast.walk(fn):
                dotted = mod.raw_dotted(node)
                if dotted is None or not isinstance(
                        node, (ast.Name, ast.Attribute)):
                    continue
                parent = mod.parents.get(node)
                if isinstance(parent, ast.Attribute):
                    continue  # only record the full chain once
                kind = ("store" if isinstance(
                    node.ctx, (ast.Store, ast.Del)) else "load")
                events.append((node.lineno, node.col_offset, kind,
                               dotted, node))
            events.sort(key=lambda e: (e[0], e[1]))

            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                for arg, label in self._call_donations(mod, node, local):
                    path = mod.raw_dotted(arg)
                    if path is None:
                        continue
                    stmt = node
                    while (mod.parents.get(stmt) is not None
                           and not isinstance(stmt, ast.stmt)):
                        stmt = mod.parents[stmt]
                    # same-statement rebind (x = f(x)) is the idiom
                    rebound = isinstance(stmt, ast.Assign) and any(
                        mod.raw_dotted(t) == path
                        or (mod.raw_dotted(t) is not None
                            and path.startswith(mod.raw_dotted(t) + "."))
                        for t in _assign_targets(stmt))
                    end = getattr(stmt, "end_lineno", stmt.lineno)
                    if not rebound:
                        for line, col, kind, dotted, enode in events:
                            if line <= end:
                                continue
                            related = (dotted == path
                                       or dotted.startswith(path + ".")
                                       or path.startswith(dotted + "."))
                            if not related:
                                continue
                            if kind == "store":
                                break
                            yield self.finding(
                                mod, enode,
                                f"`{dotted}` read after being donated to "
                                f"`{label}` at line {node.lineno} — "
                                f"deleted on aliasing backends; rebind "
                                f"the program's result first")
                            break
                    # loop-carried donation: the next iteration passes a
                    # deleted buffer unless the path is rebound in-loop
                    cur = mod.parents.get(node)
                    loop = None
                    while cur is not None and cur is not fn:
                        if isinstance(cur, (ast.For, ast.While)):
                            loop = cur
                            break
                        cur = mod.parents.get(cur)
                    if loop is not None:
                        stored = any(
                            e[2] == "store" and (
                                e[3] == path
                                or path.startswith(e[3] + "."))
                            for e in events
                            if loop.lineno <= e[0]
                            <= getattr(loop, "end_lineno", loop.lineno))
                        if not stored:
                            yield self.finding(
                                mod, node,
                                f"`{path}` is donated to `{label}` inside "
                                f"a loop but never rebound in the loop "
                                f"body — the second iteration passes a "
                                f"deleted buffer")


class DivergentCollective(Rule):
    """Collectives issued under per-host-divergent control flow.

    A collective is a rendezvous: every participating host must issue
    the same sequence.  One guarded by ``if jax.process_index() == 0``,
    an ``except`` handler, or a filesystem/clock condition can be
    entered by some hosts and skipped by others — on a pod that is a
    deadlock (multi-minute stall, then a watchdog kill), not an
    exception.  Route per-host outcomes through the vote protocol
    (every host reaches the gather; the *decision* is collective) and
    suppress here with the justification.
    """

    name = "divergent-collective"
    summary = ("collective/cross-process call under per-host-divergent "
               "control flow (except handler, process_index/filesystem/"
               "clock condition) — pod deadlock")

    def _divergent_expr(self, mod, node, tainted) -> bool:
        if isinstance(node, ast.Call):
            if self._is_collective(mod, node):
                # the RESULT of a vote/collective is host-uniform by
                # construction — branching on it is the sanctioned
                # pattern, whatever per-host facts fed the vote
                return False
            dotted = mod.dotted(node.func)
            if dotted:
                if any(dotted.startswith(p) for p in DIVERGENT_PREFIXES):
                    return True
                if dotted in DIVERGENT_BUILTINS:
                    return True
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in DIVERGENT_ATTRS):
                return True
        elif isinstance(node, (ast.Name, ast.Attribute)):
            dotted = mod.raw_dotted(node)
            if dotted and dotted in tainted:
                return True
            if (isinstance(node, ast.Attribute)
                    and node.attr in DIVERGENT_ATTRS):
                return True
        return any(self._divergent_expr(mod, c, tainted)
                   for c in ast.iter_child_nodes(node))

    def _is_collective(self, mod, node) -> bool:
        dotted = mod.dotted(node.func)
        if dotted in COLLECTIVE_CALLS:
            return True
        if dotted and dotted.startswith("jax.experimental.multihost_utils."):
            return True
        name = None
        if isinstance(node.func, ast.Attribute):
            name = node.func.attr
        elif isinstance(node.func, ast.Name):
            name = node.func.id
        return name in COLLECTIVE_HELPERS

    def _under_divergent_control(self, mod, node, fn, tainted) -> bool:
        """Is this statement lexically inside an except handler or a
        branch gated on a divergent condition?  An assignment there is
        control-dependent on per-host state even when its RHS is a
        constant (`flag = True` under `if os.path.exists(...)`)."""
        cur = mod.parents.get(node)
        while cur is not None and cur is not fn:
            if isinstance(cur, ast.ExceptHandler):
                return True
            if isinstance(cur, (ast.If, ast.While)) and \
                    self._divergent_expr(mod, cur.test, tainted):
                return True
            cur = mod.parents.get(cur)
        return False

    def check(self, mod: Module):
        # taint pass per function: names assigned from divergent
        # sources — by DATA flow (divergent RHS) or by CONTROL flow
        # (any assignment under a divergent branch).  Iterated to a
        # fixpoint so `a = os.*; if a: b = True; if b: collective()`
        # chains resolve.
        for fn in mod.functions:
            tainted: set[str] = set()
            changed = True
            while changed:
                changed = False
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Assign):
                        continue
                    if (self._divergent_expr(mod, node.value, tainted)
                            or self._under_divergent_control(
                                mod, node, fn, tainted)):
                        for t in _assign_targets(node):
                            dotted = mod.raw_dotted(t)
                            if dotted is not None and dotted not in tainted:
                                tainted.add(dotted)
                                changed = True
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and self._is_collective(mod, node)):
                    continue
                cur = mod.parents.get(node)
                prev = node
                while cur is not None and cur is not fn:
                    if isinstance(cur, ast.ExceptHandler):
                        yield self.finding(
                            mod, node,
                            "collective issued inside an except handler — "
                            "exception occurrence is per-host, so peers "
                            "that didn't fault never reach the rendezvous "
                            "(vote at an unconditional decision point "
                            "instead)")
                        break
                    if isinstance(cur, (ast.If, ast.While)):
                        # the `test` itself runs unconditionally
                        in_test = any(prev is c or prev in ast.walk(c)
                                      for c in [cur.test])
                        if not in_test and self._divergent_expr(
                                mod, cur.test, tainted):
                            yield self.finding(
                                mod, node,
                                "collective under a per-host-divergent "
                                "condition — hosts taking different "
                                "branches deadlock the rendezvous")
                            break
                    prev = cur
                    cur = mod.parents.get(cur)


def _bumps_trace_counts(fn) -> bool:
    """Does this def's body contain ``TRACE_COUNTS[...] += 1``?"""
    for node in ast.walk(fn):
        if (isinstance(node, ast.AugAssign)
                and isinstance(node.target, ast.Subscript)
                and isinstance(node.target.value, ast.Name)
                and node.target.value.id == "TRACE_COUNTS"):
            return True
    return False


class UnregisteredJit(Rule):
    """Jitted programs in compile-once modules must be observable.

    The serve layer's compile-once invariant is enforced by tests that
    watch ``TRACE_COUNTS``; a jitted program that doesn't bump a
    counter is invisible to them, so a recompile regression in it
    ships silently.  Every jit in scope bumps
    ``TRACE_COUNTS[<name>]`` as the first traced side effect and is
    then eligible for the trace-stability audit registry
    (tpudp/analysis/programs.py).
    """

    name = "unregistered-jit"
    summary = ("jitted program in a compile-once module does not bump "
               "TRACE_COUNTS — recompiles in it are unobservable")

    def _bumps_trace_counts(self, fn) -> bool:
        return _bumps_trace_counts(fn)

    def check(self, mod: Module):
        if not _in_scope(mod, COMPILE_ONCE_PREFIXES, "compile-once-module"):
            return
        for fn in mod.functions:
            rooted, _, _ = mod._jit_decorator_info(fn)
            if rooted and not self._bumps_trace_counts(fn):
                yield self.finding(
                    mod, fn,
                    f"jitted `{fn.name}` never bumps TRACE_COUNTS — its "
                    f"recompiles are invisible to the compile-once tests; "
                    f"add TRACE_COUNTS[\"{fn.name}\"] += 1 in the traced "
                    f"body and register it for the trace audit")
        # call-form jits too: `fast = jax.jit(body)` / partial(jax.jit)
        # — same invisibility, different spelling
        by_name: dict[str, list] = {}
        for fn in mod.functions:
            by_name.setdefault(fn.name, []).append(fn)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            dotted = mod.dotted(call.func)
            inner = None
            if dotted in ("jax.jit", "jax.pjit") and call.args:
                inner = call.args[0]
            elif (dotted in ("functools.partial", "partial") and call.args
                    and mod.dotted(call.args[0]) in ("jax.jit", "jax.pjit")
                    and len(call.args) > 1):
                inner = call.args[1]
            if not isinstance(inner, ast.Name):
                continue
            for fn in by_name.get(inner.id, ()):
                if not self._bumps_trace_counts(fn):
                    yield self.finding(
                        mod, node,
                        f"call-form jit of `{fn.name}` never bumps "
                        f"TRACE_COUNTS — its recompiles are invisible to "
                        f"the compile-once tests; add "
                        f"TRACE_COUNTS[\"{fn.name}\"] += 1 in the traced "
                        f"body and register it for the trace audit")


class UnregisteredKernel(Rule):
    """Pallas kernels outside the pinned program registry.

    Every hand-written kernel on the serving hot path is pinned in the
    trace-audit registry (tpudp/analysis/programs.py) through the
    program that dispatches it: the program bumps its TRACE_COUNTS key,
    the key maps to a registered program name, and the lockfile carries
    the kernel body's fingerprint.  A ``pl.pallas_call`` reachable from
    code that is neither inside a counter-bumping program nor inside a
    wrapper marked ``# tpudp: kernel-program(<registered name>)`` is a
    kernel whose body can change without any named, reviewed lockfile
    event — exactly the silent-regression class the audit exists to
    close (mirrors ``unregistered-jit``, one layer down).
    """

    name = "unregistered-kernel"
    summary = ("pl.pallas_call site not tied to a registered trace-audit "
               "program — kernel-body changes would dodge the lock")

    def _program_marker(self, mod: Module, fn) -> str | None:
        """``# tpudp: kernel-program(NAME)`` on the def line or the
        line above it (the hot-path marker placement)."""
        start = fn.lineno
        if fn.decorator_list:
            start = fn.decorator_list[0].lineno
        for line in range(max(1, start - 1), fn.lineno + 1):
            m = KERNEL_PROGRAM_RE.search(mod.comments.get(line, ""))
            if m:
                return m.group(1)
        return None

    def check(self, mod: Module):
        if not _in_scope(mod, KERNEL_SCOPE_PREFIXES, "kernel-module"):
            return
        # Stdlib-safe: programs.py's module level is pure tables (the
        # heavy imports live inside its builders).
        from .programs import TRACE_COUNTER_PROGRAMS
        registered = set(TRACE_COUNTER_PROGRAMS.values())
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.dotted(node.func) or ""
            if dotted.split(".")[-1] != "pallas_call":
                continue
            marker, covered = None, False
            fn = mod.enclosing_function(node)
            while fn is not None:
                if _bumps_trace_counts(fn):
                    covered = True  # inside a counted (hence registered
                    break           # or registry-test-caught) program
                if marker is None:
                    marker = self._program_marker(mod, fn)
                fn = mod.enclosing_function(fn)
            if covered or marker in registered:
                continue
            if marker is None:
                yield self.finding(
                    mod, node,
                    "pl.pallas_call site belongs to no registered "
                    "program — dispatch it from a TRACE_COUNTS-bumping "
                    "program, or mark its wrapper `# tpudp: "
                    "kernel-program(<name>)` with a name from "
                    "TRACE_COUNTER_PROGRAMS")
            else:
                yield self.finding(
                    mod, node,
                    f"kernel-program({marker}) names no registered "
                    f"program — register it in tpudp/analysis/"
                    f"programs.py (TRACE_COUNTER_PROGRAMS + "
                    f"build_programs) so the kernel body is pinned")


class ObsInHotPath(Rule):
    """Allocating telemetry calls on designated scheduler hot paths.

    Instrumentation must pass the same bar as the code it observes:
    ``tpudp.obs``'s ``span(...)``/``event(...)`` build dicts and context
    managers per call — fine at request admission or a recovery
    decision, a per-token allocation regression inside
    ``Engine.step``/``_run_decode``/``Trainer.train_epoch``.  The
    recorder's allocation-free ``begin``/``end``/``count`` API exists
    precisely for those paths (tpudp/obs/record.py documents the
    contract), so on a hot path ONLY that API is allowed — the same
    "every exception is visible in the diff" discipline as the
    host-sync rule's suppressions.
    """

    name = "obs-in-hot-path"
    summary = ("allocating obs recorder call (.span()/.event()) on a "
               "designated hot path — use the allocation-free "
               "begin()/end()/count() API")

    def check(self, mod: Module):
        for fn in _hot_functions(mod):
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in OBS_ALLOC_METHODS):
                    continue
                yield self.finding(
                    mod, node,
                    f".{node.func.attr}() allocates per call on the "
                    f"`{fn.name}` hot path — record through the "
                    f"allocation-free begin()/end()/count() API (or move "
                    f"the event off the hot path)")


RULES = [
    TraceNondeterminism(),
    UnorderedIteration(),
    TracedBranch(),
    HostSync(),
    UseAfterDonation(),
    DivergentCollective(),
    UnregisteredJit(),
    UnregisteredKernel(),
    ObsInHotPath(),
]

RULES_BY_NAME = {r.name: r for r in RULES}

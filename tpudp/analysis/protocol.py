"""The cross-host protocol verifier: statically prove host-uniform
collective sequences through the multihost modules.

The linter's ``divergent-collective`` rule (PR 8) is *lexical*: it
flags a collective spelled inside an ``except`` handler or under a
condition tainted by per-host state.  The bug class that survived it —
PR 7's review caught a per-host listing probe deciding entry into a
collective restore, by hand — is *path-shaped*: the probe lives in one
function, the collective in another, and the hazard is that two hosts
take different execution paths whose collective *sequences* differ.
This module closes that gap with three ingredients:

  * an **interprocedural call graph** over the multihost modules
    (:data:`PROTOCOL_MODULES`), summarizing per function whether it
    transitively issues a rendezvous (``has_collectives``) and whether
    its return value is a per-host fact (``host_local_return`` — e.g.
    ``latest_step_dir`` returns a filesystem listing, through two
    levels of helpers);
  * **bounded path enumeration** per function
    (:mod:`tpudp.analysis.cfg`): every acyclic path records its ordered
    collective sites and the branch decisions that led there, and at
    every branch whose predicate is *host-local* the verifier compares
    the collective sequences of the arms — they must be identical,
    because hosts may take different arms;
  * a **bounded model checker** for the vote/park state machine
    (:class:`VoteSpec` / :func:`explore_vote_machine`): exhaustive
    interleavings of N hosts with fault, crash, and timeout
    transitions, proving the agreed-action protocol deadlock-free
    within bounds — and catching a spec that drops the
    completion-vote park (a clean finisher leaving a late faulter
    without a vote partner).

Host-uniform predicates — branch conditions every host computes
identically — are never compared: vote/allgather results
(``all_hosts_ok``, ``coordinated_any``, ``gather_host_values``, ...),
``jax.process_count()``, static config, function arguments, constants.
Host-LOCAL predicates are filesystem probes, clocks, RNG,
``jax.process_index()``, exception occurrence, and anything data-flow
tainted by those (interprocedurally, through helper summaries).

Findings anchor at a concrete collective site (or the early
``return``/``raise``) so the standard ``# tpudp: lint-ok(rule)``
suppressions apply; a suppression naming a protocol rule that matches
nothing is reported by THIS pass (the lint pass defers those names
here), so stale protocol exemptions cannot linger.

Pure stdlib, importable from the watcher poll path like the linter.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from collections import deque

from .cfg import MAX_PATHS as _MAX_PATHS
from .cfg import MAX_SEQ as _MAX_SEQ
from .cfg import PathEnumerator
from .core import (PROTOCOL_MODULES, PROTOCOL_RULE_NAMES, Finding,
                   Module, iter_python_files)
from .rules import COLLECTIVE_CALLS, COLLECTIVE_HELPERS

#: The default verification scope lives in core.PROTOCOL_MODULES
#: (lint needs it to decide which files' protocol-rule suppressions to
#: defer here); fixture files opt in with a ``# tpudp:
#: protocol-module`` marker in their first lines.  Re-exported for
#: callers.

#: Calls whose RESULT is host-uniform by construction, whatever
#: per-host facts fed them — the sanctioned way to turn a local fact
#: into a collective decision.  Classification stops descending here.
UNIFORM_RESULT_CALLS = {
    "all_hosts_ok", "coordinated_any", "gather_host_values",
    "gather_host_blobs",
    "broadcast_one_to_all", "process_allgather", "reduce_outcomes",
    "_vote", "_coordinated_recover", "_coverage_union_uncovered",
    "restore_emergency_voted", "restore_latest_verified",
    "verify_across_processes", "sync_global_devices",
    "commit_after_all_hosts",
}
UNIFORM_RESULT_DOTTED = {"jax.process_count"}

#: Host-local sources: calls/attribute probes whose value differs per
#: host.  (`os.path.join` and friends are pure — only the probing
#: subset of `os` is listed.)
HOST_LOCAL_DOTTED = {
    "os.listdir", "os.scandir", "os.walk", "os.stat", "os.getpid",
    "os.urandom", "os.times", "open", "input", "jax.process_index",
}
HOST_LOCAL_PREFIXES = ("time.", "random.", "numpy.random.", "socket.",
                       "uuid.", "secrets.", "glob.", "tempfile.")
HOST_LOCAL_ATTRS = {"process_index", "exists", "isfile", "isdir",
                    "listdir", "scandir", "getmtime", "stat", "glob",
                    "iglob", "walk"}


def _terminal_name(func: ast.AST) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


@dataclasses.dataclass
class FnInfo:
    """Interprocedural summary for one function def."""

    mod: Module
    fn: ast.AST
    qual: str
    has_collectives: bool = False
    host_local_return: str | None = None  # reason, or None
    taint: dict | None = None  # cached AFTER the summary fixpoint


class ModuleSet:
    """The analyzed modules plus the cross-module function index and
    fixpoint summaries."""

    def __init__(self, modules: list[Module]):
        self.modules = modules
        self.infos: dict[int, FnInfo] = {}
        self.by_name: dict[str, list[FnInfo]] = {}
        self.by_qual: dict[tuple[str, str], FnInfo] = {}
        self._summaries_final = False
        for mod in modules:
            for fn, qual in mod.functions.items():
                info = FnInfo(mod, fn, qual)
                self.infos[id(fn)] = info
                self.by_name.setdefault(fn.name, []).append(info)
                self.by_qual[(mod.rel, qual)] = info
        self._summarize()
        self._summaries_final = True

    # -- call resolution ------------------------------------------------

    def resolve(self, mod: Module, caller_qual: str,
                call: ast.Call) -> list[FnInfo]:
        """Candidate callee summaries for a call.  ``self.m()`` resolves
        within the caller's class; a bare name prefers same-module defs;
        an attribute call on an arbitrary object resolves by terminal
        name only when unambiguous across the module set."""
        name = _terminal_name(call.func)
        if name is None:
            return []
        if isinstance(call.func, ast.Attribute):
            # only `self.m()` resolves through an attribute — methods
            # on arbitrary objects would have to match by terminal name
            # alone, which is both unsound (`it.close()` is not
            # `AsyncCheckpointWriter.close`) and unstable across
            # analyzed-file sets
            if (isinstance(call.func.value, ast.Name)
                    and call.func.value.id == "self"
                    and "." in caller_qual):
                cls_prefix = caller_qual.rsplit(".", 1)[0]
                hit = self.by_qual.get((mod.rel, f"{cls_prefix}.{name}"))
                if hit is not None:
                    return [hit]
            return []
        cands = self.by_name.get(name, [])
        local = [c for c in cands if c.mod is mod]
        if local:
            return local
        if len(cands) == 1:
            return cands
        # ambiguous cross-module bare name: only trust a UNANIMOUS
        # summary
        if cands and all(c.has_collectives for c in cands):
            return cands[:1]
        return []

    # -- site / predicate classification --------------------------------

    def site_label(self, mod: Module, caller_qual: str,
                   call: ast.Call) -> str | None:
        """Non-None when the call is a cross-host rendezvous: the token
        that enters the path's collective sequence."""
        dotted = mod.dotted(call.func)
        if dotted in COLLECTIVE_CALLS:
            return dotted.rsplit(".", 1)[1]
        if dotted and dotted.startswith("jax.experimental.multihost_utils."):
            return dotted.rsplit(".", 1)[1]
        name = _terminal_name(call.func)
        if name in COLLECTIVE_HELPERS:
            return name
        for info in self.resolve(mod, caller_qual, call):
            if info.has_collectives:
                return f"->{name}"
        return None

    def host_local_reason(self, mod: Module, caller_qual: str, expr,
                          tainted: dict[str, str]) -> str | None:
        """Why ``expr`` evaluates through per-host state, or None.
        Descends the expression; a uniform-result call is a hard stop
        (its arguments may be per-host — that is its purpose)."""
        if expr is None or isinstance(expr, ast.Constant):
            return None
        if isinstance(expr, ast.Call):
            dotted = mod.dotted(expr.func)
            name = _terminal_name(expr.func)
            if (name in UNIFORM_RESULT_CALLS
                    or dotted in UNIFORM_RESULT_DOTTED):
                return None
            if dotted in HOST_LOCAL_DOTTED:
                return f"{dotted}()"
            if dotted and any(dotted.startswith(p)
                              for p in HOST_LOCAL_PREFIXES):
                return f"{dotted}()"
            if (isinstance(expr.func, ast.Attribute)
                    and expr.func.attr in HOST_LOCAL_ATTRS):
                return f".{expr.func.attr}()"
            for info in self.resolve(mod, caller_qual, expr):
                if info.host_local_return:
                    return (f"{name}() returns a per-host fact "
                            f"({info.host_local_return})")
            parts = [*expr.args, *[kw.value for kw in expr.keywords]]
            if isinstance(expr.func, ast.Attribute):
                parts.append(expr.func.value)
            for p in parts:
                r = self.host_local_reason(mod, caller_qual, p, tainted)
                if r:
                    return r
            return None
        if isinstance(expr, (ast.Name, ast.Attribute)):
            dotted = mod.raw_dotted(expr)
            if dotted is not None:
                for t, reason in tainted.items():
                    if dotted == t or dotted.startswith(t + "."):
                        return f"`{t}` ({reason})"
                return None
        for child in ast.iter_child_nodes(expr):
            r = self.host_local_reason(mod, caller_qual, child, tainted)
            if r:
                return r
        return None

    def function_taint(self, mod: Module, info: FnInfo) -> dict[str, str]:
        """name -> reason for every local name data-flow tainted by a
        host-local source (monotone fixpoint; reassignment never clears
        — a name that EVER held per-host state stays suspect, the
        conservative direction for a rendezvous check).

        Cached per function once the summary fixpoint settled (the
        taint depends on callee summaries, which only grow DURING
        :meth:`_summarize`; afterwards the ASTs are immutable) — the
        watcher polls verify_paths, so the repeated whole-AST fixpoints
        are worth skipping."""
        if info.taint is not None:
            return info.taint
        tainted: dict[str, str] = {}

        def taint_targets(targets, value, reason_prefix=""):
            if value is None:
                return False
            reason = self.host_local_reason(mod, info.qual, value, tainted)
            if not reason:
                return False
            reason = reason_prefix + reason
            hit = False
            flat = []
            for t in targets:
                flat.extend(t.elts if isinstance(
                    t, (ast.Tuple, ast.List)) else [t])
            for t in flat:
                dotted = mod.raw_dotted(t)
                if dotted is not None and dotted not in tainted:
                    tainted[dotted] = reason
                    hit = True
            return hit

        changed = True
        while changed:
            changed = False
            for node in ast.walk(info.fn):
                if isinstance(node, ast.Assign):
                    changed |= taint_targets(node.targets, node.value)
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    changed |= taint_targets([node.target], node.value)
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    # iterating a per-host iterable binds per-host items
                    # (`for name in os.listdir(root)` taints `name`)
                    changed |= taint_targets(
                        [node.target], node.iter,
                        reason_prefix="iterated from ")
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        if item.optional_vars is not None:
                            changed |= taint_targets(
                                [item.optional_vars], item.context_expr)
        if self._summaries_final:
            info.taint = tainted
        return tainted

    # -- fixpoint summaries ---------------------------------------------

    def _summarize(self) -> None:
        changed = True
        while changed:
            changed = False
            for info in self.infos.values():
                if not info.has_collectives:
                    for node in ast.walk(info.fn):
                        if isinstance(node, ast.Call) and self.site_label(
                                info.mod, info.qual, node) is not None:
                            info.has_collectives = True
                            changed = True
                            break
                if info.host_local_return is None:
                    r = self._returns_host_local(info)
                    if r:
                        info.host_local_return = r
                        changed = True

    def _returns_host_local(self, info: FnInfo) -> str | None:
        tainted = self.function_taint(info.mod, info)
        for node in ast.walk(info.fn):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            if info.mod.enclosing_function(node) is not info.fn:
                continue
            r = self.host_local_reason(info.mod, info.qual, node.value,
                                       tainted)
            if r:
                return r
            # control-sensitivity: a return under a host-local branch
            # returns a per-host fact even when its value is clean
            # (`if not os.path.isdir(p): return None`)
            cur = info.mod.parents.get(node)
            while cur is not None and cur is not info.fn:
                if isinstance(cur, (ast.If, ast.While)):
                    r = self.host_local_reason(info.mod, info.qual,
                                               cur.test, tainted)
                    if r:
                        return f"returned under a branch on {r}"
                cur = info.mod.parents.get(cur)
        return None


# -- the path-sensitive divergence check --------------------------------


def _label_seq(enum, seq):
    return tuple(enum.sites[s].label for s in seq)


def _seqset(enum, entries):
    # compare SETS of LABEL sequences: two paths through one arm with
    # the same rendezvous sequence are one behavior, not two — and two
    # ARMS spelling the identical collective sequence at different call
    # sites (`gather(1)` vs `gather(2)`) rendezvous identically, so
    # they must compare equal (site indices are per-node and would
    # always differ)
    return tuple(sorted({_label_seq(enum, e[0]) for e in entries}))


def _verify_function(modset: ModuleSet, mod: Module,
                     info: FnInfo) -> tuple[list[Finding], bool]:
    """(findings, truncated) — ``truncated`` is True when path or
    sequence bounds were hit and coverage is therefore partial."""
    if not info.has_collectives:
        return [], False
    tainted = modset.function_taint(mod, info)

    def site_label(call):
        return modset.site_label(mod, info.qual, call)

    def classify(expr):
        r = modset.host_local_reason(mod, info.qual, expr, tainted)
        return ("host-local", r) if r else ("uniform", "")

    enum = PathEnumerator(site_label, classify)
    paths = enum.run(info.fn)
    findings: list[Finding] = []
    seen: set[tuple] = set()
    for guard in enum.guards:
        if guard.cls != "host-local":
            continue
        # partition paths that reached this guard by their decision
        # prefix (identical prefix => identical collective prefix), then
        # compare the arms' downstream sequences
        groups: dict[tuple, dict[int, list]] = {}
        for p in paths:
            for i, (gid, arm) in enumerate(p.decisions):
                if gid == guard.gid:
                    groups.setdefault(p.decisions[:i], {}).setdefault(
                        arm, []).append((p.seq, p.exit, p.exit_node))
                    break
        for buckets in groups.values():
            arms = sorted(buckets)
            # ALL pairs, not each-vs-first: two handler arms can
            # rendezvous in different orders while each diverges from
            # the normal path only at an already-reviewed site
            for i, arm_a in enumerate(arms):
                for arm_b in arms[i + 1:]:
                    findings.extend(_diverging_arms(
                        mod, enum, guard, buckets[arm_a],
                        buckets[arm_b], seen))
    return findings, enum.truncated


def _first_site(enum, entries, labels):
    """The EXECUTION-ORDER-first concrete call node across ``entries``
    whose label is in ``labels`` (site indices follow discovery order,
    which follows statement order) — findings anchor where the
    divergence first bites, not at an alphabetically arbitrary label."""
    best = None
    for seq, _, _ in entries:
        for idx in seq:
            if enum.sites[idx].label in labels:
                if best is None or idx < best:
                    best = idx
    return enum.sites[best] if best is not None else None


def _diverging_arms(mod, enum, guard, a, b, seen):
    if _seqset(enum, a) == _seqset(enum, b):
        return []
    labels_a = {lab for seq, _, _ in a for lab in _label_seq(enum, seq)}
    labels_b = {lab for seq, _, _ in b for lab in _label_seq(enum, seq)}

    def mk(rule, node, detail):
        key = (rule, getattr(node, "lineno", 1))
        if key in seen:
            return []
        # the suppression check lives HERE, not post-hoc: a suppressed
        # anchor absorbs ITS divergence (and marks the suppression
        # used) while other divergent sequence pairs at the same guard
        # keep their own anchors — a reviewed single-host arm must not
        # bury an unreviewed swap in a sibling arm
        if mod.suppressions.allows(getattr(node, "lineno", 1), rule):
            seen.add(key)
            return []
        seen.add(key)
        where = (f"branch at line {guard.line} "
                 f"({guard.reason or 'per-host state'})")
        return [Finding(rule, mod.rel, getattr(node, "lineno", 1),
                        getattr(node, "col_offset", 0),
                        f"{detail} — {where}; every host must issue the "
                        f"same ordered collective sequence, or guard the "
                        f"divergence with a host-uniform predicate "
                        f"(vote/allgather result)")]

    if guard.kind == "loop":
        extra = ((labels_a | labels_b) - (labels_a & labels_b)) \
            or (labels_a | labels_b)
        anchor = _first_site(enum, a + b, extra)
        return mk("protocol-divergent-loop", anchor.node,
                  f"collective `{anchor.label}` inside a loop whose "
                  f"trip count is host-local: hosts iterating different "
                  f"counts issue different rendezvous sequences")
    if labels_a != labels_b and (labels_a <= labels_b
                                 or labels_b <= labels_a):
        small, big = (a, b) if labels_a <= labels_b else (b, a)
        missing = (labels_b - labels_a) or (labels_a - labels_b)
        anchor = _first_site(enum, big, missing)
        exits = {e for _, e, _ in small}
        if exits and exits <= {"return", "raise"}:
            exit_node = next(n for _, e, n in small
                             if e in ("return", "raise") and n is not None)
            # anchor at the exit only when it sits inside the guarded
            # region — a path that merely BYPASSES the arm may exit far
            # away, and the suppressible decision is the guard itself
            g0 = guard.line
            g1 = getattr(guard.node, "end_lineno", g0)
            exit_line = getattr(exit_node, "lineno", 0)
            where_node = exit_node if g0 <= exit_line <= g1 else guard.node
            return mk("protocol-early-exit", where_node,
                      f"early {'/'.join(sorted(exits))} skips collective "
                      f"`{anchor.label}` (line {anchor.line}) that the "
                      f"fall-through path still issues: a peer taking the "
                      f"other arm parks alone in the rendezvous")
        return mk("protocol-divergent-entry", anchor.node,
                  f"collective `{anchor.label}` is issued on one arm of a "
                  f"host-local branch and never on the other: entry into "
                  f"the rendezvous is decided per-host")
    # both arms issue collectives, but the sequences differ: each
    # sequence one arm can produce and the other cannot is its own
    # candidate divergence, anchored at the first site where it departs
    # from the other arm's closest behavior — so one reviewed
    # (suppressed) divergent pair does not mask an unreviewed one
    uniq_a = {}
    for seq, _, _ in a:
        uniq_a.setdefault(_label_seq(enum, seq), seq)
    uniq_b = {}
    for seq, _, _ in b:
        uniq_b.setdefault(_label_seq(enum, seq), seq)
    only_a = sorted(k for k in uniq_a if k not in uniq_b)
    only_b = sorted(k for k in uniq_b if k not in uniq_a)
    # pair unmatched behaviors one-to-one (each pair is ONE divergence
    # fact with ONE anchor — so a reviewed pair's suppression absorbs
    # exactly that pair, while an unreviewed swap in a sibling pair
    # keeps its own anchor); when one side has no unmatched behavior,
    # pair against its closest (minimal) behavior instead
    pairs = []
    if only_a and only_b:
        for la, lb in zip(only_a, only_b):
            pairs.append((la, uniq_a[la], lb, uniq_b[lb], "b"))
        # surplus behaviors on either side are witnessed by the zipped
        # pairs above (the arms already provably diverge)
    elif only_a:
        ref = min(uniq_b)
        for la in only_a:
            pairs.append((la, uniq_a[la], ref, uniq_b[ref], "a"))
    else:
        ref = min(uniq_a)
        for lb in only_b:
            pairs.append((ref, uniq_a[ref], lb, uniq_b[lb], "b"))
    out = []
    for la, ia, lb, ib, prefer in pairs:
        anchor = None
        for i in range(max(len(la), len(lb))):
            ta = la[i] if i < len(la) else None
            tb = lb[i] if i < len(lb) else None
            if ta != tb:
                cand = []
                if prefer == "b":
                    cand = [(ib, i, len(lb)), (ia, i, len(la))]
                else:
                    cand = [(ia, i, len(la)), (ib, i, len(lb))]
                for iseq, pos, n in cand:
                    if pos < n:
                        anchor = enum.sites[iseq[pos]]
                        break
                break
        if anchor is None:
            idxs = ib or ia
            anchor = enum.sites[idxs[0]] if idxs else _first_site(
                enum, a + b, labels_a | labels_b)
        out.extend(mk(
            "protocol-order-divergence", anchor.node,
            f"collective order diverges across the arms of a "
            f"host-local branch ({list(la)} vs {list(lb)}): hosts "
            f"taking different arms rendezvous in different orders "
            f"and deadlock"))
    return out


def verify_paths(paths: list[str], root: str,
                 report_useless: bool = True):
    """Run the protocol verifier over every .py under ``paths`` that is
    in scope (PROTOCOL_MODULES, or carries a ``# tpudp:
    protocol-module`` marker).  Returns ``(findings, errors)`` exactly
    like :func:`tpudp.analysis.core.lint_paths` — suppressed hits
    removed, plus a ``useless-suppression`` finding for every
    suppression naming a protocol rule that matched nothing (the lint
    pass defers protocol-rule names here)."""
    from .core import in_protocol_scope

    modules: list[Module] = []
    errors: list[str] = []
    for path, rel in iter_python_files(paths, root):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            if not in_protocol_scope(rel, _head_markers(source)):
                continue
            modules.append(Module(path, rel, source))
        except (SyntaxError, UnicodeDecodeError) as exc:
            errors.append(f"{rel}: parse failed: {exc}")
    modset = ModuleSet(modules)
    findings: list[Finding] = []
    for mod in modules:
        for fn in mod.functions:
            info = modset.infos[id(fn)]
            # suppression filtering happened inside the comparison
            # (mk's in-check absorption), so these are final
            fn_findings, truncated = _verify_function(modset, mod, info)
            findings.extend(fn_findings)
            if truncated:
                # silent under-coverage must be visible: a truncated
                # function fails the gate like a parse error does
                errors.append(
                    f"{mod.rel}: `{info.qual}` exceeded the path/"
                    f"sequence bounds (MAX_PATHS={_MAX_PATHS}, "
                    f"MAX_SEQ={_MAX_SEQ}) — protocol verification of "
                    f"it is incomplete; split the function or raise "
                    f"the bounds")
        if report_useless:
            for line, rule_name in mod.suppressions.unused():
                if rule_name in PROTOCOL_RULE_NAMES:
                    findings.append(Finding(
                        "useless-suppression", mod.rel, line, 0,
                        f"lint-ok({rule_name}) suppresses nothing — "
                        f"remove it (or the protocol divergence it "
                        f"excused is gone)"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, errors


def _head_markers(source: str) -> set[str]:
    """Markers in the first 5 lines, extracted with EXACTLY the same
    machinery as ``Module.markers`` (real comment tokens + MARKER_RE) —
    the scope decision must agree between the lint pass (which defers
    protocol-rule suppressions for in-scope files) and this pass, or a
    marker spelled with trailing text would be in one pass's scope and
    not the other's."""
    from .core import MARKER_RE, comment_tokens

    head = "\n".join(source.splitlines()[:5])
    return {m.group(1)
            for _line, text in comment_tokens(head).items()
            for m in [MARKER_RE.search(text)] if m}


# -- the vote/park state-machine model checker --------------------------

OK, FAULT = 0, 1

RUN, VOTE, PARK, DONE, CRASH, TEXIT = "run", "vote", "park", "done", \
    "crash", "texit"
TERMINAL = {DONE, CRASH, TEXIT}


@dataclasses.dataclass(frozen=True)
class VoteSpec:
    """The agreed-action protocol as a checkable spec.

    ``completion_park``: a host that finishes cleanly parks at a
    completion vote (joins every later round) instead of exiting —
    PR 7's fix for the late-faulter-with-no-partner deadlock.
    ``bounded_timeout``: a vote that can never complete (peer crashed
    or departed) hard-exits (VOTE_TIMEOUT_EXIT) instead of waiting
    forever.  Both are extracted from the live source by
    :func:`extract_vote_spec`."""

    n_hosts: int = 2
    max_faults: int = 1
    max_crashes: int = 1
    completion_park: bool = True
    bounded_timeout: bool = True


def explore_vote_machine(spec: VoteSpec) -> dict:
    """Exhaustive BFS over bounded host interleavings.  Returns
    ``{"states": n, "violations": [...]}`` where each violation is
    ``{"kind": "deadlock" | "spurious-timeout", "state": ...}`` —
    deadlock = a non-final state with no enabled transition;
    spurious-timeout = a healthy pod (zero crashes so far) losing a
    host to the vote timeout, i.e. the protocol itself stranded a
    live voter."""
    # host state: (RUN, faults_left, rounds) | (VOTE, rounds+1) |
    # (PARK, rounds+1) | terminal markers
    init = tuple((RUN, spec.max_faults, 0) for _ in range(spec.n_hosts))
    queue = deque([(init, 0)])
    seen = {(init, 0)}
    violations = []

    def waiting(h):
        return h[0] in (VOTE, PARK)

    while queue:
        state, crashes = queue.popleft()
        nexts = []
        # joint vote resolution: the allgather answers only when EVERY
        # configured host is waiting at the same seq — a crashed or
        # departed (done-without-park) peer never answers, and the
        # survivors' only way out is the bounded timeout
        if all(waiting(h) for h in state):
            seqs = {h[1] for h in state}
            if len(seqs) == 1:
                worst = FAULT if any(h[0] == VOTE for h in state) else OK
                new = []
                for h in state:
                    if h[0] == VOTE:
                        new.append((RUN, h[2], h[1]))
                    elif h[0] == PARK:
                        new.append((RUN, h[2], h[1]) if worst == FAULT
                                   else (DONE,))
                    else:
                        new.append(h)
                nexts.append((tuple(new), crashes))
        for i, h in enumerate(state):
            if h[0] == RUN:
                _, faults, rounds = h
                if faults > 0:  # a fault: call a vote round
                    nexts.append((_swap(state, i,
                                        (VOTE, rounds + 1, faults - 1)),
                                  crashes))
                # clean finish
                fin = (PARK, rounds + 1, faults) if spec.completion_park \
                    else (DONE,)
                nexts.append((_swap(state, i, fin), crashes))
            if h[0] not in TERMINAL and crashes < spec.max_crashes:
                nexts.append((_swap(state, i, (CRASH,)), crashes + 1))
            if waiting(h) and spec.bounded_timeout:
                # the timeout only FIRES when the vote can never
                # complete: some peer is terminal (crashed, exited, or
                # done-without-parking)
                if any(p[0] in TERMINAL for j, p in enumerate(state)
                       if j != i):
                    nexts.append((_swap(state, i, (TEXIT,)), crashes))
                    if crashes == 0:
                        violations.append({
                            "kind": "spurious-timeout",
                            "state": _render(state),
                            "detail": f"host {i} times out of a vote "
                                      f"with every peer alive — a "
                                      f"healthy pod loses a host"})
        if not nexts and any(h[0] not in TERMINAL for h in state):
            violations.append({
                "kind": "deadlock", "state": _render(state),
                "detail": "live hosts wait at a rendezvous no peer "
                          "will ever join"})
        for n in nexts:
            if n not in seen:
                seen.add(n)
                queue.append(n)
    return {"states": len(seen), "violations": violations}


def _swap(state, i, h):
    return state[:i] + (h,) + state[i + 1:]


def _render(state):
    return tuple("/".join(str(x) for x in h) for h in state)


def extract_vote_spec(source: str, *, n_hosts: int = 2,
                      max_faults: int = 2,
                      max_crashes: int = 1) -> VoteSpec:
    """Extract the protocol's two load-bearing properties from the live
    ``tpudp/resilience.py`` source: does a clean finisher park at a
    completion vote (``self._vote(OUTCOME_OK)`` on ``Supervisor.run``'s
    success path), and is the vote wait bounded (``vote_timeout_s``
    plus a hard exit in ``Supervisor._vote``)?  The returned spec is
    what :func:`explore_vote_machine` proves deadlock-free — so
    deleting either property from the source is caught by the model
    checker, not just by review."""
    tree = ast.parse(source)
    completion_park = False
    bounded_timeout = False
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name == "run":
            for call in ast.walk(node):
                if (isinstance(call, ast.Call)
                        and _terminal_name(call.func) == "_vote"
                        and call.args
                        and isinstance(call.args[0], ast.Name)
                        and call.args[0].id == "OUTCOME_OK"):
                    completion_park = True
        if node.name == "_vote":
            has_timeout = any(
                isinstance(n, (ast.Name, ast.Attribute))
                and (getattr(n, "id", None) == "vote_timeout_s"
                     or getattr(n, "attr", None) == "vote_timeout_s")
                for n in ast.walk(node))
            has_exit = any(
                isinstance(n, ast.Call)
                and _terminal_name(n.func) == "_exit"
                for n in ast.walk(node))
            bounded_timeout = has_timeout and has_exit
    return VoteSpec(n_hosts=n_hosts, max_faults=max_faults,
                    max_crashes=max_crashes,
                    completion_park=completion_park,
                    bounded_timeout=bounded_timeout)


# -- the migration-handshake state-machine model checker ----------------

# Phases of one tpudp/serve/disagg.py migration round, in rendezvous
# order.  OFFER/TRANSFER/ACK/SEAL are collective barriers every live
# host joins; ADOPT is the receiver-local work between TRANSFER and
# ACK where a corrupt payload is discovered.
OFFER, TRANSFER, ADOPT, ACK, SEAL = ("offer", "transfer", "adopt",
                                     "ack", "seal")


@dataclasses.dataclass(frozen=True)
class MigrationSpec:
    """The offer → transfer → adopt-ack → release handshake as a
    checkable spec.

    ``quarantine_acks``: a receiver that unpacks a corrupt or torn
    transfer quarantines it and STILL joins the ack gather (nacking
    the ticket) instead of leaving the round — without it the sender
    parks alone at phase 3.  ``release_on_ack``: the sender resolves
    its pending tickets only after the ack gather, so staged state is
    released exactly once per outcome.  ``fallback_local``: a ticket
    that exhausts its retries is re-admitted LOCALLY, so a dead link
    degrades to a pressure-vacate resume instead of wedging the
    request and leaking its staged pages.  All three are extracted
    from the live ``tpudp/serve/disagg.py`` source by
    :func:`extract_migration_spec`."""

    n_transfers: int = 2
    max_faults: int = 2
    max_retries: int = 1
    quarantine_acks: bool = True
    release_on_ack: bool = True
    fallback_local: bool = True


def explore_migration_machine(spec: MigrationSpec) -> dict:
    """Exhaustive BFS over one sender/receiver pair driving
    ``n_transfers`` tickets through migration rounds, with up to
    ``max_faults`` adversarial transfer corruptions injected at any
    round.  Returns ``{"states": n, "violations": [...]}`` where each
    violation is one of:

      * ``orphaned-rendezvous`` — one host leaves a round while its
        peer is still committed to a later barrier of the SAME round
        (the sender parks alone at the ack gather forever);
      * ``wedge`` — a ticket that can never resolve: retries
        exhausted, no local fallback, so the round loop never reaches
        the joint ``done`` decision;
      * ``page-leak`` — the run completes but staged sender state was
        never released.

    State: (tickets_left, attempts, faults_left, staged).  Rounds are
    lock-step (every barrier is a collective), so the only
    nondeterminism is the adversary's corrupt/clean choice per round —
    the bounded space is explored exhaustively."""
    init = (spec.n_transfers, 0, spec.max_faults, 0)
    queue = deque([init])
    seen = {init}
    violations = []

    def viol(kind, state, detail):
        violations.append({"kind": kind, "state": state,
                           "detail": detail})

    while queue:
        state = queue.popleft()
        tickets, attempts, faults, staged = state
        if tickets == 0:
            if staged:
                viol("page-leak", state,
                     f"{staged} staged page(s) never released after "
                     f"the final round — export leaked on the sender")
            continue
        nexts = []
        # adversary choice per round: deliver clean, or corrupt the
        # payload (while it still has faults in budget)
        for corrupt in ((False, True) if faults > 0 else (False,)):
            if not corrupt:
                # clean delivery: receiver adopts, acks ok; sender
                # releases on the ack (or keeps the staged state
                # forever if release_on_ack was deleted)
                new_staged = 0 if spec.release_on_ack else staged + 1
                nexts.append((tickets - 1, 0, faults, new_staged))
                continue
            nfaults = faults - 1
            if not spec.quarantine_acks:
                # receiver bails out of the round between TRANSFER and
                # ACK; the sender is already committed to the ack
                # gather and parks alone — terminal
                viol("orphaned-rendezvous", state,
                     "receiver exits the round on a corrupt transfer; "
                     "sender parks alone at the ack gather (phase "
                     f"{ACK!r} of the same round)")
                continue
            # quarantined: nack comes back on the ack gather
            if attempts < spec.max_retries:
                nexts.append((tickets, attempts + 1, nfaults, staged))
            elif spec.fallback_local:
                # retries exhausted: local re-admission resolves the
                # ticket (as failed) and releases the staged state
                nexts.append((tickets - 1, 0, nfaults,
                              0 if spec.release_on_ack else staged + 1))
            else:
                # no retry budget, no fallback: the ticket re-enters
                # the outbox forever and the joint done vote never
                # fires — terminal
                viol("wedge", state,
                     f"ticket out of retries with no local fallback — "
                     f"the round loop never reaches the joint "
                     f"{SEAL!r} with done=1")
        for n in nexts:
            if n not in seen:
                seen.add(n)
                queue.append(n)
    return {"states": len(seen), "violations": violations}


def extract_migration_spec(source: str, *, n_transfers: int = 2,
                           max_faults: int = 2,
                           max_retries: int = 1) -> MigrationSpec:
    """Extract the handshake's three load-bearing properties from the
    live ``tpudp/serve/disagg.py`` source: does ``DisaggHost.round``'s
    ``TransferCorrupt`` handler stay in the round (no ``return`` /
    ``raise`` — it must still reach the ack gather), does ``round``
    resolve pending tickets via ``release_acks`` only AFTER the ack
    gather (the last ``gather_host_blobs``), and does ``release_acks``
    fall back to local ``admit_ticket`` when a ticket dies?  The
    returned spec is what :func:`explore_migration_machine` proves
    orphan/wedge/leak-free — deleting any property from the source is
    caught by the model checker, not just by review."""
    tree = ast.parse(source)
    quarantine_acks = False
    release_on_ack = False
    fallback_local = False
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name == "round":
            for handler in (h for n in ast.walk(node)
                            if isinstance(n, ast.Try)
                            for h in n.handlers):
                if (_terminal_name(handler.type) != "TransferCorrupt"):
                    continue
                leaves = any(isinstance(n, (ast.Return, ast.Raise))
                             for b in handler.body for n in ast.walk(b))
                quarantine_acks = not leaves
            gathers = [n.lineno for n in ast.walk(node)
                       if isinstance(n, ast.Call)
                       and _terminal_name(n.func) == "gather_host_blobs"]
            releases = [n.lineno for n in ast.walk(node)
                        if isinstance(n, ast.Call)
                        and _terminal_name(n.func) == "release_acks"]
            release_on_ack = bool(gathers and releases
                                  and min(releases) > max(gathers))
        if node.name == "release_acks":
            fallback_local = any(
                isinstance(n, ast.Call)
                and _terminal_name(n.func) == "admit_ticket"
                for n in ast.walk(node))
    return MigrationSpec(n_transfers=n_transfers, max_faults=max_faults,
                         max_retries=max_retries,
                         quarantine_acks=quarantine_acks,
                         release_on_ack=release_on_ack,
                         fallback_local=fallback_local)

"""``python -m tpudp.analysis`` — lint, audit, protocol, budget, and
the ``check`` umbrella.

Exit codes compose with ``set -o pipefail`` harnesses: 0 = clean,
1 = findings / audit mismatch, 2 = usage or internal error.  ``check``
runs every gate and composes their codes (2 beats 1 beats 0).

``lint`` and ``protocol`` are pure stdlib and run anywhere; ``audit``
and ``budget`` force the CPU backend at the pinned smoke geometry
(8 virtual devices) BEFORE jax initializes, so the committed lockfile
is reproducible on any host — laptop, CI, or a TPU VM — and never
depends on what accelerator happens to be attached.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .audit import repo_root

DEFAULT_LOCK = os.path.join("tools", "trace_lock.json")

#: What `check` lints (tier-1's tree-wide scope) when no paths given.
CHECK_LINT_PATHS = ("tpudp", "tools", "benchmarks")


def _cmd_lint(args) -> int:
    from .core import lint_paths
    from .rules import RULES

    if args.list_rules:
        for rule in RULES:
            print(f"{rule.name}: {rule.summary}")
        return 0
    root = repo_root()
    paths = args.paths or ["tpudp"]
    missing = [p for p in paths if not os.path.exists(
        p if os.path.isabs(p) else os.path.join(root, p))]
    if missing:
        # a typo'd path must not turn the gate green by linting nothing
        print(f"error: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    findings, errors = lint_paths(paths, root)
    for err in errors:
        print(f"error: {err}", file=sys.stderr)
    for f in findings:
        print(f.render())
    n = len(findings)
    print(f"tpudp.analysis lint: {n} finding{'s' if n != 1 else ''} "
          f"({len(errors)} parse error{'s' if len(errors) != 1 else ''})")
    return 1 if findings or errors else 0


def _cmd_audit(args) -> int:
    from . import audit

    root = repo_root()
    lock_path = os.path.join(root, args.lock)
    lock = None
    if not args.update:
        # fail fast BEFORE the (multi-second) trace capture
        try:
            lock = audit.load_lock(lock_path)
        except FileNotFoundError:
            print(f"error: no lockfile at {args.lock} — run "
                  f"`python -m tpudp.analysis audit --update` and commit "
                  f"it", file=sys.stderr)
            return 1
        except (OSError, json.JSONDecodeError) as exc:
            print(f"error: unreadable lockfile {args.lock} "
                  f"({type(exc).__name__}: {exc}) — fix it (merge "
                  f"conflict?) or regenerate with --update",
                  file=sys.stderr)
            return 1
    try:
        audit.force_smoke_backend()
    except RuntimeError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    current = audit.capture()
    if args.update:
        audit.write_lock(lock_path, current)
        print(f"tpudp.analysis audit: lockfile updated "
              f"({len(current['programs'])} programs) -> {args.lock}")
        return 0
    problems = audit.compare(lock, current)
    for p in problems:
        print(p)
    n = len(current["programs"])
    if problems:
        print(f"tpudp.analysis audit: {len(problems)} mismatch"
              f"{'es' if len(problems) != 1 else ''} against {args.lock} — "
              f"if the trace change is intended, regenerate with --update "
              f"and commit the diff")
        return 1
    print(f"tpudp.analysis audit: {n} step programs match {args.lock}")
    return 0


def _cmd_protocol(args) -> int:
    from .protocol import (PROTOCOL_MODULES, VoteSpec, explore_vote_machine,
                           extract_vote_spec, verify_paths)

    root = repo_root()
    paths = args.paths or ["tpudp"]
    missing = [p for p in paths if not os.path.exists(
        p if os.path.isabs(p) else os.path.join(root, p))]
    if missing:
        print(f"error: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    findings, errors = verify_paths(paths, root)
    for err in errors:
        print(f"error: {err}", file=sys.stderr)
    for f in findings:
        print(f.render())
    rc = 1 if findings or errors else 0
    n = len(findings)
    print(f"tpudp.analysis protocol: {n} finding{'s' if n != 1 else ''} "
          f"over the multihost modules ({len(PROTOCOL_MODULES)} in scope)")
    if not args.no_model_check:
        # Bounded interleaving exploration of the vote/park machine, the
        # spec extracted from the LIVE resilience source: deleting the
        # completion park or the bounded timeout fails here.
        res_path = os.path.join(root, "tpudp", "resilience.py")
        try:
            with open(res_path, encoding="utf-8") as f:
                spec = extract_vote_spec(f.read(), n_hosts=args.hosts,
                                         max_faults=2, max_crashes=1)
        except OSError as exc:
            print(f"error: cannot read {res_path}: {exc}", file=sys.stderr)
            return 2
        result = explore_vote_machine(spec)
        if result["violations"]:
            for v in result["violations"][:8]:
                print(f"vote machine {v['kind']}: {v['detail']} "
                      f"[state {v['state']}]")
            print(f"tpudp.analysis protocol: vote state machine has "
                  f"{len(result['violations'])} violation(s) within bounds "
                  f"(hosts={spec.n_hosts}, faults<=2/host, crashes<=1; "
                  f"extracted spec: completion_park={spec.completion_park}, "
                  f"bounded_timeout={spec.bounded_timeout})")
            rc = max(rc, 1)
        else:
            print(f"tpudp.analysis protocol: vote state machine "
                  f"deadlock-free within bounds ({result['states']} states; "
                  f"hosts={spec.n_hosts}, faults<=2/host, crashes<=1)")
        # the spec a correct protocol must extract to
        if not (spec.completion_park and spec.bounded_timeout):
            rc = max(rc, 1)
    return rc


def _cmd_budget(args) -> int:
    import json as _json

    from . import audit, budget

    root = repo_root()
    lock_path = os.path.join(root, args.lock)
    try:
        lock = audit.load_lock(lock_path)
    except FileNotFoundError:
        print(f"error: no lockfile at {args.lock} — run "
              f"`python -m tpudp.analysis audit --update` and commit it",
              file=sys.stderr)
        return 1
    except (OSError, _json.JSONDecodeError) as exc:
        print(f"error: unreadable lockfile {args.lock} "
              f"({type(exc).__name__}: {exc})", file=sys.stderr)
        return 1
    if args.table:
        print(budget.render_table(lock.get("programs", {})))
        if not budget.lock_has_ledgers(lock):
            missing = sorted(n for n, rec in lock.get("programs",
                                                      {}).items()
                             if "budget" not in rec)
            what = (f"{len(missing)} program(s) without a ledger: "
                    f"{', '.join(missing)}" if missing
                    else "no capture geometry recorded")
            print(f"tpudp.analysis budget: lock is not budget-complete "
                  f"({what}) — regenerate with `audit --update`")
            return 1
        return 0
    try:
        audit.force_smoke_backend()
    except RuntimeError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    current = audit.capture()
    # same skew gate as `audit`: a jax/geometry mismatch must be ONE
    # named diagnostic, never a per-program budget-mismatch storm with
    # misleading --update advice
    skew = audit.identity_skew(lock, current)
    if skew:
        for p in skew:
            print(p)
        return 1
    problems = []
    locked = lock.get("programs", {})
    for name, rec in current["programs"].items():
        problems.extend(budget.compare_budgets(
            name, locked.get(name, {}).get("budget"), rec.get("budget")))
    for p in problems:
        print(p)
    n = len(current["programs"])
    if problems:
        print(f"tpudp.analysis budget: {len(problems)} budget "
              f"mismatch{'es' if len(problems) != 1 else ''} against "
              f"{args.lock}")
        return 1
    print(f"tpudp.analysis budget: {n} program ledgers within tolerance "
          f"of {args.lock}")
    return 0


def _cmd_check(args) -> int:
    """The umbrella gate: lint + protocol (stdlib) then audit incl.
    budget (jax), exit codes composed — 2 (usage/internal) beats 1
    (findings) beats 0."""
    import argparse as _argparse

    rcs = []
    print("== lint ==")
    rcs.append(_cmd_lint(_argparse.Namespace(
        paths=list(CHECK_LINT_PATHS), list_rules=False)))
    print("== protocol ==")
    rcs.append(_cmd_protocol(_argparse.Namespace(
        paths=["tpudp"], no_model_check=False, hosts=3)))
    print("== audit (trace + budget ledgers) ==")
    rcs.append(_cmd_audit(_argparse.Namespace(
        update=False, lock=args.lock)))
    rc = max(rcs)
    names = ["lint", "protocol", "audit+budget"]
    status = ", ".join(f"{n}={'ok' if c == 0 else f'FAIL({c})'}"
                       for n, c in zip(names, rcs))
    print(f"tpudp.analysis check: {status}")
    return rc


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpudp.analysis",
        description="JAX-hazard linter + trace-stability auditor for the "
                    "tpudp invariants (docs/ANALYSIS.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    lint = sub.add_parser(
        "lint", help="AST hazard rules over the given paths (default: "
                     "tpudp/); nonzero on any unsuppressed finding")
    lint.add_argument("paths", nargs="*",
                      help="files/directories, relative to the repo root")
    lint.add_argument("--list-rules", action="store_true",
                      help="print the rule catalogue and exit")
    lint.set_defaults(fn=_cmd_lint)

    aud = sub.add_parser(
        "audit", help="trace the registered step programs at the CPU "
                      "smoke geometries and diff jaxpr fingerprints + "
                      "host-transfer/collective census against "
                      f"{DEFAULT_LOCK}")
    aud.add_argument("--update", action="store_true",
                     help="regenerate the lockfile from the current tree")
    aud.add_argument("--lock", default=DEFAULT_LOCK,
                     help="lockfile path relative to the repo root")
    aud.set_defaults(fn=_cmd_audit)

    proto = sub.add_parser(
        "protocol", help="path-sensitive cross-host protocol verifier "
                         "over the multihost modules (host-uniform "
                         "collective sequences) + bounded vote-machine "
                         "model check; stdlib-only")
    proto.add_argument("paths", nargs="*",
                       help="files/directories, relative to the repo root "
                            "(default: tpudp/)")
    proto.add_argument("--no-model-check", action="store_true",
                       help="skip the vote state-machine exploration")
    proto.add_argument("--hosts", type=int, default=3,
                       help="host count bound for the interleaving "
                            "explorer (default 3)")
    proto.set_defaults(fn=_cmd_protocol)

    bud = sub.add_parser(
        "budget", help="diff the per-program resource ledgers (peak live "
                       "bytes, collective payload) against the lockfile; "
                       "--table prints the committed ledgers without "
                       "tracing (stdlib)")
    bud.add_argument("--lock", default=DEFAULT_LOCK,
                     help="lockfile path relative to the repo root")
    bud.add_argument("--table", action="store_true",
                     help="print the committed ledger table and exit "
                          "(no jax import)")
    bud.set_defaults(fn=_cmd_budget)

    chk = sub.add_parser(
        "check", help="umbrella gate: lint + protocol + audit (with "
                      "budget ledgers), exit codes composed — nonzero "
                      "if ANY gate fails")
    chk.add_argument("--lock", default=DEFAULT_LOCK,
                     help="lockfile path relative to the repo root")
    chk.set_defaults(fn=_cmd_check)

    args = parser.parse_args(argv)
    return args.fn(args)

"""``python -m tpudp.analysis`` — lint and audit entry points.

Exit codes compose with ``set -o pipefail`` harnesses: 0 = clean,
1 = findings / audit mismatch, 2 = usage or internal error.

``lint`` is pure stdlib and runs anywhere; ``audit`` forces the CPU
backend at the pinned smoke geometry (8 virtual devices) BEFORE jax
initializes, so the committed lockfile is reproducible on any host —
laptop, CI, or a TPU VM — and never depends on what accelerator
happens to be attached.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .audit import repo_root

DEFAULT_LOCK = os.path.join("tools", "trace_lock.json")


def _cmd_lint(args) -> int:
    from .core import lint_paths
    from .rules import RULES

    if args.list_rules:
        for rule in RULES:
            print(f"{rule.name}: {rule.summary}")
        return 0
    root = repo_root()
    paths = args.paths or ["tpudp"]
    missing = [p for p in paths if not os.path.exists(
        p if os.path.isabs(p) else os.path.join(root, p))]
    if missing:
        # a typo'd path must not turn the gate green by linting nothing
        print(f"error: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    findings, errors = lint_paths(paths, root)
    for err in errors:
        print(f"error: {err}", file=sys.stderr)
    for f in findings:
        print(f.render())
    n = len(findings)
    print(f"tpudp.analysis lint: {n} finding{'s' if n != 1 else ''} "
          f"({len(errors)} parse error{'s' if len(errors) != 1 else ''})")
    return 1 if findings or errors else 0


def _cmd_audit(args) -> int:
    from . import audit

    root = repo_root()
    lock_path = os.path.join(root, args.lock)
    lock = None
    if not args.update:
        # fail fast BEFORE the (multi-second) trace capture
        try:
            lock = audit.load_lock(lock_path)
        except FileNotFoundError:
            print(f"error: no lockfile at {args.lock} — run "
                  f"`python -m tpudp.analysis audit --update` and commit "
                  f"it", file=sys.stderr)
            return 1
        except (OSError, json.JSONDecodeError) as exc:
            print(f"error: unreadable lockfile {args.lock} "
                  f"({type(exc).__name__}: {exc}) — fix it (merge "
                  f"conflict?) or regenerate with --update",
                  file=sys.stderr)
            return 1
    try:
        audit.force_smoke_backend()
    except RuntimeError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    current = audit.capture()
    if args.update:
        audit.write_lock(lock_path, current)
        print(f"tpudp.analysis audit: lockfile updated "
              f"({len(current['programs'])} programs) -> {args.lock}")
        return 0
    problems = audit.compare(lock, current)
    for p in problems:
        print(p)
    n = len(current["programs"])
    if problems:
        print(f"tpudp.analysis audit: {len(problems)} mismatch"
              f"{'es' if len(problems) != 1 else ''} against {args.lock} — "
              f"if the trace change is intended, regenerate with --update "
              f"and commit the diff")
        return 1
    print(f"tpudp.analysis audit: {n} step programs match {args.lock}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpudp.analysis",
        description="JAX-hazard linter + trace-stability auditor for the "
                    "tpudp invariants (docs/ANALYSIS.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    lint = sub.add_parser(
        "lint", help="AST hazard rules over the given paths (default: "
                     "tpudp/); nonzero on any unsuppressed finding")
    lint.add_argument("paths", nargs="*",
                      help="files/directories, relative to the repo root")
    lint.add_argument("--list-rules", action="store_true",
                      help="print the rule catalogue and exit")
    lint.set_defaults(fn=_cmd_lint)

    aud = sub.add_parser(
        "audit", help="trace the registered step programs at the CPU "
                      "smoke geometries and diff jaxpr fingerprints + "
                      "host-transfer/collective census against "
                      f"{DEFAULT_LOCK}")
    aud.add_argument("--update", action="store_true",
                     help="regenerate the lockfile from the current tree")
    aud.add_argument("--lock", default=DEFAULT_LOCK,
                     help="lockfile path relative to the repo root")
    aud.set_defaults(fn=_cmd_audit)

    args = parser.parse_args(argv)
    return args.fn(args)

"""The per-program resource ledger: static memory/comms budgets from a
pinned program's jaxpr.

The trace audit (PR 8) answers "did the program CHANGE?" — its sha256
fingerprint flips on any edit, but the diff says nothing about *what
got more expensive*.  This module walks the same ``jax.make_jaxpr``
capture and reduces it to the three quantities the upcoming serving
rungs (paged attention, TP serving) must not silently regress:

  * ``peak_live_bytes`` — peak simultaneously-live buffer bytes under a
    donation-aware liveness sweep: every equation's outputs are born at
    their definition and die after their last use; **donated** program
    inputs (the arena, the train state — the ``donate_argnums`` tables
    the use-after-donation rule mirrors) die at their last use too,
    while non-donated inputs and the frozen-weight constants stay
    resident for the whole program, exactly as XLA's aliasing rules
    allow.  Equations carrying sub-jaxprs (scan/cond/pjit) contribute
    their own inner peak at their program point.
  * ``collective_payload_bytes`` — total bytes moved by collective
    primitives (psum/ppermute/all_gather/...), recursively through
    sub-jaxprs: the static comms-volume twin of the audit's ordered
    collective sequence.
  * ``arg_bytes`` / ``out_bytes`` — the program's I/O footprint (flat
    argument and result bytes), the coarse "how big is a call" canary.

The ledger is committed into ``tools/trace_lock.json`` per program
(under ``"budget"``) by ``audit --update`` and diffed by ``audit`` /
``python -m tpudp.analysis budget`` with per-program, per-metric deltas
named.  Byte metrics carry a tolerance band
(:data:`BUDGET_TOLERANCES`) so an intended small change does not thrash
the gate, while a doubled live buffer or a new collective fails loudly
with the program and metric in the message.

This is a *static* model, not a simulator: XLA's scheduler may overlap
or rematerialize differently on a real backend.  It is a deterministic
canary — the same jaxpr always produces the same ledger, so any delta
in the lock diff is a real change to the traced program.

Only the jax half of the package touches this module; imports stay
inside functions so the lint half remains stdlib-importable.
"""

from __future__ import annotations

#: Relative tolerance per budget metric: |new - old| / max(old, 1)
#: must stay within the band, else the audit fails naming the metric.
#: Byte-exact metrics use 0.0.
BUDGET_TOLERANCES = {
    "peak_live_bytes": 0.10,
    "arg_bytes": 0.0,
    "out_bytes": 0.0,
    "collective_payload_bytes": 0.0,
}

#: Primitives whose name marks a collective (same parts as the audit
#: census, duplicated here so this module imports standalone).
_COLLECTIVE_PARTS = ("psum", "pmax", "pmin", "ppermute", "pbroadcast",
                     "all_gather", "all_to_all", "reduce_scatter",
                     "pgather")

#: Wrapper primitives that pass their invars straight through to one
#: sub-jaxpr — unwrapped so a jitted step function's ledger reflects
#: the program body, not a single opaque call equation.
_WRAPPER_PRIMS = {"pjit", "closed_call", "core_call", "remat", "remat2",
                  "custom_jvp_call", "custom_vjp_call"}


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    size = 1
    try:
        for d in shape:
            size *= int(d)
    except (TypeError, ValueError):  # symbolic dimension
        return 0
    return size * dtype.itemsize


def _sub_jaxprs(eqn):
    from jax.core import Jaxpr

    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for sub in vs:
            if isinstance(sub, Jaxpr) or hasattr(sub, "jaxpr"):
                yield sub


def _unwrap(jaxpr, donated):
    """Descend through single-equation pass-through wrappers (a jitted
    function traces to one ``pjit`` eqn) so the ledger sees the real
    body.  The donated-invar index set survives because a wrapper's eqn
    invars are the outer invars in order."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    while len(inner.eqns) == 1:
        eqn = inner.eqns[0]
        if eqn.primitive.name not in _WRAPPER_PRIMS:
            break
        outer_vars = [v for v in eqn.invars if hasattr(v, "aval")]
        if len(outer_vars) != len(inner.invars) or any(
                a is not b for a, b in zip(outer_vars, inner.invars)):
            break
        subs = list(_sub_jaxprs(eqn))
        if len(subs) != 1:
            break
        inner = getattr(subs[0], "jaxpr", subs[0])
    return inner, donated


def _peak_live(jaxpr, donated=frozenset()) -> int:
    """Donation-aware liveness sweep over one (open) jaxpr level."""
    from jax.core import Literal

    eqns = list(jaxpr.eqns)
    n = len(eqns)
    last_use: dict = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not isinstance(v, Literal):
                last_use[v] = i
    for v in jaxpr.outvars:
        if not isinstance(v, Literal):
            last_use[v] = n  # program results outlive every eqn
    resident = 0  # live for the whole program
    dying: list = []  # (birth, death, bytes) intervals
    for v in getattr(jaxpr, "constvars", ()):
        resident += _aval_bytes(v)
    for idx, v in enumerate(jaxpr.invars):
        if idx in donated:
            dying.append((-1, last_use.get(v, -1), _aval_bytes(v)))
        else:
            resident += _aval_bytes(v)
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            dying.append((i, last_use.get(v, i), _aval_bytes(v)))
    inner_extra = [0] * max(n, 1)
    for i, eqn in enumerate(eqns):
        io = sum(_aval_bytes(v) for v in list(eqn.invars) + list(eqn.outvars)
                 if not isinstance(v, Literal))
        extra = 0
        for sub in _sub_jaxprs(eqn):
            extra += max(0, _peak_live(getattr(sub, "jaxpr", sub)) - io)
        inner_extra[i] = extra
    if n == 0:
        return resident + sum(b for _, _, b in dying)
    peak = 0
    for i in range(n):
        live = resident + inner_extra[i]
        for b, d, size in dying:
            if b <= i <= d:
                live += size
        peak = max(peak, live)
    return peak


def _collective_payload(jaxpr) -> int:
    total = 0
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        name = eqn.primitive.name
        if any(p in name for p in _COLLECTIVE_PARTS):
            total += sum(_aval_bytes(v) for v in eqn.outvars)
        for sub in _sub_jaxprs(eqn):
            total += _collective_payload(sub)
    return total


def ledger(closed_jaxpr, donated=frozenset()) -> dict:
    """The budget record for one traced program.  ``donated`` holds the
    FLAT invar indices (pytree arguments flattened, the same order
    ``jax.make_jaxpr`` binds them) that the runtime donates."""
    from jax.core import Literal

    inner, donated = _unwrap(closed_jaxpr, frozenset(donated))
    arg_bytes = sum(_aval_bytes(v) for v in inner.invars)
    out_bytes = sum(_aval_bytes(v) for v in inner.outvars
                    if not isinstance(v, Literal))
    return {
        "peak_live_bytes": _peak_live(inner, donated),
        "arg_bytes": arg_bytes,
        "out_bytes": out_bytes,
        "collective_payload_bytes": _collective_payload(inner),
    }


def donated_flat_indices(args, donate_argnums) -> frozenset[int]:
    """Map per-ARGUMENT donation indices (the runtime's
    ``donate_argnums``) to FLAT invar indices: each pytree argument
    occupies a contiguous run of leaves in the traced program's invars."""
    import jax

    flat: set[int] = set()
    offset = 0
    donate = set(donate_argnums)
    for i, arg in enumerate(args):
        n = len(jax.tree_util.tree_leaves(arg))
        if i in donate:
            flat.update(range(offset, offset + n))
        offset += n
    return frozenset(flat)


def compare_budgets(name: str, old: dict | None,
                    new: dict | None) -> list[str]:
    """Named per-metric deltas for one program, tolerance bands applied.
    Returns human-readable problem strings (empty = within budget)."""
    problems = []
    if new is None:
        return problems
    if old is None:
        return [f"{name}: no budget ledger in the lockfile — regenerate "
                f"with --update to pin peak-live/comms budgets"]
    for metric, tol in BUDGET_TOLERANCES.items():
        a, b = old.get(metric), new.get(metric)
        if a is None or b is None or a == b:
            continue
        rel = abs(b - a) / max(abs(a), 1)
        if rel <= tol:
            continue
        direction = "+" if b > a else "-"
        problems.append(
            f"{name}: budget metric {metric} {a} -> {b} "
            f"({direction}{rel * 100:.2g}%, tolerance {tol * 100:.0f}%) — "
            f"the program's static resource ledger regressed; if intended, "
            f"regenerate with --update and review the lockfile diff")
    return problems


def lock_has_ledgers(lock: dict) -> bool:
    """Is the committed lock budget-complete — capture geometry present
    and a ledger under every pinned program?  THE one definition,
    shared by `budget --table`, the bench_gaps poll gate, and the
    tier-1 presence test (three consumers that must never disagree
    about the same artifact).  Stdlib-only."""
    programs = lock.get("programs")
    return bool(lock.get("geometry") and programs
                and all("budget" in rec for rec in programs.values()))


def render_table(programs: dict) -> str:
    """A fixed-width ledger table for the ``budget`` subcommand."""
    rows = [("program", "peak_live", "args", "outs", "coll_payload")]
    for name in sorted(programs):
        b = programs[name].get("budget") or {}
        rows.append((name,
                     _human(b.get("peak_live_bytes")),
                     _human(b.get("arg_bytes")),
                     _human(b.get("out_bytes")),
                     _human(b.get("collective_payload_bytes"))))
    widths = [max(len(r[i]) for r in rows) for i in range(5)]
    return "\n".join(
        "  ".join(c.ljust(widths[i]) for i, c in enumerate(r)).rstrip()
        for r in rows)


def _human(n) -> str:
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB"):
        if abs(n) < 1024:
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n:.1f}GiB"

"""The trace-audit program registry: which jitted step programs are
pinned, and at which CPU smoke geometries.

Every program the serve/train hot loops dispatch is registered here
with a builder that reconstructs the EXACT argument shapes/dtypes the
runtime passes, at a geometry small enough to trace in milliseconds on
the CPU backend.  ``python -m tpudp.analysis audit`` traces each one
with ``jax.make_jaxpr`` (trace only — nothing compiles or runs),
fingerprints the jaxpr, and diffs against ``tools/trace_lock.json``.

If the runtime changes a program's argument shapes or its body, the
audit fails and names the program — that is the point: a trace change
in a pinned hot path must be an explicit, reviewed event
(``audit --update`` + a committed lockfile diff), never a silent
recompile/new-transfer regression discovered on the pod.

Geometries are deliberately tiny and FIXED (they are part of the lock
identity); they only need to exercise the same code paths the smoke
tests pin, not realistic sizes.

Heavy imports (jax, the models) happen inside the builders so the lint
half of the package stays stdlib-importable.
"""

from __future__ import annotations

#: Files whose edits can change a registered trace.  Their sha256
#: digests ride in the lockfile: tools/bench_gaps.py compares them on
#: the watcher poll path (stdlib-only) to report a stale lock without
#: paying a jax import, and the tier-1 audit test requires them fresh
#: so `audit --update` provenance can't rot.
AUDIT_SOURCES = (
    "tpudp/serve/engine.py",
    "tpudp/serve/prefix_cache.py",
    "tpudp/serve/speculate.py",
    "tpudp/models/generate.py",
    "tpudp/models/gpt2.py",
    "tpudp/models/llama.py",
    "tpudp/ops/sampling.py",
    "tpudp/ops/attention.py",
    "tpudp/ops/paged_attention.py",
    "tpudp/ops/losses.py",
    "tpudp/train.py",
    "tpudp/parallel/sync.py",
    "tpudp/parallel/ring.py",
    "tpudp/parallel/pipeline.py",
    "tpudp/parallel/schedule.py",
    "tpudp/analysis/programs.py",
)

#: Which registered program covers each TRACE_COUNTS key the serve
#: layer can bump.  tests/test_analysis.py derives the key set from the
#: actual ``TRACE_COUNTS[...] += 1`` sites by AST, so a new jit that
#: satisfies the unregistered-jit rule (it bumps a counter) but skips
#: this registry fails the suite instead of dodging the trace lock.
TRACE_COUNTER_PROGRAMS = {
    "decode_step": "serve.decode_step",
    "verify_step": "serve.verify_step",
    "prefill_chunk": "serve.prefill_chunk",
    "sample_row": "serve.sample_row",
    "fused_decode": "serve.fused_decode",
    "decode_paged": "serve.decode_paged",
    "decode_paged_kernel": "serve.decode_paged_kernel",
    "verify_paged": "serve.verify_paged",
    "verify_paged_kernel": "serve.verify_paged_kernel",
    "prefill_paged": "serve.prefill_paged",
    "prefill_paged_kernel": "serve.prefill_paged_kernel",
    "fused_decode_paged": "serve.fused_decode_paged",
    "fused_decode_paged_kernel": "serve.fused_decode_paged_kernel",
    "fused_spec_decode": "serve.fused_spec_decode",
    "fused_spec_paged": "serve.fused_spec_paged",
    "fused_spec_paged_kernel": "serve.fused_spec_paged_kernel",
    "tree_verify": "serve.tree_verify",
    "tree_verify_paged": "serve.tree_verify_paged",
    "tree_verify_paged_kernel": "serve.tree_verify_paged_kernel",
    "prefix_block_in": "prefix.copy_block_in",
    "prefix_block_out": "prefix.copy_block_out",
    "draft_model": "serve.draft_model",
}

#: Donated ARGUMENT positions per program (name before the ``@``),
#: mirroring the runtime ``donate_argnums`` at each build site (the
#: same facts the use-after-donation rule tables in rules.py).  The
#: budget pass (tpudp/analysis/budget.py) uses these for its
#: donation-aware peak-live-bytes sweep: a donated buffer's storage is
#: reusable after its last read, a non-donated one is resident for the
#: whole call.
PROGRAM_DONATIONS = {
    "serve.decode_step": (0, 8),
    "serve.verify_step": (0, 9),
    "serve.prefill_chunk": (0,),
    "serve.fused_decode": (0, 11),
    "serve.fused_decode_stream": (0, 11),
    # Paged twins (Engine(kv_pages=N)): the shared page POOL donates in
    # place of the dense arena; the block table is host-authoritative
    # and never donated.  The kernel twins (Engine(paged_attn='kernel')
    # — the TPU default) share their einsum twins' signatures and
    # donation facts program-for-program.
    "serve.decode_paged": (0, 9),
    "serve.decode_paged_kernel": (0, 9),
    "serve.verify_paged": (0, 10),
    "serve.verify_paged_kernel": (0, 10),
    "serve.prefill_paged": (0,),
    "serve.prefill_paged_kernel": (0,),
    "serve.fused_decode_paged": (0, 12),
    "serve.fused_decode_paged_stream": (0, 12),
    "serve.fused_decode_paged_kernel": (0, 12),
    # On-device speculation (Engine(speculate_k=k, decode_fuse=N,
    # drafter=DraftModelDrafter(...))): the fused draft→verify→accept
    # while_loop donates the target arena/pool and the counters — the
    # draft model's KV arena is carry-local scratch, never an argument.
    # The tree-verify window donates like verify_step (its paged twin's
    # accepted-path commit is what makes rejected branches zero-write).
    "serve.fused_spec_decode": (0, 12),
    "serve.fused_spec_decode_stream": (0, 12),
    "serve.fused_spec_paged": (0, 13),
    "serve.fused_spec_paged_stream": (0, 13),
    "serve.fused_spec_paged_kernel": (0, 13),
    "serve.tree_verify": (0, 9),
    "serve.tree_verify_paged": (0, 10),
    "serve.tree_verify_paged_kernel": (0, 10),
    "serve.sample_row": (),
    "serve.draft_model": (),
    "prefix.copy_block_in": (0,),
    "prefix.copy_block_out": (1,),
    "train.step_single": (0,),
    "train.step_dp_allreduce": (0,),
    "train.step_dp_ring": (0,),
    # SDC-fingerprint twins donate identically: the fingerprint reads
    # the post-update params/opt_state VALUES before the donated input
    # buffers are reused — same aliasing facts, two extra u32 words.
    "train.step_single_sdc": (0,),
    "train.step_dp_allreduce_sdc": (0,),
    "train.eval_step": (),
    # MPMD pipeline steps (tpudp/parallel/schedule.py): the TrainState
    # (params + flat-sharded optimizer shards) donates, like every train
    # step; tokens/targets are host-fed each call.  The budget ledger
    # pins each geometry's per-stage ppermute sequence and peak_live.
    "train.pp_1f1b": (0,),
    "train.pp_1f1b_int": (0,),
    "train.pp_eval": (),
}

# Serve smoke geometry: 2 slots x 32 arena positions, chunk 8, k=3,
# fused window 4 — the same scale tests/test_serve.py exercises.
# "pages" is the PAGED twin's pool budget: 6 real pages (48 tokens)
# + 1 scratch page — deliberately BELOW the 2x32 = 64 tokens of one
# dense arena, so the committed budget ledger states the capacity
# claim at the smoke geometry: a paged engine serving the SAME slots
# persists fewer KV bytes than one dense arena, and a 2-model paged
# engine (one shared pool) persists far less than two (see
# tests/test_paged.py's ledger assertion).
SERVE = dict(vocab=64, seq=64, layers=2, heads=2, d_model=32,
             slots=2, max_len=32, chunk=8, k=3, blocks=4, fuse=4,
             pages=6)
# Draft-model smoke geometry for the fused speculative programs: a
# 1-layer model whose max_seq_len covers max_len + k (the Engine
# eligibility bound `dcfg.max_seq_len >= max_len + speculate_k`), its
# weights frozen into the fused program next to the target's.
DRAFT = dict(vocab=64, seq=64, layers=1, heads=2, d_model=16)
# Tree-verify smoke shape: fork2x2 (last token at node 0 → two branches
# of depth 2) — the smallest registered shape whose attention mask
# actually diverges (node 3 must NOT see nodes 1/2), matching
# tpudp.serve.speculate.TREE_SHAPES["fork2x2"].
TREE_PARENTS = (-1, 0, 1, 0, 3)
# Train smoke geometry: a tiny conv-free net over 8x8x3 inputs on the
# 8-virtual-device CPU mesh the tier-1 suite runs on.
TRAIN = dict(input=(8, 8, 3), classes=4, batch=8, devices=8)
# Pipeline smoke geometry (tpudp/parallel/schedule.py): the tiny GPT-2
# tests/test_schedule.py drives, on PP x DP sub-meshes of the same 8
# virtual devices.  Each (pp, dp, interleave) triple is its own pinned
# program — geometry is part of the unrolled schedule's compile key, so
# each gets its own ppermute sequence and budget ledger in the lock.
PIPELINE = dict(vocab=64, seq=32, layers=4, heads=2, d_model=32,
                batch=8, t=16, micro=2,
                geometries=((2, 2, 1), (4, 2, 1), (2, 2, 2)))


def _tiny_lm():
    import jax
    import jax.numpy as jnp

    from tpudp.models.gpt2 import GPT2, GPT2Config

    cfg = GPT2Config(vocab_size=SERVE["vocab"], max_seq_len=SERVE["seq"],
                     num_layers=SERVE["layers"], num_heads=SERVE["heads"],
                     d_model=SERVE["d_model"])
    model = GPT2(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32), train=False)["params"]
    return cfg, params


def _tiny_draft():
    import jax
    import jax.numpy as jnp

    from tpudp.models.gpt2 import GPT2, GPT2Config

    cfg = GPT2Config(vocab_size=DRAFT["vocab"], max_seq_len=DRAFT["seq"],
                     num_layers=DRAFT["layers"], num_heads=DRAFT["heads"],
                     d_model=DRAFT["d_model"])
    model = GPT2(cfg)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32), train=False)["params"]
    return cfg, params


def _serve_args():
    import jax.numpy as jnp
    import numpy as np

    from tpudp.models.generate import KVCache

    s, m, k = SERVE["slots"], SERVE["max_len"], SERVE["k"]
    cfg, params = _tiny_lm()
    cache = KVCache.zeros(cfg, s, m)
    host = dict(
        last=np.zeros(s, np.int32), lens=np.zeros(s, np.int32),
        active=np.zeros(s, bool), temps=np.zeros(s, np.float32),
        topk=np.zeros(s, np.int32), topp=np.ones(s, np.float32),
        keys=jnp.zeros((s, 2), jnp.uint32),
        window=np.zeros((s, k + 1), np.int32),
        ndraft=np.zeros(s, np.int32),
        hist=np.zeros((s, m), np.int32),
        tree=np.zeros((s, len(TREE_PARENTS)), np.int32),
        chunk=np.zeros((1, SERVE["chunk"]), np.int32),
        budgets=np.zeros(s, np.int32),
        eos=np.full(s, -1, np.int32),
        # OBS_DEVICE_COUNTERS accumulator (tpudp.obs zero-sync device
        # counters) — the shape the engine passes every decode/verify/
        # fused call.
        counts=jnp.zeros((5,), jnp.float32),
    )
    return cfg, params, cache, host


def build_programs() -> dict:
    """name → (fn, args): every pinned program, ready for
    ``jax.make_jaxpr(fn)(*args)``.  Insertion order is the lockfile
    order."""
    import numpy as np

    from tpudp.models.generate import KVCache

    programs: dict[str, tuple] = {}

    # -- serve step programs (frozen-weight jits, engine.py) -----------
    from tpudp.serve import engine as _engine

    cfg, params, cache, h = _serve_args()
    dcfg, dparams = _tiny_draft()
    (decode, verify, prefill, fused, fused_spec, tree_verify,
     decode_paged, verify_paged, prefill_paged, fused_paged,
     fused_spec_paged, tree_paged) = _engine._build_steps(
        cfg, params, draft=(dcfg, dparams))
    geo = f"s{SERVE['slots']}m{SERVE['max_len']}"
    programs[f"serve.decode_step@{geo}"] = (
        decode, (cache, h["last"], h["lens"], h["active"], h["temps"],
                 h["topk"], h["topp"], h["keys"], h["counts"]))
    programs[f"serve.verify_step@{geo}k{SERVE['k']}"] = (
        verify, (cache, h["window"], h["lens"], h["active"], h["ndraft"],
                 h["temps"], h["topk"], h["topp"], h["keys"],
                 h["counts"]))
    programs[f"serve.prefill_chunk@{geo}c{SERVE['chunk']}"] = (
        prefill, (cache, np.int32(0), h["chunk"], np.int32(0),
                  np.int32(SERVE["chunk"] - 1)))
    # Fused decode window, both variants: the stream twin pins the
    # ordered io_callback in its host-callback census, so ANY change to
    # the callback count inside the loop (a new host round trip — the
    # exact regression this program exists to prevent) fails the audit
    # naming the program.
    fused_args = (cache, h["last"], h["lens"], h["active"], h["temps"],
                  h["topk"], h["topp"], h["keys"], h["budgets"], h["eos"],
                  np.int32(-1), h["counts"])
    import functools

    programs[f"serve.fused_decode@{geo}n{SERVE['fuse']}"] = (
        functools.partial(fused, n_steps=SERVE["fuse"], stream=False),
        fused_args)
    programs[f"serve.fused_decode_stream@{geo}n{SERVE['fuse']}"] = (
        functools.partial(fused, n_steps=SERVE["fuse"], stream=True),
        fused_args)
    # On-device speculation (ISSUE 16): the fused draft→verify→accept
    # while_loop — both drafters' weights frozen in, the slot histories
    # in, k+1-wide verify windows and per-slot PRNG chains advanced
    # in-carry.  Pinned in BOTH stream variants like the plain fused
    # window: a new host callback inside the speculative loop (the
    # regression class this whole program deletes) fails the audit by
    # name.
    spec_args = (cache, h["hist"], h["last"], h["lens"], h["active"],
                 h["temps"], h["topk"], h["topp"], h["keys"],
                 h["budgets"], h["eos"], np.int32(-1), h["counts"])
    sgeo = f"{geo}k{SERVE['k']}n{SERVE['fuse']}"
    programs[f"serve.fused_spec_decode@{sgeo}"] = (
        functools.partial(fused_spec, n_draft_k=SERVE["k"],
                          n_steps=SERVE["fuse"], stream=False), spec_args)
    programs[f"serve.fused_spec_decode_stream@{sgeo}"] = (
        functools.partial(fused_spec, n_draft_k=SERVE["k"],
                          n_steps=SERVE["fuse"], stream=True), spec_args)
    # The speculative TREE window (Engine(speculate_tree=...)): one
    # tree-masked forward over fork2x2's five nodes, accepted-path-only
    # commit.  The parents tuple is static (part of the compile key and
    # the lock identity, like n_steps on the fused window).
    tgeo = f"{geo}t{len(TREE_PARENTS)}"
    tree_args = (cache, h["tree"], h["lens"], h["active"], h["ndraft"],
                 h["temps"], h["topk"], h["topp"], h["keys"], h["counts"])
    programs[f"serve.tree_verify@{tgeo}"] = (
        functools.partial(tree_verify, parents=TREE_PARENTS), tree_args)
    # Paged twins (Engine(kv_pages=N)): same math read through per-slot
    # block tables into ONE shared page pool (+1 trailing scratch page)
    # — since the gather-free rework, THROUGH the table inside the
    # attention contraction (tpudp.ops.paged_attention), with the new
    # token's K/V committed straight into its page.  Pinning them locks
    # the indirection — a new host transfer or callback inside the
    # paged hot loop fails the audit by name — and gives the budget
    # pass the paged programs' peak_live_bytes for the capacity ledger
    # (tests pin the gather-free values strictly below the PR 13
    # gather-based ones).
    n_pages = SERVE["pages"]
    pool = KVCache.zeros(cfg, n_pages + 1, SERVE["chunk"])
    table = np.zeros((SERVE["slots"], SERVE["max_len"] // SERVE["chunk"]),
                     np.int32)
    pgeo2 = f"{geo}p{n_pages}"
    programs[f"serve.decode_paged@{pgeo2}"] = (
        decode_paged, (pool, table, h["last"], h["lens"], h["active"],
                       h["temps"], h["topk"], h["topp"], h["keys"],
                       h["counts"]))
    programs[f"serve.verify_paged@{pgeo2}k{SERVE['k']}"] = (
        verify_paged, (pool, table, h["window"], h["lens"], h["active"],
                       h["ndraft"], h["temps"], h["topk"], h["topp"],
                       h["keys"], h["counts"]))
    programs[f"serve.prefill_paged@{pgeo2}c{SERVE['chunk']}"] = (
        prefill_paged, (pool, table[0], h["chunk"], np.int32(0),
                        np.int32(SERVE["chunk"] - 1)))
    # Both stream variants, like the dense fused window: the stream
    # twin pins the ordered io_callback in its census, so a host
    # round-trip change inside the PAGED loop fails the audit by name
    # too (kv_pages + fuse_stream is a legal engine configuration).
    fused_paged_args = (
        pool, table, h["last"], h["lens"], h["active"], h["temps"],
        h["topk"], h["topp"], h["keys"], h["budgets"], h["eos"],
        np.int32(-1), h["counts"])
    programs[f"serve.fused_decode_paged@{pgeo2}n{SERVE['fuse']}"] = (
        functools.partial(fused_paged, n_steps=SERVE["fuse"], stream=False),
        fused_paged_args)
    programs[f"serve.fused_decode_paged_stream@{pgeo2}n{SERVE['fuse']}"] = (
        functools.partial(fused_paged, n_steps=SERVE["fuse"], stream=True),
        fused_paged_args)
    # Paged speculative twins: same fused draft/verify/accept carry and
    # tree-verify math through the block-table indirection — the tree
    # twin's accepted-path commit is the zero-write-on-reject claim the
    # byte-diff test pins, so its trace (and any new transfer in it) is
    # locked here.
    spec_paged_args = (
        pool, table, h["hist"], h["last"], h["lens"], h["active"],
        h["temps"], h["topk"], h["topp"], h["keys"], h["budgets"],
        h["eos"], np.int32(-1), h["counts"])
    programs[f"serve.fused_spec_paged@{pgeo2}k{SERVE['k']}n{SERVE['fuse']}"] = (
        functools.partial(fused_spec_paged, n_draft_k=SERVE["k"],
                          n_steps=SERVE["fuse"], stream=False),
        spec_paged_args)
    programs[f"serve.fused_spec_paged_stream@{pgeo2}k{SERVE['k']}n{SERVE['fuse']}"] = (
        functools.partial(fused_spec_paged, n_draft_k=SERVE["k"],
                          n_steps=SERVE["fuse"], stream=True),
        spec_paged_args)
    programs[f"serve.tree_verify_paged@{pgeo2}t{len(TREE_PARENTS)}"] = (
        functools.partial(tree_paged, parents=TREE_PARENTS),
        (pool, table, h["tree"], h["lens"], h["active"], h["ndraft"],
         h["temps"], h["topk"], h["topp"], h["keys"], h["counts"]))
    # The Pallas kernel twins (Engine(paged_attn='kernel') — the TPU
    # default): same signatures/donations as their einsum twins
    # program-for-program, but the attention contractions run the
    # hot-path kernels — the paged-decode kernel, the flash-window
    # verify/prefill kernel, kernels dispatched inside the fused loop
    # bodies, and the tree-verify kernel — each with the block table as
    # scalar prefetch.  Pinned so a kernel-body change (or a new
    # callback/transfer around one) is a named, reviewed event like
    # every other hot-path trace.  The audit captures on forced CPU, so
    # the kernels trace in interpret mode — host-independent like the
    # rest of the lock.
    (_, verify_k, prefill_k, fused_k, fused_spec_k,
     tree_k) = _engine._build_steps(cfg, params, paged_attn="kernel",
                                    draft=(dcfg, dparams))[6:]
    decode_paged_kernel = _engine._build_steps(cfg, params,
                                               paged_attn="kernel")[6]
    programs[f"serve.decode_paged_kernel@{pgeo2}"] = (
        decode_paged_kernel,
        (pool, table, h["last"], h["lens"], h["active"], h["temps"],
         h["topk"], h["topp"], h["keys"], h["counts"]))
    programs[f"serve.verify_paged_kernel@{pgeo2}k{SERVE['k']}"] = (
        verify_k, (pool, table, h["window"], h["lens"], h["active"],
                   h["ndraft"], h["temps"], h["topk"], h["topp"],
                   h["keys"], h["counts"]))
    programs[f"serve.prefill_paged_kernel@{pgeo2}c{SERVE['chunk']}"] = (
        prefill_k, (pool, table[0], h["chunk"], np.int32(0),
                    np.int32(SERVE["chunk"] - 1)))
    programs[f"serve.fused_decode_paged_kernel@{pgeo2}n{SERVE['fuse']}"] = (
        functools.partial(fused_k, n_steps=SERVE["fuse"], stream=False),
        fused_paged_args)
    programs[
        f"serve.fused_spec_paged_kernel@{pgeo2}k{SERVE['k']}n{SERVE['fuse']}"
    ] = (functools.partial(fused_spec_k, n_draft_k=SERVE["k"],
                           n_steps=SERVE["fuse"], stream=False),
         spec_paged_args)
    programs[f"serve.tree_verify_paged_kernel@{pgeo2}t{len(TREE_PARENTS)}"] = (
        functools.partial(tree_k, parents=TREE_PARENTS),
        (pool, table, h["tree"], h["lens"], h["active"], h["ndraft"],
         h["temps"], h["topk"], h["topp"], h["keys"], h["counts"]))

    programs["serve.sample_row@v%d" % SERVE["vocab"]] = (
        _engine._sample_row,
        (np.zeros((1, SERVE["vocab"]), np.float32), np.float32(0.0),
         np.int32(0), np.float32(1.0), h["keys"][0]))

    # -- prefix-cache block copies (prefix_cache.py) -------------------
    from tpudp.serve import prefix_cache as _prefix

    pool = KVCache.zeros(cfg, SERVE["blocks"], SERVE["chunk"])
    pgeo = f"{geo}b{SERVE['blocks']}"
    programs[f"prefix.copy_block_in@{pgeo}"] = (
        _prefix.copy_block_in,
        (cache, pool, np.int32(0), np.int32(0), np.int32(0)))
    programs[f"prefix.copy_block_out@{pgeo}"] = (
        _prefix.copy_block_out,
        (cache, pool, np.int32(0), np.int32(0), np.int32(0)))

    # -- speculative drafter program (speculate.py) --------------------
    from tpudp.serve.speculate import _draft_greedy

    ctx = 16
    programs[f"serve.draft_model@ctx{ctx}k{SERVE['k']}"] = (
        lambda p, t, n: _draft_greedy(cfg, p, t, n, SERVE["k"]),
        (params, np.zeros((1, ctx), np.int32), np.int32(8)))

    # -- train/eval step programs (train.py) ---------------------------
    import flax.linen as nn
    import jax.numpy as jnp

    from tpudp.mesh import make_mesh
    from tpudp.train import (init_state, make_eval_step, make_optimizer,
                             make_train_step)

    class _TinyNet(nn.Module):
        """Minimal image classifier — enough structure for the fused
        fwd+loss+bwd+sync+update step to have its real shape."""

        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(16, name="fc1")(x))
            return nn.Dense(TRAIN["classes"], name="fc2")(x)

    model = _TinyNet()
    tx = make_optimizer()
    state = init_state(model, tx, input_shape=(1, *TRAIN["input"]))
    b = TRAIN["batch"]
    images = jnp.zeros((b, *TRAIN["input"]), jnp.float32)
    labels = jnp.zeros((b,), jnp.int32)
    weights = jnp.ones((b,), jnp.float32)

    programs["train.step_single@tiny"] = (
        make_train_step(model, tx, None), (state, images, labels))
    mesh = make_mesh(TRAIN["devices"])
    for sync in ("allreduce", "ring"):
        programs[f"train.step_dp_{sync}@mesh{TRAIN['devices']}"] = (
            make_train_step(model, tx, mesh, sync), (state, images, labels))
    programs[f"train.eval_step@mesh{TRAIN['devices']}"] = (
        make_eval_step(model, mesh), (state, images, labels, weights))
    # SDC-fingerprint twins (tpudp/sdc.py): the SAME fused step with
    # the TrainState's ``sdc_fp`` slot allocated (init_state(
    # track_sdc=True)) — the u32 checksum of the post-update params +
    # optimizer bits rides the step, structure-gated at trace time.
    # Pinned separately so growth in the corruption detector's traced
    # footprint is a lockfile diff, not silent drift.
    sdc_state = init_state(model, tx, input_shape=(1, *TRAIN["input"]),
                           track_sdc=True)
    programs["train.step_single_sdc@tiny"] = (
        make_train_step(model, tx, None), (sdc_state, images, labels))
    programs[f"train.step_dp_allreduce_sdc@mesh{TRAIN['devices']}"] = (
        make_train_step(model, tx, mesh, "allreduce"),
        (sdc_state, images, labels))

    # -- MPMD pipeline programs (parallel/schedule.py) ------------------
    import jax

    from tpudp.mesh import make_mesh_nd
    from tpudp.models.gpt2 import gpt2_small
    from tpudp.parallel.schedule import (make_pipeline_eval_step,
                                         make_pipeline_train_step)

    lm = gpt2_small(vocab_size=PIPELINE["vocab"],
                    max_seq_len=PIPELINE["seq"],
                    num_layers=PIPELINE["layers"],
                    num_heads=PIPELINE["heads"],
                    d_model=PIPELINE["d_model"])
    lm_tx = make_optimizer(learning_rate=0.01)
    lm_state = init_state(lm, lm_tx, input_shape=(1, 8))
    toks = jnp.zeros((PIPELINE["batch"], PIPELINE["t"]), jnp.int32)
    lm_w = jnp.ones((PIPELINE["batch"],), jnp.float32)
    eval_geo = None
    for pp, dp, il in PIPELINE["geometries"]:
        pp_mesh = make_mesh_nd({"data": dp, "pipe": pp},
                               devices=jax.devices()[: dp * pp])
        pp_state, pp_step = make_pipeline_train_step(
            lm, lm_tx, pp_mesh, lm_state,
            n_microbatches=PIPELINE["micro"], interleave=il)
        fam = "train.pp_1f1b_int" if il > 1 else "train.pp_1f1b"
        geo = (f"pp{pp}dp{dp}m{PIPELINE['micro']}"
               + (f"v{il}" if il > 1 else "")
               + f"L{PIPELINE['layers']}")
        programs[f"{fam}@{geo}"] = (pp_step, (pp_state, toks, toks))
        if eval_geo is None:
            # Eval twin once, at the first (smallest) geometry: the
            # forward-only tick program shares its transport with the
            # train program, so one pin covers the family.
            eval_geo = (make_pipeline_eval_step(
                lm, pp_mesh, pp_state, n_microbatches=PIPELINE["micro"],
                interleave=il), (pp_state, toks, toks, lm_w))
            programs[f"train.pp_eval@{geo}"] = eval_geo
    return programs

"""Bounded path enumeration over function ASTs — the control-flow
substrate of the protocol verifier (:mod:`tpudp.analysis.protocol`).

Python functions are structured (no goto), so instead of a generic
basic-block CFG this enumerates *paths* directly from the AST: every
acyclic route through a function body, each recording

  * ``seq`` — the ordered collective *sites* the path issues (site = a
    call the caller classified as a cross-host rendezvous, directly or
    through an interprocedural summary),
  * ``decisions`` — the ordered ``(guard_id, arm)`` choices taken at
    every branch point (``if``/ternary, loop entry, ``except`` arm),
  * ``exit`` — how the path leaves the function (``fall``, ``return``,
    ``raise``), with the exiting statement for anchoring findings.

The verifier then partitions paths by their decision prefix and
compares collective sequences *across the arms of each guard* — the
path-sensitive generalization of the linter's lexical
divergent-collective rule.

Loop abstraction: every loop contributes a guard with two arms — zero
iterations or exactly one (``while True`` only the one).  This is the
abstraction that makes enumeration finite; it is deliberately lenient
(hosts that iterate the *same* number of times always compare equal)
and still catches the class that matters: a loop whose trip count is
host-local and whose body holds a rendezvous.

Exception abstraction: each ``except`` arm is a guard alternative
entered with *none* of the try body executed (the earliest-raise
approximation).  A handler whose entire body is a bare ``raise`` is
transparent — re-raising is propagation, not a protocol decision.

Pure stdlib, like the rest of the lint half.
"""

from __future__ import annotations

import ast
import dataclasses

#: Path-explosion bounds.  On overflow enumeration stops adding new
#: alternatives (keeps the first arms); the verifier reports the
#: function as truncated so silent under-coverage is visible.
MAX_PATHS = 2048
MAX_SEQ = 64


@dataclasses.dataclass(frozen=True)
class Site:
    """One collective call site."""

    index: int
    label: str
    node: ast.AST

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 1)


@dataclasses.dataclass(frozen=True)
class Guard:
    """One branch point: an ``if``/ternary test, a loop entry, or an
    ``except`` arm set.  ``kind`` is 'if' | 'loop' | 'except';
    ``cls``/``reason`` are the caller's host-uniformity classification
    of the predicate."""

    gid: int
    kind: str
    node: ast.AST
    cls: str      # 'uniform' | 'host-local'
    reason: str

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 1)


@dataclasses.dataclass(frozen=True)
class Path:
    seq: tuple            # of site indices, in issue order
    decisions: tuple      # of (gid, arm)
    exit: str             # 'fall' | 'return' | 'raise' | 'break' | 'continue'
    exit_node: ast.AST | None = None


class _Partial:
    __slots__ = ("seq", "decisions")

    def __init__(self, seq=(), decisions=()):
        self.seq = seq
        self.decisions = decisions

    def add_site(self, idx) -> bool:
        """False when the sequence bound was hit (site dropped) — the
        enumerator marks itself truncated so the caller can report the
        partial coverage instead of silently under-verifying."""
        if len(self.seq) < MAX_SEQ:
            self.seq = self.seq + (idx,)
            return True
        return False

    def fork(self, gid, arm):
        p = _Partial(self.seq, self.decisions + ((gid, arm),))
        return p

    def finish(self, exit_kind, node=None):
        return Path(self.seq, self.decisions, exit_kind, node)


class PathEnumerator:
    """Enumerate paths through one function.

    The caller provides two callbacks:

      * ``site_label(call_node) -> str | None`` — non-None when the
        call is a rendezvous (directly a collective, or a summary says
        the callee transitively issues one); the string is the sequence
        token.
      * ``classify(expr_node) -> (cls, reason)`` — host-uniformity of a
        branch predicate / loop iterable ('uniform' or 'host-local').
    """

    def __init__(self, site_label, classify):
        self._site_label = site_label
        self._classify = classify
        self.sites: list[Site] = []
        self.guards: list[Guard] = []
        self.truncated = False
        self._site_by_node: dict[int, int] = {}

    # -- construction ---------------------------------------------------

    def _site(self, node, label) -> int:
        key = id(node)
        if key not in self._site_by_node:
            self._site_by_node[key] = len(self.sites)
            self.sites.append(Site(len(self.sites), label, node))
        return self._site_by_node[key]

    def _guard(self, kind, node) -> Guard:
        cls, reason = ("uniform", "")
        if kind == "except":
            cls, reason = "host-local", "exception occurrence is per-host"
        else:
            test = node.test if isinstance(
                node, (ast.If, ast.IfExp, ast.While)) else getattr(
                    node, "iter", node)
            cls, reason = self._classify(test)
        g = Guard(len(self.guards), kind, node, cls, reason)
        self.guards.append(g)
        return g

    # -- expression scanning -------------------------------------------

    def _expr_sites(self, expr, partials):
        """Append the collective sites an expression issues, in source
        order, to every partial.  EVERY collective-bearing ternary
        forks (their arms are real control flow — one suffices to
        decide rendezvous entry per-host); everything else is scanned
        linearly."""
        if expr is None:
            return partials
        ternaries = [n for n in ast.walk(expr) if isinstance(n, ast.IfExp)
                     and self._has_site(n)]
        if not ternaries:
            self._scan_linear(expr, partials)
            return partials
        # outermost collective-bearing ternaries, in source order;
        # ones nested inside another are handled by the outer's arms
        all_inside = set()
        for t in ternaries:
            for sub in ast.walk(t):
                if sub is not t:
                    all_inside.add(id(sub))
        top = sorted((t for t in ternaries if id(t) not in all_inside),
                     key=lambda n: (n.lineno, n.col_offset))
        skip = set()
        for t in top:
            skip.update(map(id, ast.walk(t)))
        self._scan_linear(expr, partials, skip=skip)
        for t in top:
            partials = self._expr_sites(t.test, partials)
            guard = self._guard("if", t)
            out = []
            for arm, sub in ((0, t.body), (1, t.orelse)):
                forked = [p.fork(guard.gid, arm) for p in partials]
                out.extend(self._expr_sites(sub, forked))
            partials = self._cap(out)
        return partials

    def _has_site(self, expr) -> bool:
        return any(isinstance(n, ast.Call)
                   and self._site_label(n) is not None
                   for n in ast.walk(expr))

    def _scan_linear(self, expr, partials, skip=frozenset()):
        # EVALUATION order, not source order: arguments evaluate before
        # their call (`f(g(x))` issues g's rendezvous first), so sites
        # are emitted post-order.
        def visit(node):
            if id(node) in skip:
                return
            for child in ast.iter_child_nodes(node):
                visit(child)
            if isinstance(node, ast.Call):
                label = self._site_label(node)
                if label is not None:
                    idx = self._site(node, label)
                    for p in partials:
                        if not p.add_site(idx):
                            self.truncated = True

        visit(expr)

    def _cap(self, partials):
        if len(partials) > MAX_PATHS:
            self.truncated = True
            return partials[:MAX_PATHS]
        return partials

    # -- transparency ---------------------------------------------------

    def _walk_skip_defs(self, stmts):
        stack = list(stmts)
        while stack:
            n = stack.pop()
            yield n
            for c in ast.iter_child_nodes(n):
                if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                    continue
                stack.append(c)

    def _transparent(self, bodies, allow_break=False) -> bool:
        """A branch region with no rendezvous sites and no control-flow
        exits contributes nothing to any path's collective sequence —
        skipping the fork entirely keeps path counts linear in the
        number of RELEVANT branches (a 600-line CLI main would
        otherwise blow MAX_PATHS on branches the verifier does not care
        about).  ``allow_break``: break/continue are internal to a loop
        being tested as one unit, but inside an If's arms they redirect
        flow around later sites and must keep the fork."""
        for body in bodies:
            for n in self._walk_skip_defs(body):
                if isinstance(n, (ast.Return, ast.Raise)):
                    return False
                if not allow_break and isinstance(
                        n, (ast.Break, ast.Continue)):
                    return False
                if isinstance(n, ast.Call) \
                        and self._site_label(n) is not None:
                    return False
        return True

    # -- statement walk -------------------------------------------------

    def run(self, fn: ast.AST) -> list[Path]:
        finished, falling = self._block(fn.body, [_Partial()])
        return finished + [p.finish("fall") for p in falling]

    def _block(self, body, partials):
        finished = []
        cur = partials
        for stmt in body:
            if not cur:
                break
            done, cur = self._stmt(stmt, cur)
            finished.extend(done)
            cur = self._cap(cur)
        return finished, cur

    def _stmt(self, stmt, partials):
        """Returns (finished_paths, continuing_partials)."""
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return [], partials  # nested defs analyzed on their own
        if isinstance(stmt, ast.Return):
            partials = self._expr_sites(stmt.value, partials)
            return [p.finish("return", stmt) for p in partials], []
        if isinstance(stmt, ast.Raise):
            partials = self._expr_sites(stmt.exc, partials)
            return [p.finish("raise", stmt) for p in partials], []
        if isinstance(stmt, (ast.Break, ast.Continue)):
            kind = "break" if isinstance(stmt, ast.Break) else "continue"
            return [p.finish(kind, stmt) for p in partials], []
        if isinstance(stmt, ast.If):
            partials = self._expr_sites(stmt.test, partials)
            if self._transparent([stmt.body, stmt.orelse]):
                return [], partials  # no sites, no exits: nothing to fork
            guard = self._guard("if", stmt)
            finished, out = [], []
            for arm, body in ((0, stmt.body), (1, stmt.orelse)):
                forked = [p.fork(guard.gid, arm) for p in partials]
                if body:
                    done, cont = self._block(body, forked)
                    finished.extend(done)
                    out.extend(cont)
                else:
                    out.extend(forked)
            return finished, self._cap(out)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(stmt, partials)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, partials)
        if isinstance(stmt, ast.Match):
            return self._match(stmt, partials)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                partials = self._expr_sites(item.context_expr, partials)
            return self._block(stmt.body, partials)
        # plain statement: scan its expressions for sites
        for field in ("value", "test", "exc"):
            sub = getattr(stmt, field, None)
            if isinstance(sub, ast.AST):
                partials = self._expr_sites(sub, partials)
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            pass  # value already scanned above
        elif isinstance(stmt, ast.Expr):
            pass
        return [], partials

    def _loop(self, stmt, partials):
        always = (isinstance(stmt, ast.While)
                  and isinstance(stmt.test, ast.Constant)
                  and bool(stmt.test.value))
        if isinstance(stmt, ast.While):
            partials = self._expr_sites(stmt.test, partials)
        else:
            partials = self._expr_sites(stmt.iter, partials)
        if self._transparent([stmt.body], allow_break=True):
            return [], partials  # site-free, exit-free loop body
        guard = self._guard("loop", stmt)
        finished, out = [], []
        arms = ((1, True),) if always else ((0, False), (1, True))
        for arm, enter in arms:
            forked = [p.fork(guard.gid, arm) for p in partials]
            if not enter:
                out.extend(forked)
                continue
            done, cont = self._block(stmt.body, forked)
            for path in done:
                if path.exit in ("break", "continue"):
                    # loop exits after this iteration (one-iteration
                    # abstraction): resume after the loop
                    out.append(_Partial(path.seq, path.decisions))
                else:
                    finished.append(path)
            out.extend(cont)  # body fell through -> loop exits
        return finished, self._cap(out)

    def _match(self, stmt, partials):
        """``match`` arms are a branch on the subject, same as an If —
        collectives under case arms must be visible (silent
        under-coverage is this module's cardinal sin)."""
        partials = self._expr_sites(stmt.subject, partials)
        if self._transparent([c.body for c in stmt.cases]):
            return [], partials
        cls, reason = self._classify(stmt.subject)
        guard = Guard(len(self.guards), "if", stmt, cls, reason)
        self.guards.append(guard)
        finished, out = [], []
        wildcard = any(
            isinstance(c.pattern, ast.MatchAs) and c.pattern.pattern
            is None and c.guard is None for c in stmt.cases)
        for arm, case in enumerate(stmt.cases):
            forked = [p.fork(guard.gid, arm) for p in partials]
            if case.guard is not None:
                forked = self._expr_sites(case.guard, forked)
            done, cont = self._block(case.body, forked)
            finished.extend(done)
            out.extend(cont)
        if not wildcard:  # the no-case-matched fall-through arm
            out.extend(p.fork(guard.gid, len(stmt.cases))
                       for p in partials)
        return finished, self._cap(out)

    def _try(self, stmt, partials):
        bodies = [stmt.body, stmt.orelse, stmt.finalbody] + [
            h.body for h in stmt.handlers]
        if self._transparent(bodies):
            return [], partials  # no rendezvous anywhere in the region
        finished, out = [], []
        guard = self._guard("except", stmt)
        # arm 0: no exception — body, else, (finally via fallthrough)
        normal = [p.fork(guard.gid, 0) for p in partials]
        done, cont = self._block(stmt.body + list(stmt.orelse), normal)
        for path in done:
            # a raise inside a guarded try body is (assumed) caught by
            # the handlers — the handler arms below model it; keeping it
            # as a function exit would fabricate early-exit divergences
            if path.exit == "raise" and stmt.handlers:
                continue
            finished.append(path)
        out.extend(cont)
        for i, handler in enumerate(stmt.handlers):
            if (len(handler.body) == 1
                    and isinstance(handler.body[0], ast.Raise)
                    and handler.body[0].exc is None):
                continue  # bare re-raise: propagation, not a decision
            forked = [p.fork(guard.gid, i + 1) for p in partials]
            done, cont = self._block(handler.body, forked)
            finished.extend(done)
            out.extend(cont)
        if stmt.finalbody:
            # the finally runs on EVERY exit of the region — a
            # rendezvous in it is issued by return/raise paths too
            # (dropping it would fabricate early-exit findings on
            # barrier-in-finally cleanup)
            refinished = []
            for path in finished:
                done, cont = self._block(
                    stmt.finalbody,
                    [_Partial(path.seq, path.decisions)])
                refinished.extend(done)  # finally's own exits win
                refinished.extend(p.finish(path.exit, path.exit_node)
                                  for p in cont)
            finished = refinished
            done, out = self._block(stmt.finalbody, out)
            finished.extend(done)
        return finished, self._cap(out)

"""Trace-stability auditor: jaxpr fingerprints + transfer census vs a
committed lockfile.

For every program in :mod:`tpudp.analysis.programs` this traces the
function (``jax.make_jaxpr`` — trace only, nothing compiles) and
records:

  * ``fingerprint`` — sha256 of the canonicalized jaxpr text (memory
    addresses scrubbed).  Any change to the traced computation —
    including one that would force a recompile at fixed shapes —
    changes it.
  * ``collectives`` — the ordered sequence of collective primitives
    (psum/ppermute/all_gather/...), recursively through scan/cond/pjit
    sub-jaxprs.  This is the static twin of PR 7's runtime vote: two
    hosts tracing different collective sequences deadlock a pod.
  * ``callbacks`` / ``transfers`` — host-callback and device_put
    primitive counts: a new host round trip inside a step program is a
    latency regression serve_bench would only catch after the fact.
  * ``eqns`` — total equation count (a coarse program-size canary).

``compare`` diffs a capture against the lockfile and names the
offending program and WHAT changed.  Source digests (sha256 of
AUDIT_SOURCES) also ride in the lock so stdlib-only tooling
(tools/bench_gaps.py) can flag a stale lock without importing jax; the
tier-1 test keeps them fresh, so every hot-path edit forces an
explicit ``audit --update`` + lockfile diff in review.

Module import is jax-free; jax loads inside the functions.
"""

from __future__ import annotations

import hashlib
import json
import os
import re

#: Bumped whenever the lock schema changes shape (v2: per-program
#: "budget" ledgers + top-level "geometry"), so an old committed lock
#: fails with the version diagnostic and its --update advice instead
#: of a misleading field-level mismatch.
LOCK_VERSION = 2

#: Substrings identifying collective primitives (matched against
#: primitive names so jax renames like psum→psum2 keep being counted
#: — the recorded name is always the real one).
COLLECTIVE_PRIM_PARTS = ("psum", "pmax", "pmin", "ppermute", "pbroadcast",
                         "all_gather", "all_to_all", "reduce_scatter",
                         "pgather")
CALLBACK_PRIM_PARTS = ("callback",)
TRANSFER_PRIM_NAMES = {"device_put", "copy"}

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


# -- stdlib half (bench_gaps-safe) ------------------------------------

def source_digests(root: str | None = None) -> dict[str, str]:
    from .programs import AUDIT_SOURCES

    root = root or repo_root()
    out = {}
    for rel in AUDIT_SOURCES:
        path = os.path.join(root, rel)
        h = hashlib.sha256()
        try:
            with open(path, "rb") as f:
                h.update(f.read())
            out[rel] = h.hexdigest()
        except OSError:
            out[rel] = "MISSING"
    return out


def load_lock(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def write_lock(path: str, capture_result: dict) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(capture_result, f, indent=1, sort_keys=True)
        f.write("\n")


def sources_stale(lock_path: str, root: str | None = None) -> list[str]:
    """Pinned source files whose digest no longer matches the lock —
    pure stdlib, usable from the watcher poll path.  A missing/
    unreadable lock returns every pinned source."""
    try:
        lock = load_lock(lock_path)
    except (OSError, json.JSONDecodeError):
        from .programs import AUDIT_SOURCES
        return list(AUDIT_SOURCES)
    recorded = lock.get("sources", {})
    current = source_digests(root)
    return sorted(set(
        [rel for rel, digest in current.items()
         if recorded.get(rel) != digest]
        + [rel for rel in recorded if rel not in current]))


# -- jax half ----------------------------------------------------------

def force_smoke_backend():
    """Pin the CPU backend with 8 virtual devices BEFORE first use, so
    the audit geometry is identical on every host (laptop, CI, TPU VM).
    Raises RuntimeError if another backend already initialized."""
    flag = "--xla_force_host_platform_device_count=8"
    if flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag)
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already up — verified below
    if jax.default_backend() != "cpu":
        raise RuntimeError(
            "the trace audit must run on the CPU smoke backend, but "
            f"backend {jax.default_backend()!r} is already initialized — "
            "run `python -m tpudp.analysis audit` in a fresh process")
    if jax.device_count() < 8:
        raise RuntimeError(
            "the trace audit needs >= 8 virtual CPU devices for the mesh "
            "geometries; launch with XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 (a fresh "
            "`python -m tpudp.analysis audit` sets this itself)")
    return jax


def _census(jaxpr, acc) -> None:
    from jax.core import Jaxpr

    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        name = eqn.primitive.name
        acc["eqns"] += 1
        if any(p in name for p in COLLECTIVE_PRIM_PARTS):
            acc["collectives"].append(name)
        if any(p in name for p in CALLBACK_PRIM_PARTS):
            acc["callbacks"] += 1
        if name in TRANSFER_PRIM_NAMES:
            acc["transfers"] += 1
        for v in eqn.params.values():
            vs = v if isinstance(v, (tuple, list)) else (v,)
            for sub in vs:
                if isinstance(sub, Jaxpr) or hasattr(sub, "jaxpr"):
                    _census(sub, acc)


def fingerprint(fn, args, donate_argnums=()) -> dict:
    """Trace ``fn(*args)`` and reduce the jaxpr to its lock record —
    fingerprint + census + the static resource ledger
    (:mod:`tpudp.analysis.budget`, donation-aware via
    ``donate_argnums``)."""
    import jax

    from . import budget as _budget

    closed = jax.make_jaxpr(fn)(*args)
    text = _ADDR_RE.sub("0xX", str(closed))
    acc = {"eqns": 0, "collectives": [], "callbacks": 0, "transfers": 0}
    _census(closed, acc)
    return {
        "fingerprint": hashlib.sha256(text.encode()).hexdigest(),
        "eqns": acc["eqns"],
        "collectives": acc["collectives"],
        "callbacks": acc["callbacks"],
        "transfers": acc["transfers"],
        "budget": _budget.ledger(
            closed, _budget.donated_flat_indices(args, donate_argnums)),
    }


def geometry() -> dict:
    """The capture environment's identity: the lock is only comparable
    under the same jax backend/device-count (the audit pins cpu+8
    virtual devices precisely so this never varies between hosts)."""
    import jax

    return {"platform": jax.default_backend(),
            "devices": jax.device_count()}


def capture(programs: dict | None = None) -> dict:
    """Trace every registered program → a lockfile-shaped dict."""
    import jax

    from .programs import PROGRAM_DONATIONS

    if programs is None:
        from .programs import build_programs
        programs = build_programs()
    return {
        "version": LOCK_VERSION,
        "jax": jax.__version__,
        "geometry": geometry(),
        "programs": {
            name: fingerprint(
                fn, args,
                PROGRAM_DONATIONS.get(name.split("@")[0], ()))
            for name, (fn, args) in programs.items()},
        "sources": source_digests(),
    }


def identity_skew(lock: dict, current: dict) -> list[str]:
    """NAMED version/geometry-skew diagnostics, checked BEFORE any
    per-program diff: a different jax re-prints every jaxpr (and a
    different device count re-derives every ledger), so reporting that
    as thirteen per-program mismatches would bury the one actual
    cause.  Shared by ``compare`` and the ``budget`` subcommand — any
    consumer diffing lock records against a live capture must gate on
    this first."""
    problems: list[str] = []
    if lock.get("jax") != current.get("jax"):
        problems.append(
            f"jax version skew: lock was generated under jax "
            f"{lock.get('jax')}, this environment runs "
            f"{current.get('jax')} — jaxpr text is only comparable "
            f"within one jax version; regenerate with --update under "
            f"the pinned toolchain")
    elif lock.get("geometry") != current.get("geometry"):
        problems.append(
            f"capture geometry skew: lock was generated on "
            f"{lock.get('geometry')}, this capture ran on "
            f"{current.get('geometry')} — device count/backend are part "
            f"of the lock identity (the audit pins cpu+8 virtual "
            f"devices); rerun `python -m tpudp.analysis audit` in a "
            f"fresh process, or --update if the pinned geometry changed")
    return problems


def compare(lock: dict, current: dict) -> list[str]:
    """Human-readable mismatches, each naming the offending program."""
    problems: list[str] = []
    if lock.get("version") != current["version"]:
        problems.append(
            f"lock version {lock.get('version')} != auditor version "
            f"{current['version']} — regenerate with --update")
        return problems
    skew = identity_skew(lock, current)
    if skew:
        problems.extend(skew)
        return problems
    locked = lock.get("programs", {})
    live = current["programs"]
    for name in locked:
        if name not in live:
            problems.append(
                f"{name}: in the lockfile but no longer registered — a "
                f"pinned hot-path program disappeared (deliberate removal "
                f"=> --update)")
    for name, rec in live.items():
        old = locked.get(name)
        if old is None:
            problems.append(
                f"{name}: registered but not in the lockfile — run "
                f"--update to pin the new program")
            continue
        if old == rec:
            continue
        deltas = []
        if old.get("collectives") != rec["collectives"]:
            deltas.append(
                f"collective sequence changed: {old.get('collectives')} "
                f"-> {rec['collectives']} (host-uniform ordering is the "
                f"pod-deadlock invariant)")
        if old.get("callbacks") != rec["callbacks"]:
            deltas.append(
                f"host callbacks {old.get('callbacks')} -> "
                f"{rec['callbacks']} (a new host round trip inside the "
                f"step program)")
        if old.get("transfers") != rec["transfers"]:
            deltas.append(f"device transfers {old.get('transfers')} -> "
                          f"{rec['transfers']}")
        if old.get("eqns") != rec["eqns"]:
            deltas.append(f"eqn count {old.get('eqns')} -> {rec['eqns']}")
        from . import budget as _budget

        budget_problems = _budget.compare_budgets(
            name, old.get("budget"), rec.get("budget"))
        deltas.extend(p.split(": ", 1)[1] for p in budget_problems)
        if not deltas:
            if old.get("fingerprint") == rec.get("fingerprint"):
                # identical trace, differing record fields that cleared
                # their tolerance bands (e.g. a donation-table edit
                # re-derived peak_live_bytes within ±10%) — the lock is
                # stale, not the math
                deltas.append(
                    "record fields changed within tolerance bands "
                    "(budget ledger re-derived under new donation "
                    "facts?) — the trace itself is identical; "
                    "regenerate with --update to refresh the lock")
            else:
                deltas.append("jaxpr fingerprint changed at identical "
                              "census — the traced math itself differs")
        problems.append(f"{name}: trace changed — " + "; ".join(deltas))
    cur_sources = current.get("sources", {})
    lock_sources = lock.get("sources", {})
    stale = sorted(
        {rel for rel, digest in cur_sources.items()
         if lock_sources.get(rel) != digest}
        # symmetric: a file REMOVED from AUDIT_SOURCES (or renamed)
        # without --update leaves a rotted lock entry — same staleness
        | {rel for rel in lock_sources if rel not in cur_sources})
    if stale:
        problems.append(
            "stale source digests (edit without --update): "
            + ", ".join(stale)
            + " — traces still match, but the lock's provenance is out "
              "of date; rerun `python -m tpudp.analysis audit --update` "
              "and commit the lockfile")
    return problems

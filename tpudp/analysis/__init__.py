"""tpudp.analysis — static enforcement of the repo's runtime invariants.

Two surfaces (docs/ANALYSIS.md):

  * ``python -m tpudp.analysis lint`` — an AST-based, repo-aware linter
    for the failure classes this codebase has already paid for:
    nondeterminism baked into traces, Python branches on traced values,
    host syncs on scheduler hot paths, use-after-donation, collectives
    under per-host-divergent control flow, and unobservable jit
    programs.  Suppressions are explicit ``# tpudp: lint-ok(rule)``
    comments, so every sanctioned exception is visible in a diff.
  * ``python -m tpudp.analysis audit`` — traces the registered step
    programs at pinned CPU smoke geometries, fingerprints their jaxprs
    (plus a host-callback/transfer/collective census) and diffs against
    the committed ``tools/trace_lock.json``: a PR that introduces a
    recompile, a new host transfer, or a changed collective sequence in
    a hot path fails tier-1 loudly instead of silently regressing the
    benches.

This ``__init__`` (and the lint half of the package) is import-light by
design — stdlib only, jax loaded lazily inside the audit functions — so
watcher tooling (tools/bench_gaps.py) can run the lint gate on its poll
path without paying a jax import.
"""

# Relative imports throughout the package: tools/bench_gaps.py loads it
# standalone (by file path, under a synthetic package name) to run the
# lint gate without importing the jax-heavy `tpudp` parent package.
from .core import (PROTOCOL_RULE_NAMES, Finding, Module,  # noqa: F401
                   Rule, lint_paths)
from .protocol import (MigrationSpec, VoteSpec,  # noqa: F401
                       explore_migration_machine, explore_vote_machine,
                       extract_migration_spec, extract_vote_spec)
from .protocol import verify_paths as verify_protocol  # noqa: F401
from .rules import RULES, RULES_BY_NAME  # noqa: F401

"""Rule engine for the tpudp hazard linter.

Pure stdlib (``ast`` + ``re``) by design: the linter must be loadable
from the watcher's poll path (tools/bench_gaps.py) without importing
jax, so this module and :mod:`tpudp.analysis.rules` never import
anything heavier than the standard library.  The jaxpr auditor
(:mod:`tpudp.analysis.audit`) is the only part of the package that
touches jax, and it does so lazily inside functions.

The engine parses each target file once, builds the shared per-module
facts every rule needs — a parent map, an import-alias table, and the
*traced-region index* (which function defs run under a jax trace) —
and hands the :class:`Module` to every registered rule.

Suppressions are explicit ``# tpudp: lint-ok(rule)`` comments, either
on the offending line or on a comment-only line directly above it; an
optional ``: reason`` tail documents why.  Every suppression must
*match* a finding — one that suppresses nothing is itself reported
(``useless-suppression``), so stale exceptions can't accumulate.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize

SUPPRESS_RE = re.compile(r"#\s*tpudp:\s*lint-ok\(([a-z0-9_\-,\s]+)\)")
MARKER_RE = re.compile(r"#\s*tpudp:\s*([a-z0-9\-]+)\b")

#: Rules owned by the protocol verifier (tpudp/analysis/protocol.py).
#: The lint pass and the protocol pass share one suppression syntax but
#: check different rule sets, so each pass reports useless suppressions
#: only for the names IT owns — a `lint-ok(protocol-*)` that matches
#: nothing is flagged by the protocol pass, a typo'd name that belongs
#: to neither is still flagged by lint.  Defined here (not in
#: protocol.py) to keep the import graph acyclic; protocol.py re-uses
#: this set and a test pins it against the shipped protocol rules.
PROTOCOL_RULE_NAMES = frozenset({
    "protocol-divergent-entry",
    "protocol-order-divergence",
    "protocol-early-exit",
    "protocol-divergent-loop",
})

#: The multihost modules the protocol verifier covers by default:
#: everywhere a cross-process rendezvous is issued or decided.  Files
#: outside this scope (and without a ``# tpudp: protocol-module``
#: marker) are never verified, so lint must NOT defer their
#: protocol-rule suppressions — a stale `lint-ok(protocol-*)` in an
#: out-of-scope file would otherwise be flagged by neither pass.
#: Defined here (not in protocol.py) so lint can make that scope
#: decision without a circular import; protocol.py re-exports it.
PROTOCOL_MODULES = (
    "tpudp/resilience.py",
    "tpudp/utils/checkpoint.py",
    "tpudp/utils/consistency.py",
    "tpudp/mesh.py",
    "tpudp/cli.py",
    "tpudp/train.py",
    "tpudp/serve/engine.py",
    "tpudp/serve/disagg.py",
    "tpudp/obs/flight.py",
)


def in_protocol_scope(rel: str, markers: set[str]) -> bool:
    """Is this file one the protocol verifier analyzes?  By configured
    module path, or by an explicit first-lines marker."""
    rel = rel.replace(os.sep, "/")
    return ("protocol-module" in markers
            or any(rel.endswith(m) for m in PROTOCOL_MODULES))

#: Attribute reads that yield *static* (host, trace-time-constant)
#: values even on traced arrays — branching or syncing on these is fine.
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                "nbytes", "itemsize"}

#: Builtin calls whose result is static/host regardless of arguments.
#: float/int/bool belong here for TAINT purposes: applied to a device
#: value they are themselves the sync (the host-sync rule flags the
#: call), and their result is a host scalar — downstream reads are
#: clean.
STATIC_CALLS = {"len", "isinstance", "type", "getattr", "hasattr",
                "range", "id", "repr", "str", "format",
                "float", "int", "bool", "complex"}

#: Decorator / higher-order entry points that put a function under a
#: jax trace.  Dotted names are post-alias-resolution (``from jax
#: import lax`` resolves to ``jax.lax``).
TRACING_ENTRY_POINTS = {
    "jax.jit", "jax.pjit", "jax.shard_map", "jax.vmap", "jax.pmap",
    "jax.grad", "jax.value_and_grad", "jax.checkpoint", "jax.remat",
    "jax.make_jaxpr", "jax.eval_shape", "jax.lax.scan", "jax.lax.cond",
    "jax.lax.while_loop", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan", "jax.lax.custom_root",
    "jax.experimental.shard_map.shard_map", "jax.custom_jvp",
    "jax.custom_vjp",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit, pointing at a concrete source location."""

    rule: str
    path: str          # repo-relative, posix separators
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"


def comment_tokens(source: str) -> dict[int, str]:
    """line → comment text, from real COMMENT tokens only (a docstring
    that merely *mentions* ``# tpudp: lint-ok(...)`` must not count)."""
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


class Suppressions:
    """``# tpudp: lint-ok(rule[, rule...])`` comments for one file.

    A comment on a code line covers that line; a suppression inside a
    comment block covers the next *code* line after the block (so the
    justification can span several comment lines).  :meth:`allows`
    records use so :meth:`unused` can report suppressions that matched
    nothing.
    """

    def __init__(self, source: str, comments: dict[int, str] | None = None):
        self._cover: dict[int, list[tuple[int, str]]] = {}
        self._declared: list[tuple[int, str]] = []
        self._used: set[tuple[int, str]] = set()
        if comments is None:
            comments = comment_tokens(source)
        lines = source.splitlines()

        def _comment_or_blank(n: int) -> bool:
            if n > len(lines):
                return False
            stripped = lines[n - 1].strip()
            return not stripped or stripped.startswith("#")

        for lineno, text in comments.items():
            m = SUPPRESS_RE.search(text)
            if not m:
                continue
            code = lines[lineno - 1] if lineno <= len(lines) else ""
            target = lineno
            if code.lstrip().startswith("#"):
                target = lineno + 1
                while target <= len(lines) and _comment_or_blank(target):
                    target += 1
            for rule in m.group(1).split(","):
                rule = rule.strip()
                if rule:
                    self._declared.append((lineno, rule))
                    self._cover.setdefault(target, []).append((lineno, rule))

    def allows(self, line: int, rule: str) -> bool:
        for decl_line, r in self._cover.get(line, ()):
            if r == rule:
                self._used.add((decl_line, r))
                return True
        return False

    def unused(self) -> list[tuple[int, str]]:
        return [(line, rule) for line, rule in self._declared
                if (line, rule) not in self._used]


class Module:
    """One parsed file plus the shared facts rules consume."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source)
        self.lines = source.splitlines()
        self.comments = comment_tokens(source)
        self.suppressions = Suppressions(source, self.comments)
        self.markers = {m.group(1)
                        for line, text in self.comments.items() if line <= 5
                        for m in [MARKER_RE.search(text)] if m}
        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.imports = self._import_aliases()
        self.functions = self._collect_functions()
        self.traced = self._traced_index()

    # -- imports -------------------------------------------------------

    def _import_aliases(self) -> dict[str, str]:
        aliases: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def dotted(self, node: ast.AST) -> str | None:
        """Dotted path of a Name/Attribute chain with the root resolved
        through the module's import aliases (``np.random`` →
        ``numpy.random``); None for anything else (calls, subscripts)."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.imports.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def raw_dotted(self, node: ast.AST) -> str | None:
        """Dotted path WITHOUT alias resolution (``self.state.params``)
        — the spelling taint tracking keys on."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        return ".".join(reversed(parts))

    # -- function index ------------------------------------------------

    def _collect_functions(self) -> dict[ast.FunctionDef, str]:
        """Every def, mapped to its dotted qualname (``Engine.step``,
        ``make_train_step.train_step``)."""
        out: dict[ast.FunctionDef, str] = {}

        def visit(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    out[child] = qual
                    visit(child, qual + ".")
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}{child.name}.")
                else:
                    visit(child, prefix)

        visit(self.tree, "")
        return out

    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    # -- traced-region index -------------------------------------------

    def _jit_decorator_info(self, fn) -> tuple[bool, set[str], tuple]:
        """(is_jit_rooted, static param names, donated indices) from the
        def's decorators."""
        static: set[str] = set()
        donated: tuple = ()
        rooted = False
        for dec in fn.decorator_list:
            call = dec if isinstance(dec, ast.Call) else None
            target = call.func if call else dec
            dotted = self.dotted(target)
            if dotted in TRACING_ENTRY_POINTS:
                rooted = True
            elif (dotted in ("functools.partial", "partial") and call
                    and call.args
                    and self.dotted(call.args[0]) in TRACING_ENTRY_POINTS):
                rooted = True
            else:
                continue
            kwargs = call.keywords if call else []
            for kw in kwargs:
                if kw.arg in ("static_argnames", "static_argnums"):
                    try:
                        val = ast.literal_eval(kw.value)
                    except ValueError:
                        continue
                    vals = val if isinstance(val, (tuple, list)) else (val,)
                    args = [a.arg for a in fn.args.posonlyargs + fn.args.args]
                    for v in vals:
                        if isinstance(v, str):
                            static.add(v)
                        elif isinstance(v, int) and v < len(args):
                            static.add(args[v])
                if kw.arg == "donate_argnums":
                    try:
                        val = ast.literal_eval(kw.value)
                    except ValueError:
                        continue
                    donated = tuple(val) if isinstance(
                        val, (tuple, list)) else (val,)
        return rooted, static, donated

    def _traced_index(self) -> dict[ast.FunctionDef, str]:
        """def → how it gets traced: 'root' (jit/partial(jax.jit)
        decorator), 'combinator' (passed to lax.scan/cond/shard_map/...),
        'nested' (defined inside a traced def), or 'transitive' (called
        from a traced def in this module)."""
        traced: dict[ast.FunctionDef, str] = {}
        by_name: dict[str, list[ast.FunctionDef]] = {}
        for fn in self.functions:
            by_name.setdefault(fn.name, []).append(fn)

        for fn in self.functions:
            rooted, _, _ = self._jit_decorator_info(fn)
            if rooted:
                traced[fn] = "root"

        # defs passed (by name) to tracing combinators, incl.
        # ``step = jax.jit(step_fn)`` call forms.
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = self.dotted(node.func)
            if dotted in ("functools.partial", "partial") and node.args:
                dotted = self.dotted(node.args[0])
                cands = node.args[1:]
            else:
                cands = list(node.args)
            if dotted not in TRACING_ENTRY_POINTS:
                continue
            for arg in cands:
                if isinstance(arg, ast.Name):
                    for fn in by_name.get(arg.id, ()):
                        traced.setdefault(fn, "combinator")

        # closure: nested defs + same-module callees of traced defs
        changed = True
        while changed:
            changed = False
            for fn in self.functions:
                if fn in traced:
                    continue
                parent = self.enclosing_function(fn)
                if parent is not None and parent in traced:
                    traced[fn] = "nested"
                    changed = True
            for fn, kind in list(traced.items()):
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        name = None
                        if isinstance(node.func, ast.Name):
                            name = node.func.id
                        elif (isinstance(node.func, ast.Attribute)
                                and isinstance(node.func.value, ast.Name)
                                and node.func.value.id == "self"):
                            name = node.func.attr
                        if name:
                            for callee in by_name.get(name, ()):
                                if callee not in traced and callee is not fn:
                                    traced[callee] = "transitive"
                                    changed = True
        return traced

    def traced_kind(self, node: ast.AST) -> str | None:
        """'root'/'combinator'/'nested'/'transitive' if ``node`` sits
        inside a traced def, else None."""
        fn = node if isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)) \
            else self.enclosing_function(node)
        while fn is not None:
            kind = self.traced.get(fn)
            if kind is not None:
                return kind
            fn = self.enclosing_function(fn)
        return None

    def traced_params(self, fn) -> set[str]:
        """Param names of a directly-traced def that are traced values
        (non-static).  Empty for untraced/transitively-traced defs."""
        if self.traced.get(fn) not in ("root", "combinator", "nested"):
            return set()
        _, static, _ = self._jit_decorator_info(fn)
        names = {a.arg for a in fn.args.posonlyargs + fn.args.args
                 + fn.args.kwonlyargs}
        names.discard("self")
        names.discard("cls")
        return names - static


def mentions(mod: Module, node: ast.AST, tainted: set[str]) -> bool:
    """Does ``node`` evaluate through a tainted value?

    ``tainted`` holds raw dotted paths ("x", "self.state").  Static
    attribute reads (``x.shape``), identity tests (``x is None``) and
    host builtins (``len``, ``isinstance``) break the taint.
    """
    if isinstance(node, ast.Name) or isinstance(node, ast.Attribute):
        if isinstance(node, ast.Attribute) and node.attr in STATIC_ATTRS:
            return False
        dotted = mod.raw_dotted(node)
        if dotted is not None:
            for t in tainted:
                if dotted == t or dotted.startswith(t + "."):
                    return True
            return False
        if isinstance(node, ast.Attribute):
            return mentions(mod, node.value, tainted)
        return False
    if isinstance(node, ast.Call):
        fn_dotted = mod.dotted(node.func)
        if fn_dotted in STATIC_CALLS:
            return False
        parts = [*node.args, *[kw.value for kw in node.keywords]]
        if isinstance(node.func, ast.Attribute):
            parts.append(node.func.value)
        return any(mentions(mod, p, tainted) for p in parts)
    if isinstance(node, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False
        return any(mentions(mod, c, tainted)
                   for c in [node.left, *node.comparators])
    if isinstance(node, ast.Constant):
        return False
    return any(mentions(mod, c, tainted) for c in ast.iter_child_nodes(node))


def ordered_walk(fn: ast.AST, skip_nested_defs: bool = True):
    """Nodes of ``fn`` in source order (lineno, col) — ``ast.walk`` is
    breadth-first, which breaks linear taint propagation through nested
    blocks.  With ``skip_nested_defs``, bodies of defs nested inside
    ``fn`` are excluded (they are analyzed on their own)."""
    skip: set[int] = set()
    if skip_nested_defs:
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                skip.update(id(n) for n in ast.walk(node))
                skip.discard(id(node))
    return sorted(
        (n for n in ast.walk(fn)
         if hasattr(n, "lineno") and id(n) not in skip),
        key=lambda n: (n.lineno, n.col_offset))


class Rule:
    """Base class: subclasses set ``name``/``summary`` and implement
    :meth:`check` yielding Findings (pre-suppression)."""

    name: str = ""
    summary: str = ""

    def check(self, mod: Module):
        raise NotImplementedError

    def finding(self, mod: Module, node: ast.AST, message: str) -> Finding:
        return Finding(self.name, mod.rel, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


def iter_python_files(paths: list[str], root: str):
    """Yield (abspath, relpath) for every .py under the given paths."""
    skip_dirs = {"__pycache__", ".git", "bench_results", "node_modules",
                 ".venv"}
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            yield ap, os.path.relpath(ap, root)
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = sorted(d for d in dirnames if d not in skip_dirs)
            for f in sorted(filenames):
                if f.endswith(".py"):
                    full = os.path.join(dirpath, f)
                    yield full, os.path.relpath(full, root)


def lint_paths(paths: list[str], root: str, rules=None,
               report_useless: bool = True):
    """Run every rule over every file; returns (findings, errors).

    ``findings`` excludes suppressed hits but includes a
    ``useless-suppression`` finding for each suppression that matched
    nothing.  ``errors`` are files that failed to parse (reported, not
    fatal — a syntax error is pytest/ruff's job).
    """
    if rules is None:
        from .rules import RULES
        rules = RULES
    findings: list[Finding] = []
    errors: list[str] = []
    for path, rel in iter_python_files(paths, root):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            mod = Module(path, rel, source)
        except (SyntaxError, UnicodeDecodeError) as exc:
            errors.append(f"{rel}: parse failed: {exc}")
            continue
        for rule in rules:
            for finding in rule.check(mod):
                if not mod.suppressions.allows(finding.line, rule.name):
                    findings.append(finding)
        if report_useless:
            in_protocol = in_protocol_scope(mod.rel, mod.markers)
            for line, rule_name in mod.suppressions.unused():
                if rule_name in PROTOCOL_RULE_NAMES and in_protocol:
                    continue  # the protocol pass owns these names HERE;
                    # out of its scope nothing would ever report them
                findings.append(Finding(
                    "useless-suppression", mod.rel, line, 0,
                    f"lint-ok({rule_name}) suppresses nothing — remove it "
                    f"(or the hazard it excused is gone)"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, errors

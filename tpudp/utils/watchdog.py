"""Failure detection: hang watchdog + emergency checkpointing.

SURVEY.md §5 records the reference's posture: "a dead rank hangs the
gather/all_reduce forever; no timeout is configured"
(``src/Part 2a/main.py:152`` sets none) — failure detection is entirely
absent.  This module is the beyond-reference replacement, shaped for how
TPU/SPMD programs actually fail:

  * A wedged collective (peer host died, ICI link down) never returns — so
    detection must come from OUTSIDE the blocked call.  :class:`Watchdog`
    arms a monitor thread around each step; if the step doesn't complete
    within the deadline it dumps the attached flight recorder
    (``tpudp.obs`` — the span timeline naming the wedged region), runs
    the registered callbacks (e.g. log + dump state) and can terminate
    the process so a cluster scheduler restarts it (with
    ``--checkpoint-dir`` resume, that is elastic recovery in the
    "restart from last epoch" sense).
  * Per-step health checks that ARE observable in SPMD: a non-finite loss
    (diverged or corrupted replica) fails fast via :func:`check_finite`.

The watchdog is cooperative and zero-overhead on the hot path: arming is
two monotonic-clock reads and an Event set/clear; no thread is spawned per
step.  Every armed region carries a NAME (``arm("train_epoch")``,
``wd.step(name="decode")``), so a timeout explains itself: the
:class:`StepHangError` message and the flight-record dump both say which
region was armed, when, and what last completed — a watchdog that kills
without explaining is exactly the observability hole PR 11 closed.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable


class StepHangError(RuntimeError):
    """Raised in the main thread when a hang was detected and the watchdog
    was configured not to kill the process.  ``hang`` carries the
    detection context (region name, arm timestamp, last-completed span)
    when the watchdog recorded one."""

    def __init__(self, message: str, hang: dict | None = None):
        super().__init__(message)
        self.hang = hang or {}


class Watchdog:
    """Detects training steps that exceed a wall-clock deadline.

    Two usage styles:

    *Heartbeat* (what the Trainer uses — covers EVERY blocking host call in
    the monitored region, including multi-step fused log windows, the
    first-step XLA compile, ragged-window fetches, and eval)::

        wd = Watchdog(timeout_s=600, on_hang=[dump_fn], kill=True)
        wd.start(); wd.arm("train_epoch")
        for batch in loader:
            state, loss = train_step(state, *batch)
            wd.beat()             # progress! push the deadline out
        wd.disarm(); wd.stop()

    The deadline is ``timeout_s`` after the LAST beat, so the timeout must
    exceed the slowest legitimate gap between beats (for the fused Trainer:
    one full ``log_every``-step window plus the first-step compile).

    *Scoped* — arm a deadline around one specific blocking region::

        with wd.step(name="fetch_fence"):
            fetch_fence(state.params)  # tpudp.utils.profiler

    A scope may carry its own deadline (``wd.step(timeout_s=5.0)``) so one
    watchdog can guard regions with very different legitimate durations —
    the serve engine wraps each blocking device call this way
    (``tpudp.serve.Engine(watchdog=..., step_timeout_s=...)``) with a much
    tighter budget than a training step's, naming each region after the
    device call it guards (``decode``, ``prefill``, ``fused_decode``...).

    ``kill=True`` (default) hard-exits the process on a hang — the correct
    behavior for a wedged collective, which no Python exception can unwind;
    the launcher/scheduler restarts the job and ``--checkpoint-dir``
    resumes it.  ``kill=False`` records the hang and raises
    :class:`StepHangError` at the next ``beat()``/``step()`` boundary
    (useful in tests), with the armed region and arm time in the message.

    ``flight`` (a :class:`tpudp.obs.FlightRecorder`, usually attached by
    the engine/trainer that owns the watchdog) is dumped by the monitor
    thread the moment a hang is detected — BEFORE the callbacks and the
    kill — so even a hard-exit leaves a black box whose span timeline
    names the wedged region.  ``last_hang`` keeps the same context for
    the in-process (kill=False) paths.
    """

    def __init__(
        self,
        timeout_s: float = 300.0,
        *,
        on_hang: list[Callable[[], None]] | None = None,
        kill: bool = True,
        poll_s: float | None = None,
        flight=None,
    ):
        self.timeout_s = timeout_s
        self.on_hang = list(on_hang or [])
        self.kill = kill
        self.poll_s = poll_s if poll_s is not None else min(timeout_s / 4, 1.0)
        self.flight = flight  # tpudp.obs.FlightRecorder or None
        self.last_hang: dict | None = None
        self._armed = False
        self._deadline: float | None = None
        self._region: tuple[str, float] | None = None  # (name, armed_at)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._hang_seen = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "Watchdog":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._monitor, daemon=True, name="tpudp-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- heartbeat style ------------------------------------------------
    def arm(self, name: str = "heartbeat") -> None:
        """Begin continuous monitoring: a hang fires if no :meth:`beat`
        arrives within ``timeout_s``.  ``name`` labels the armed region
        for the hang report.  Re-arming after a handled hang
        (kill=False) clears the recorded hang so the watchdog is
        reusable."""
        self._hang_seen.clear()
        with self._lock:
            self._armed = True
            self._region = (name, time.monotonic())
            self._deadline = time.monotonic() + self.timeout_s

    def beat(self) -> None:
        """Record progress; pushes the deadline ``timeout_s`` into the
        future.  Raises :class:`StepHangError` (kill=False mode) if a hang
        was detected since the last beat.  A no-op unless :meth:`arm` is
        active, so components that beat unconditionally (Trainer epoch/eval
        loops) never start monitoring by accident."""
        if not self._armed:
            return
        if self._hang_seen.is_set() and not self.kill:
            raise StepHangError(self._hang_message(), self.last_hang)
        with self._lock:
            self._deadline = time.monotonic() + self.timeout_s

    def disarm(self) -> None:
        with self._lock:
            self._armed = False
            self._deadline = None
            self._region = None

    def acknowledge(self) -> bool:
        """kill=False mode: clear a recorded hang after the caller has
        CONTAINED it (retired/requeued the affected work), so the next
        scoped :meth:`step` proceeds instead of re-raising a hang that was
        already handled.  Returns whether a hang had been recorded.  The
        serve engine calls this from its step-failure containment;
        kill=True watchdogs never reach here (the process is gone)."""
        seen = self._hang_seen.is_set()
        self._hang_seen.clear()
        return seen

    # -- hang context ----------------------------------------------------
    def _hang_message(self) -> str:
        """One line that explains the kill: armed region, arm timestamp,
        and the last span the attached recorder saw complete."""
        hang = self.last_hang or {}
        region = hang.get("region", "unarmed")
        msg = (f"no progress within {hang.get('timeout_s', self.timeout_s)}s"
               f" in armed region '{region}'")
        armed_at = hang.get("armed_for_s")
        if armed_at is not None:
            msg += f" (armed {armed_at:.3f}s before detection)"
        last = hang.get("last_span")
        if last:
            msg += (f"; last completed span: {last.get('name')!r}"
                    f" at +{last.get('t0', 0):.3f}s")
        return msg

    def _capture_hang(self) -> dict:
        with self._lock:
            region = self._region
        name, armed_at = region if region is not None else ("unarmed", None)
        now = time.monotonic()
        hang = {"region": name, "timeout_s": self.timeout_s,
                "detected_at_monotonic": now,
                "armed_at_monotonic": armed_at,
                "armed_for_s": (now - armed_at
                                if armed_at is not None else None),
                "last_span": None}
        if self.flight is not None:
            try:
                hang["last_span"] = self.flight.recorder.last_span()
            except Exception:
                pass
        return hang

    # -- hot path ------------------------------------------------------
    class _Step:
        def __init__(self, wd: "Watchdog", timeout_s: float | None = None,
                     name: str = "step"):
            self.wd = wd
            self.timeout_s = wd.timeout_s if timeout_s is None else timeout_s
            self.name = name
            self._saved: tuple = (None, None)

        def __enter__(self):
            wd = self.wd
            if wd._hang_seen.is_set() and not wd.kill:
                raise StepHangError(
                    "a previous step exceeded its deadline — "
                    + wd._hang_message(), wd.last_hang)
            with wd._lock:
                self._saved = (wd._deadline, wd._region)
                wd._deadline = time.monotonic() + self.timeout_s
                wd._region = (self.name, time.monotonic())
            return self

        def __exit__(self, *exc):
            wd = self.wd
            with wd._lock:
                # restore the enclosing (heartbeat) deadline/region, so
                # a scoped guard inside an armed epoch hands monitoring
                # back instead of silencing it
                deadline, region = self._saved
                if wd._armed and deadline is not None:
                    wd._deadline = time.monotonic() + wd.timeout_s
                    wd._region = region
                else:
                    wd._deadline = None
                    wd._region = None
            return False

    def step(self, timeout_s: float | None = None,
             name: str = "step") -> "_Step":
        """Scoped deadline; ``timeout_s`` overrides the default for this
        one region (a serving decode step's budget is not a training
        step's); ``name`` labels the region in hang reports."""
        if timeout_s is not None and timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        return Watchdog._Step(self, timeout_s, name)

    # -- monitor -------------------------------------------------------
    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                deadline = self._deadline
            if deadline is not None and time.monotonic() > deadline:
                self.last_hang = self._capture_hang()
                self._hang_seen.set()
                if self.flight is not None:
                    # Black box FIRST: the callbacks may be the kill path
                    # (emergency state dump can itself hang on a wedged
                    # device), and kill=True never returns — the span
                    # timeline must already be on disk.
                    try:
                        self.flight.dump(
                            "watchdog_timeout_"
                            + str(self.last_hang.get("region")),
                            extra=self.last_hang)
                    except Exception:
                        pass
                for cb in self.on_hang:
                    try:
                        cb()
                    except Exception:
                        pass
                if self.kill:
                    # A wedged XLA collective cannot be interrupted from
                    # Python; exit so the scheduler restarts + resumes.
                    os._exit(42)
                with self._lock:  # avoid re-firing until re-armed
                    self._deadline = None


def check_finite(loss_value: float, step: int | None = None, *,
                 what: str = "training loss",
                 context: str | None = None) -> float:
    """Fail-fast divergence/corruption check (cheap; call at log windows
    where the host already synchronized).  ``what``/``context`` label the
    failure site — eval losses run through here too (a NaN eval must fail
    loudly with epoch + iteration context, not report garbage accuracy)."""
    import math

    if not math.isfinite(loss_value):
        where = f" at step {step}" if step is not None else ""
        if context:
            where += f" ({context})"
        raise FloatingPointError(
            f"non-finite {what}{where}: {loss_value!r} — diverged "
            "or corrupted replica")
    return loss_value

"""Failure detection: hang watchdog + emergency checkpointing.

SURVEY.md §5 records the reference's posture: "a dead rank hangs the
gather/all_reduce forever; no timeout is configured"
(``src/Part 2a/main.py:152`` sets none) — failure detection is entirely
absent.  This module is the beyond-reference replacement, shaped for how
TPU/SPMD programs actually fail:

  * A wedged collective (peer host died, ICI link down) never returns — so
    detection must come from OUTSIDE the blocked call.  :class:`Watchdog`
    arms a monitor thread around each step; if the step doesn't complete
    within the deadline it runs the registered callbacks (e.g. log + dump
    state) and can terminate the process so a cluster scheduler restarts it
    (with ``--checkpoint-dir`` resume, that is elastic recovery in the
    "restart from last epoch" sense).
  * Per-step health checks that ARE observable in SPMD: a non-finite loss
    (diverged or corrupted replica) fails fast via :func:`check_finite`.

The watchdog is cooperative and zero-overhead on the hot path: arming is
two monotonic-clock reads and an Event set/clear; no thread is spawned per
step.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable


class StepHangError(RuntimeError):
    """Raised in the main thread when a hang was detected and the watchdog
    was configured not to kill the process."""


class Watchdog:
    """Detects training steps that exceed a wall-clock deadline.

    Two usage styles:

    *Heartbeat* (what the Trainer uses — covers EVERY blocking host call in
    the monitored region, including multi-step fused log windows, the
    first-step XLA compile, ragged-window fetches, and eval)::

        wd = Watchdog(timeout_s=600, on_hang=[dump_fn], kill=True)
        wd.start(); wd.arm()
        for batch in loader:
            state, loss = train_step(state, *batch)
            wd.beat()             # progress! push the deadline out
        wd.disarm(); wd.stop()

    The deadline is ``timeout_s`` after the LAST beat, so the timeout must
    exceed the slowest legitimate gap between beats (for the fused Trainer:
    one full ``log_every``-step window plus the first-step compile).

    *Scoped* — arm a deadline around one specific blocking region::

        with wd.step():
            fetch_fence(state.params)  # tpudp.utils.profiler

    A scope may carry its own deadline (``wd.step(timeout_s=5.0)``) so one
    watchdog can guard regions with very different legitimate durations —
    the serve engine wraps each blocking device call this way
    (``tpudp.serve.Engine(watchdog=..., step_timeout_s=...)``) with a much
    tighter budget than a training step's.

    ``kill=True`` (default) hard-exits the process on a hang — the correct
    behavior for a wedged collective, which no Python exception can unwind;
    the launcher/scheduler restarts the job and ``--checkpoint-dir``
    resumes it.  ``kill=False`` records the hang and raises
    :class:`StepHangError` at the next ``beat()``/``step()`` boundary
    (useful in tests).
    """

    def __init__(
        self,
        timeout_s: float = 300.0,
        *,
        on_hang: list[Callable[[], None]] | None = None,
        kill: bool = True,
        poll_s: float | None = None,
    ):
        self.timeout_s = timeout_s
        self.on_hang = list(on_hang or [])
        self.kill = kill
        self.poll_s = poll_s if poll_s is not None else min(timeout_s / 4, 1.0)
        self._armed = False
        self._deadline: float | None = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._hang_seen = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "Watchdog":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._monitor, daemon=True, name="tpudp-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- heartbeat style ------------------------------------------------
    def arm(self) -> None:
        """Begin continuous monitoring: a hang fires if no :meth:`beat`
        arrives within ``timeout_s``.  Re-arming after a handled hang
        (kill=False) clears the recorded hang so the watchdog is reusable."""
        self._hang_seen.clear()
        with self._lock:
            self._armed = True
            self._deadline = time.monotonic() + self.timeout_s

    def beat(self) -> None:
        """Record progress; pushes the deadline ``timeout_s`` into the
        future.  Raises :class:`StepHangError` (kill=False mode) if a hang
        was detected since the last beat.  A no-op unless :meth:`arm` is
        active, so components that beat unconditionally (Trainer epoch/eval
        loops) never start monitoring by accident."""
        if not self._armed:
            return
        if self._hang_seen.is_set() and not self.kill:
            raise StepHangError(f"no progress within {self.timeout_s}s")
        with self._lock:
            self._deadline = time.monotonic() + self.timeout_s

    def disarm(self) -> None:
        with self._lock:
            self._armed = False
            self._deadline = None

    def acknowledge(self) -> bool:
        """kill=False mode: clear a recorded hang after the caller has
        CONTAINED it (retired/requeued the affected work), so the next
        scoped :meth:`step` proceeds instead of re-raising a hang that was
        already handled.  Returns whether a hang had been recorded.  The
        serve engine calls this from its step-failure containment;
        kill=True watchdogs never reach here (the process is gone)."""
        seen = self._hang_seen.is_set()
        self._hang_seen.clear()
        return seen

    # -- hot path ------------------------------------------------------
    class _Step:
        def __init__(self, wd: "Watchdog", timeout_s: float | None = None):
            self.wd = wd
            self.timeout_s = wd.timeout_s if timeout_s is None else timeout_s

        def __enter__(self):
            wd = self.wd
            if wd._hang_seen.is_set() and not wd.kill:
                raise StepHangError(
                    "a previous step exceeded its deadline")
            with wd._lock:
                wd._deadline = time.monotonic() + self.timeout_s
            return self

        def __exit__(self, *exc):
            with self.wd._lock:
                self.wd._deadline = None
            return False

    def step(self, timeout_s: float | None = None) -> "_Step":
        """Scoped deadline; ``timeout_s`` overrides the default for this
        one region (a serving decode step's budget is not a training
        step's)."""
        if timeout_s is not None and timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        return Watchdog._Step(self, timeout_s)

    # -- monitor -------------------------------------------------------
    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                deadline = self._deadline
            if deadline is not None and time.monotonic() > deadline:
                self._hang_seen.set()
                for cb in self.on_hang:
                    try:
                        cb()
                    except Exception:
                        pass
                if self.kill:
                    # A wedged XLA collective cannot be interrupted from
                    # Python; exit so the scheduler restarts + resumes.
                    os._exit(42)
                with self._lock:  # avoid re-firing until re-armed
                    self._deadline = None


def check_finite(loss_value: float, step: int | None = None, *,
                 what: str = "training loss",
                 context: str | None = None) -> float:
    """Fail-fast divergence/corruption check (cheap; call at log windows
    where the host already synchronized).  ``what``/``context`` label the
    failure site — eval losses run through here too (a NaN eval must fail
    loudly with epoch + iteration context, not report garbage accuracy)."""
    import math

    if not math.isfinite(loss_value):
        where = f" at step {step}" if step is not None else ""
        if context:
            where += f" ({context})"
        raise FloatingPointError(
            f"non-finite {what}{where}: {loss_value!r} — diverged "
            "or corrupted replica")
    return loss_value

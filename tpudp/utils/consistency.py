"""Replica-consistency verification — the DP desync detector.

torch DDP verifies model parameters across processes at wrapper
construction (its C++ ``_verify_params_across_processes``) because the
classic data-parallel failure mode is SILENT: replicas drift (a missing
gradient sync, a rank applying a different update, non-deterministic op
order) and training keeps producing finite, plausible losses that belong
to no consistent model.  The reference has no such check — SURVEY.md §5
files this under race detection/sanitizers (beyond-parity).

TPU-native twist: under GSPMD a replicated array is one logical value and
XLA is free to assume the shards agree — divergence hides.  The detector
therefore compares the actual per-device shard BYTES on the host: for
every leaf whose sharding is replicated on some devices, all addressable
replicas must be bit-identical (fp drift from a missing sync is never
bit-exact for long).  Multi-host: each process checks its addressable
shards; combine with a psum'd fingerprint (``fingerprint``) to compare
across processes without shipping weights.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class ReplicaDivergenceError(RuntimeError):
    """Replicated devices hold different values for the same parameter."""


def _leaf_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        yield jax.tree_util.keystr(path), leaf


def verify_replicas(tree, *, atol: float = 0.0, beat=None) -> int:
    """Check every replicated leaf's addressable shards agree; returns the
    number of LEAVES that had at least one replica pair compared.
    ``atol=0`` demands bit-identity (the right default: a replica that
    merely *rounds* differently will still drift apart over steps); raises
    :class:`ReplicaDivergenceError` naming the first divergent leaf and
    the worst |difference|.  ``beat`` (e.g. a watchdog heartbeat) is
    called after each leaf — the device→host shard fetches are
    model-size-proportional and must not look like a hang.

    Only INTRA-process replicas are visible here; for cross-process
    divergence use :func:`verify_across_processes`.
    """
    checked = 0
    for name, leaf in _leaf_paths(tree):
        if not isinstance(leaf, jax.Array):
            continue
        shards = getattr(leaf, "addressable_shards", None)
        if not shards or len(shards) < 2:
            continue
        # group shards by index: replicas hold the SAME slice of the
        # logical array on different devices (fully-replicated leaves have
        # one group with every device; sharded-but-replicated-on-a-subaxis
        # leaves have one group per slice)
        by_index: dict = {}
        for s in shards:
            by_index.setdefault(str(s.index), []).append(s)
        compared = False
        for index, group in by_index.items():
            if len(group) < 2:
                continue
            ref = np.asarray(group[0].data)
            for other in group[1:]:
                got = np.asarray(other.data)
                if atol == 0.0:
                    ok = np.array_equal(ref, got, equal_nan=True)
                else:
                    ok = np.allclose(ref, got, atol=atol, rtol=0.0,
                                     equal_nan=True)
                if not ok:
                    worst = float(np.max(np.abs(
                        ref.astype(np.float64) - got.astype(np.float64))))
                    raise ReplicaDivergenceError(
                        f"replicas diverged at leaf {name}{index}: device "
                        f"{group[0].device} vs {other.device}, max "
                        f"|diff|={worst:.3e} (missing gradient sync? a "
                        f"rung applying per-device updates?)")
            compared = True
        if compared:
            checked += 1
        if beat is not None:
            beat()
    return checked


def verify_across_processes(tree) -> None:
    """Cross-host desync check: every process computes the fingerprint of
    its addressable view of ``tree`` and all fingerprints must agree
    (replicated leaves fetch the same logical bytes on every host, so the
    per-process sums are bit-equal when the replicas are).  Complements
    :func:`verify_replicas`, which only sees intra-process shards —
    e.g. one local device per process would leave it nothing to compare.
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    fp = fingerprint(tree)
    all_fps = np.asarray(multihost_utils.process_allgather(jnp.asarray(fp)))
    for rank in range(all_fps.shape[0]):
        if not np.array_equal(all_fps[rank], all_fps[0]):
            raise ReplicaDivergenceError(
                f"process {rank} fingerprint {all_fps[rank]} != process 0 "
                f"{all_fps[0]} — replicas diverged across hosts (missing "
                f"cross-host gradient sync?)")


def fingerprint(tree) -> np.ndarray:
    """Cheap cross-process consistency probe: per-leaf (sum, sum of
    squares, size) reduced over leaves — processes can exchange/compare
    these few floats instead of weights.  Equal fingerprints don't prove
    equality, but unequal ones prove divergence.

    Only leaves whose full logical value is visible on this host
    contribute — fully addressable ones, and fully REPLICATED multi-host
    ones (every host holds the whole value, so their sums must agree; on
    a multi-process mesh these are NOT fully addressable, and skipping
    them would fingerprint nothing at all exactly where the check
    matters).  A genuinely SHARDED leaf (ZeRO-1 optimizer state, FSDP
    params) holds a different slice on every host, so its per-host sums
    differ by construction — including it would flag healthy runs; its
    bytes are covered by the per-host checkpoint shard manifests
    instead."""
    sums = sqs = n = 0.0
    for _, leaf in _leaf_paths(tree):
        if isinstance(leaf, jax.Array):
            if not getattr(leaf, "is_fully_addressable", True):
                if not getattr(leaf, "is_fully_replicated", False):
                    continue
                # Replicated across processes: any addressable shard IS
                # the full value (np.asarray on the array itself is
                # version-dependent for non-addressable arrays).
                a = np.asarray(leaf.addressable_shards[0].data,
                               dtype=np.float64)
                sums += float(a.sum())
                sqs += float((a * a).sum())
                n += a.size
                continue
            a = np.asarray(jax.device_get(leaf), dtype=np.float64)
            sums += float(a.sum())
            sqs += float((a * a).sum())
            n += a.size
    return np.array([sums, sqs, n])


def fingerprint_coverage(tree) -> dict:
    """Classify every leaf of ``tree`` by how :func:`fingerprint` (and
    the in-step SDC checksum, which applies the same rule) treats it:

    * ``included`` — fully addressable or fully replicated: its bytes
      are in the fingerprint, so corruption there is detectable;
    * ``excluded_sharded`` — genuinely sharded across processes
      (ZeRO-1 optimizer state, FSDP params): per-host sums differ by
      construction, so it is EXCLUDED by rule and covered by the
      per-host checkpoint shard manifests instead;
    * ``excluded_non_array`` — not a ``jax.Array`` (a Python scalar or
      host numpy leaf): invisible to the fingerprint.

    The leaf-coverage regression test pins this classification for the
    real TrainState: every leaf must land in ``included`` or
    ``excluded_sharded`` — a new leaf silently falling into
    ``excluded_non_array`` is a HOLE in the corruption detector, not an
    implementation detail."""
    out: dict[str, list[str]] = {"included": [], "excluded_sharded": [],
                                 "excluded_non_array": []}
    for path, leaf in _leaf_paths(tree):
        if not isinstance(leaf, jax.Array):
            out["excluded_non_array"].append(path)
        elif (not getattr(leaf, "is_fully_addressable", True)
                and not getattr(leaf, "is_fully_replicated", False)):
            out["excluded_sharded"].append(path)
        else:
            out["included"].append(path)
    return out

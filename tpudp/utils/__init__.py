from tpudp.utils.timing import StepTimer  # noqa: F401

"""Profiling and tracing.

The reference's entire observability story is ``time.time()`` brackets and
``print`` (SURVEY.md §5: no profiler, no traces).  tpudp keeps those
parity metrics (tpudp/utils/timing.py, Trainer's window prints) and adds
the TPU-native layer the reference never had:

  * :func:`trace` — capture a real XLA/TPU profile (TensorBoard `trace
    viewer` format) around any region, with per-step boundaries marked via
    :class:`jax.profiler.StepTraceAnnotation` so the trace viewer groups
    work by training step.
  * :func:`measure_collective` — the north-star "grad all-reduce wall-time"
    metric (BASELINE.json:2): times a jitted shard_map psum over a pytree
    shaped exactly like the model's gradients, fetch-fenced (see
    BASELINE.md on why ``block_until_ready`` alone is not a barrier under
    the axon relay).
"""

from __future__ import annotations

import time
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tpudp.mesh import DATA_AXIS
# trace/step_annotation moved to tpudp.obs (PR 11 folded the one-off
# timing/tracing APIs under the telemetry package); re-exported here so
# existing `from tpudp.utils.profiler import trace` imports keep working.
from tpudp.obs.tracing import step_annotation, trace  # noqa: F401


def fetch_fence(tree: Any) -> None:
    """Device->host fetch of one leaf element — the only reliable compute
    barrier under relay transports (BASELINE.md); the single shared
    implementation used by bench.py and the collective timer."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return
    np.asarray(jax.device_get(leaves[0].ravel()[0]))


def measure_collective(
    mesh: Mesh,
    grad_tree: Any,
    *,
    axis: str = DATA_AXIS,
    steps: int = 20,
    warmup: int = 3,
) -> dict:
    """Wall-time one mean-all-reduce of ``grad_tree`` over ``mesh``.

    Returns ``{"allreduce_wall_time_s", "bytes", "gbps"}`` — the measured
    cost of exactly the collective every DP sync strategy issues per step
    (reference analogue: the Gloo ``all_reduce`` in
    ``src/Part 2b/main.py:118``, there paid once PER PARAMETER; here one
    fused all-reduce over the whole tree).
    """
    size = mesh.shape[axis]

    def body(tree):
        return jax.tree.map(
            lambda g: jax.lax.psum(g, axis) / size, tree)

    fn = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(),), out_specs=P(), check_vma=False))

    tree = jax.device_put(
        grad_tree, jax.sharding.NamedSharding(mesh, P()))
    out = fn(tree)
    fetch_fence(out)  # compile + warm
    for _ in range(warmup):
        out = fn(tree)
    fetch_fence(out)

    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(out)
    fetch_fence(out)
    dt = (time.perf_counter() - t0) / steps

    nbytes = sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(grad_tree))
    # ring all-reduce moves 2(n-1)/n of the payload per device
    wire = 2 * (size - 1) / size * nbytes if size > 1 else 0
    return {
        "allreduce_wall_time_s": dt,
        "bytes": nbytes,
        "gbps": (wire / dt / 1e9) if dt > 0 else 0.0,
    }

"""Compiled-program caching: the in-process step-program LRU and the
persistent XLA executable cache.

Two layers, one module, because both answer the same question — "have
we already paid for this compile?":

  * :class:`ProgramCache` — in-process LRU of BUILT jitted step
    programs keyed by ``(cfg, id(params))``.  The serve engine freezes
    weights into its step programs as compile-time constants (PR 2);
    without this cache every Engine over the same weight tree would
    re-freeze (and re-compile) its own copies.  The trace-stability
    audit (tpudp/analysis) leans on these semantics: programs are
    reused per (config, params identity), so admission/retirement churn
    and co-resident engines can never mint new traces.  Programs with a
    per-engine static axis compose with it through jit statics rather
    than extra cache keys: the fused decode window
    (``engine.fused_decode_step``) is built once per ``(cfg, params)``
    here and jitted with ``static_argnames=("n_steps", "stream")``, so
    jax's own trace cache keys the compilations per ``(cfg, params,
    N[, stream])`` — engines sharing weights but differing in
    ``decode_fuse`` share one build and compile once per window size.
  * :func:`enable_persistent_cache` — JAX's on-disk executable cache
    for the relay-gated TPU (below).

Persistent XLA compilation cache for the relay-gated TPU.

The axon relay gives short, unpredictable windows of TPU health
(BASELINE.md "relay outage" note); the dominant cost inside a window is
the first compile of the fused train step (tens of seconds of RPC the
relay can wedge on).  JAX's persistent compilation cache removes that
cost for every run after the first successful one: the serialized
executable is stored on disk keyed by program hash, and later processes
(including the driver's own end-of-round ``bench.py``) deserialize it
instead of recompiling, shrinking the window a measurement needs.

Accelerator backends only: on XLA:CPU the AOT loader re-checks the host
feature string on every cache hit and prints multi-line "machine type
mismatch ... SIGILL" errors (the compile-side string carries XLA
preference pseudo-features like ``+prefer-no-gather`` that the runtime
probe never reports), drowning trainer output for a cache the 1-core
smoke path doesn't benefit from anyway — so the helper checks the
RESOLVED backend itself and no-ops on CPU.  Call it after any
``jax.config.update("jax_platforms", ...)`` override.

Opt-out with ``TPUDP_COMPILE_CACHE=0``; set a path to relocate.  Safe on
backends without executable serialization: JAX falls back to a normal
compile with a warning.  The reference has no analogue (eager torch
compiles nothing); this is TPU-runtime machinery.
"""

import collections
import os


class ProgramCache:
    """LRU of built (jitted) programs keyed by ``(cfg, id(params))``.

    ``build(cfg, params)`` runs on a miss; its result is cached and
    returned as-is on later hits.  Entries hold a STRONG reference to
    ``params``, which both bounds memory (the LRU evicts whole entries,
    weights included) and makes the ``id()`` key safe: an id can only
    be reused after the object it named was collected, and ours can't
    be collected while the entry holds it — the ``is`` check then
    confirms the identity on every hit.

    ``cfg`` must be hashable (the model configs are frozen dataclasses).
    Eviction is LRU over GETS, not builds: the hottest (cfg, params)
    pairs survive a parade of one-shot engines.
    """

    def __init__(self, build, max_entries: int = 8):
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self._build = build
        self.max_entries = max_entries
        self._entries: "collections.OrderedDict" = collections.OrderedDict()
        self.builds = 0
        self.hits = 0

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, cfg, params):
        key = (cfg, id(params))
        hit = self._entries.get(key)
        if hit is not None and hit[0] is params:
            self.hits += 1
            self._entries.move_to_end(key)
            return hit[1]
        programs = self._build(cfg, params)
        self.builds += 1
        self._entries[key] = (params, programs)
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
        return programs

    def clear(self) -> None:
        self._entries.clear()


# Inside the repo (the environment forbids writes elsewhere) and inside
# bench_results/ (gitignored by the `bench_results/*` rule).
DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "bench_results", "xla_cache")


def enable_persistent_cache(path: str | None = None, *,
                            force: bool = False) -> str | None:
    """Point JAX at the on-disk executable cache; returns the dir or None.

    Must run before the first compile (config flags are read per-compile).
    ``force=True`` skips the CPU-backend check (tests).  Every threshold
    is zeroed: on this relay even a small program's compile rides a
    wedge-prone RPC, so caching everything is the right trade.
    """
    import jax

    path = path if path is not None else os.environ.get(
        "TPUDP_COMPILE_CACHE", DEFAULT_DIR)
    if not path or path == "0":
        return None
    if not force:
        try:
            # Resolving the backend may itself ride the relay; callers
            # initialize the same backend immediately afterwards, so this
            # adds no new hang surface.
            if jax.default_backend() == "cpu":
                return None
        except Exception:  # noqa: BLE001 — no backend, nothing to cache
            return None
    try:
        os.makedirs(path, exist_ok=True)
        # Thresholds BEFORE the cache dir: the dir is the on/off switch,
        # so a failure anywhere leaves caching fully off — never half-on
        # with default thresholds while the caller was told "disabled".
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
        return None
    return path

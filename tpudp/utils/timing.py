"""Dispatch-honest timing helpers.

The reference brackets ``time.time()`` around eager torch calls
(``src/Part 2a/main.py:87-98``).  Under JAX async dispatch a naive bracket
measures dispatch, not compute — every timer here blocks on the measured
value before reading the clock (SURVEY.md §7 "timing honesty" hard part).
"""

from __future__ import annotations

import time

import jax


class StepTimer:
    """Accumulates wall time across steps with block_until_ready edges."""

    def __init__(self):
        self.total = 0.0
        self.count = 0
        self._t0 = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, *block_on) -> float:
        for x in block_on:
            jax.block_until_ready(x)
        dt = time.perf_counter() - self._t0
        self.total += dt
        self.count += 1
        return dt

    @property
    def mean(self) -> float:
        return self.total / max(self.count, 1)

    def reset(self) -> None:
        self.total, self.count = 0.0, 0

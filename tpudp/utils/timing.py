"""Compatibility shim: :class:`StepTimer` moved to ``tpudp.obs.timing``
(the one timing API — PR 11 folded the scattered timing helpers under
``tpudp.obs``).  Import from ``tpudp.obs`` in new code."""

from tpudp.obs.timing import StepTimer  # noqa: F401

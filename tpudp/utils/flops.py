"""Analytic FLOPs accounting and MFU (model FLOPs utilization).

The reference defines only wall-clock metrics (``src/Part 2a/main.py:
87-98,106-109``); this module converts them into the single-chip perf
criterion a TPU build is judged on: achieved model FLOPs/s divided by the
chip's peak.  Counts follow the standard convention — matmul/conv FLOPs
only (2 x MACs), elementwise/norm/pool ignored, backward = 2 x forward so
a train step is 3 x forward.

Peak numbers are the published per-chip bf16 figures (the "How to Scale
Your Model" hardware table); MFU is reported against bf16 peak regardless
of compute dtype, which is conservative for fp32 runs.
"""

from __future__ import annotations

# Published per-chip dense bf16 peak FLOPs/s, keyed by substrings of
# jax.Device.device_kind.  Order matters: first match wins, so the more
# specific "lite" kinds precede their generation's full-size chip.
_PEAK_BF16: tuple[tuple[str, float], ...] = (
    ("v6 lite", 918e12),  # Trillium
    ("v6e", 918e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def chip_peak_flops(device_kind: str) -> float | None:
    """Per-chip bf16 peak for a ``jax.Device.device_kind`` string, or None
    when the chip isn't in the table (e.g. the CPU smoke-test platform)."""
    kind = device_kind.lower()
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak
    return None


def mfu(flops_per_step: float, sec_per_step: float,
        device_kind: str, n_devices: int = 1) -> float | None:
    """Achieved fraction of peak: ``flops / (time * n * peak)``."""
    peak = chip_peak_flops(device_kind)
    if peak is None or sec_per_step <= 0:
        return None
    return flops_per_step / (sec_per_step * n_devices * peak)


# --- per-model analytic counts (forward, per batch) ----------------------

def conv2d_flops(batch: int, h_out: int, w_out: int, c_in: int, c_out: int,
                 kh: int, kw: int) -> int:
    return 2 * batch * h_out * w_out * c_in * c_out * kh * kw


def dense_flops(batch: int, d_in: int, d_out: int) -> int:
    return 2 * batch * d_in * d_out


def vgg_fwd_flops(batch: int, variant: str = "VGG11", image_size: int = 32,
                  num_classes: int = 10) -> int:
    """Walk the variant's config table (tpudp.models.vgg.CONFIGS — the
    reference's ``_cfg``, ``src/Part 1/model.py:3-8``)."""
    from tpudp.models.vgg import CONFIGS

    h = image_size
    c_in = 3
    total = 0
    for v in CONFIGS[variant]:
        if v == "M":
            h //= 2
        else:
            total += conv2d_flops(batch, h, h, c_in, int(v), 3, 3)
            c_in = int(v)
    total += dense_flops(batch, c_in * h * h, num_classes)
    return total


def resnet_fwd_flops(batch: int, stage_sizes=(3, 4, 6, 3),
                     image_size: int = 224, num_classes: int = 1000,
                     width: int = 64) -> int:
    """Bottleneck-ResNet walk matching tpudp.models.resnet.ResNet: 7x7/2
    stem, 3x3/2 maxpool, stages of (1x1 -> 3x3 -> 1x1 x4) bottlenecks with
    a projection on each stage's first block."""
    h = image_size // 2  # stem conv stride 2
    total = conv2d_flops(batch, h, h, 3, width, 7, 7)
    h = (h + 1) // 2  # maxpool stride 2
    c_in = width
    for stage, num_blocks in enumerate(stage_sizes):
        w = width * (2 ** stage)
        for block in range(num_blocks):
            stride = 2 if (stage > 0 and block == 0) else 1
            h_out = h // stride
            total += conv2d_flops(batch, h, h, c_in, w, 1, 1)
            total += conv2d_flops(batch, h_out, h_out, w, w, 3, 3)
            total += conv2d_flops(batch, h_out, h_out, w, 4 * w, 1, 1)
            if block == 0:  # projection shortcut
                total += conv2d_flops(batch, h_out, h_out, c_in, 4 * w, 1, 1)
            c_in, h = 4 * w, h_out
    total += dense_flops(batch, c_in, num_classes)
    return total


def gpt2_fwd_flops(batch: int, seq_len: int, *, num_layers: int = 12,
                   d_model: int = 768, vocab_size: int = 50_257,
                   mlp_ratio: int = 4) -> int:
    """Per-layer matmuls (QKV 3d^2 + proj d^2 + MLP 2*ratio*d^2 per token)
    plus the quadratic attention score/value matmuls and the LM head."""
    tokens = batch * seq_len
    per_layer = dense_flops(tokens, d_model, 3 * d_model)      # qkv
    per_layer += dense_flops(tokens, d_model, d_model)         # out proj
    per_layer += 2 * dense_flops(tokens, d_model, mlp_ratio * d_model)
    per_layer += 2 * 2 * batch * seq_len * seq_len * d_model   # QK^T + AV
    return num_layers * per_layer + dense_flops(tokens, d_model, vocab_size)


def llama_fwd_flops(batch: int, seq_len: int, *, num_layers: int,
                    d_model: int, vocab_size: int, hidden: int,
                    num_heads: int, kv_heads: int) -> int:
    """LLaMA-family analytic MACs: q/wo at d^2, k/v shrunk by the GQA
    ratio, SwiGLU's three d*hidden matmuls, quadratic attention, and the
    untied LM head (tpudp/models/llama.py)."""
    tokens = batch * seq_len
    kv_dim = d_model * kv_heads // num_heads
    per_layer = dense_flops(tokens, d_model, d_model)       # wq
    per_layer += 2 * dense_flops(tokens, d_model, kv_dim)   # wk, wv
    per_layer += dense_flops(tokens, d_model, d_model)      # wo
    per_layer += 3 * dense_flops(tokens, d_model, hidden)   # gate, up, down
    per_layer += 2 * 2 * batch * seq_len * seq_len * d_model  # QK^T + AV
    return num_layers * per_layer + dense_flops(tokens, d_model, vocab_size)


def train_step_flops(fwd_flops: int) -> int:
    """Backward is ~2x forward (grad wrt activations + grad wrt weights)."""
    return 3 * fwd_flops


def pipeline_bubble_fraction(stages: int, n_microbatches: int,
                             interleave: int = 1) -> float:
    """Idle fraction of a 1F1B pipeline schedule: ``(P-1)/(M+P-1)`` for
    ``P`` stages and ``M`` microbatches — the fill/drain slots no
    microbatch occupies.  ``interleave=V`` virtual stages per device cut
    each ramp slot to ``1/V`` of a stage's work (Megatron's interleaved
    schedule): ``(P-1)/(V*M + P-1)``.

    Reported alongside MFU for pipeline bench rows so they are
    comparable to DP rows: a PP row's achievable MFU ceiling is
    ``(1 - bubble) * dp_mfu``, making a bubble-bound row distinguishable
    from a kernel-bound one.  Degenerates to 0.0 at a single stage.
    """
    if stages < 1 or n_microbatches < 1 or interleave < 1:
        raise ValueError(
            f"stages ({stages}), n_microbatches ({n_microbatches}) and "
            f"interleave ({interleave}) must all be >= 1")
    if stages == 1:
        return 0.0
    return (stages - 1) / (interleave * n_microbatches + stages - 1)


def xla_cost_flops(jitted_fn, *args) -> float | None:
    """XLA's own FLOPs estimate for a jitted function at these args — an
    independent cross-check of the analytic counts above (the two differ
    by design: XLA counts every op post-fusion, the analytic count only
    matmul/conv MACs).  Returns None when the backend/relay doesn't expose
    cost analysis."""
    try:
        compiled = jitted_fn.lower(*args).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):  # older jax: one per device
            analysis = analysis[0]
        flops = analysis.get("flops") if analysis else None
        return float(flops) if flops and flops > 0 else None
    except Exception:  # pragma: no cover - backend-dependent surface
        return None

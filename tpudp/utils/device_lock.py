"""Single-client mutex for the relay-gated TPU.

The axon relay wedges — for hours — when two OS processes touch the TPU
concurrently (2026-07-31 postmortem: a manual ``tpu_probe.py`` overlapping
the watcher's own probe by a few seconds cost the whole morning window).
Every first-party TPU client (``tools/tpu_probe.py``, ``bench.py``, the
benchmark harnesses and Part/example trainers via
``acquire_for_process``, and the watcher battery) therefore takes this
advisory ``flock`` before its first device touch, so an accidental
second client fails fast with a clear "busy" instead of wedging the
relay for everyone.

Kernel-backed, so a crashed/SIGKILLed holder releases automatically —
stale locks cannot outlive their process.  Cooperative children of a
holder (e.g. bench.py's measurement child, the watcher's battery stages)
skip re-acquisition via the ``TPUDP_DEVICE_LOCK_HELD=1`` env var the
holder exports.  CPU smoke runs never take it (no shared device).

The reference has no analogue — Gloo ranks each own their process and
the assignment assumes a human launches exactly one per node
(``/root/reference/src/Part 2a/main.py:156-175``); the relay's
one-client constraint is a property of THIS runtime, handled here.
"""

import atexit
import contextlib
import errno
import fcntl
import os
import sys
import time

LOCK_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "bench_results", ".tpu.lock")

HELD_ENV = "TPUDP_DEVICE_LOCK_HELD"


@contextlib.contextmanager
def tpu_client_lock(timeout: float = 0.0, path: str = LOCK_PATH):
    """Yield False iff a LIVE competing TPU client holds the lock.

    Polls up to ``timeout`` seconds (0 = one non-blocking try).  Yielding
    False — rather than raising — leaves the caller the policy decision:
    a probe should report "busy = unhealthy", while the driver's
    end-of-round bench may prefer banked evidence or a last-resort run.

    Every OTHER outcome yields True: held, inherited via the env flag, or
    the locking infrastructure itself being unavailable (unwritable
    bench_results/, a filesystem without flock support raising ENOLCK,
    ...).  Mutual exclusion is best-effort protection for the relay;
    measurement availability wins when the two conflict — bench.py's
    "always print a headline line" contract must survive an unwritable
    lock file, and a phantom "another client holds the lock" diagnosis
    would freeze benching on banked evidence forever.  Infrastructure
    failures warn on stderr instead of silently degrading.
    """
    if os.environ.get(HELD_ENV) == "1":
        yield True
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        f = open(path, "w")
    except OSError as e:
        print(f"[device_lock] warning: cannot open lock file {path} ({e}); "
              "proceeding WITHOUT single-client protection",
              file=sys.stderr, flush=True)
        yield True
        return
    acquired = False
    busy = False
    deadline = time.monotonic() + timeout
    try:
        while True:
            try:
                fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
                acquired = True
                break
            except OSError as e:
                if e.errno not in (errno.EAGAIN, errno.EACCES):
                    # Broken locking (e.g. ENOLCK), not a competitor:
                    # warn and proceed unprotected rather than inventing
                    # a phantom client.
                    print(f"[device_lock] warning: flock failed ({e}); "
                          "proceeding WITHOUT single-client protection",
                          file=sys.stderr, flush=True)
                    break
                if time.monotonic() >= deadline:
                    busy = True
                    break
                time.sleep(1.0)
        if acquired:
            os.environ[HELD_ENV] = "1"  # inherited by children we spawn
        try:
            yield not busy
        finally:
            if acquired:
                os.environ.pop(HELD_ENV, None)
                fcntl.flock(f, fcntl.LOCK_UN)
    finally:
        f.close()


_PROCESS_LOCK = None  # keeps the context (and its fd) alive for the process


def acquire_for_process(skip: bool = False, timeout: float = 0.0,
                        path: str = LOCK_PATH, *,
                        force: bool = False) -> None:
    """Hold the single-client lock for this process's remaining lifetime.

    The entry hook for long-running TPU clients that are not structured
    around a ``with`` block (benchmark harnesses, the Part/ example
    trainers): call once before the first device touch; the lock is
    released at interpreter exit.  A live competing client raises
    ``SystemExit(2)`` with a pointer at the watcher — the manual-overlap
    wedge from the 2026-07-31 postmortem is exactly this path.
    Self-skips when ``jax_platforms`` is cpu-pinned (smoke runs, the
    test suite) — callers apply their platform override first; ``skip``
    lets a caller opt out on its own knowledge.  Idempotent.
    """
    global _PROCESS_LOCK
    if skip or _PROCESS_LOCK is not None:
        return
    # CPU-pinned processes (simulated meshes, the test suite's conftest)
    # have no shared device and must not take — or block on — the TPU
    # lock.  The jax_platforms CONFIG value is readable without
    # initializing a backend (resolving the backend would itself touch
    # the relay before the lock is held, defeating fail-fast); callers
    # apply their platform overrides before calling here.
    if not force:  # force=True: tests exercise the lock on the CPU suite
        try:
            import jax

            # cpu-pinned means EVERY entry is cpu: the axon sitecustomize
            # pins "axon,cpu" (accelerator first, cpu fallback) and a
            # substring test on that would skip the lock on the real TPU
            # host — the exact wedge this lock exists to prevent.  None/
            # empty (auto-detect) locks too: on this host it finds the TPU.
            platforms = str(getattr(jax.config, "jax_platforms", "") or "")
            entries = {p.strip() for p in platforms.split(",") if p.strip()}
            if entries == {"cpu"}:
                return
        except Exception:  # noqa: BLE001 — no config, fall through to lock
            pass
    ctx = tpu_client_lock(timeout=timeout, path=path)
    mine = ctx.__enter__()
    if not mine:
        ctx.__exit__(None, None, None)
        print("device_lock: another TPU client holds the device lock "
              f"({path}) — a second concurrent relay client wedges the "
              "TPU for hours.  If tools/tpu_when_ready.sh is running, let "
              "it finish (check bench_results/watch.log) or kill its "
              "process tree first.", file=sys.stderr, flush=True)
        raise SystemExit(2)
    _PROCESS_LOCK = ctx
    atexit.register(ctx.__exit__, None, None, None)

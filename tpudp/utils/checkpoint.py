"""Checkpoint / resume via orbax, with per-leaf integrity manifests.

The reference has NO checkpointing (SURVEY.md §5: "training state lives and
dies with the process") — this is a beyond-reference capability: save and
restore the full :class:`tpudp.train.TrainState` (params, BatchNorm stats,
optimizer state, step counter) so training resumes exactly where it stopped.
Sharded arrays round-trip with their shardings on multi-device meshes.

Integrity (the resilience layer's restore oracle, docs/RESILIENCE.md):
every save also writes a per-leaf crc32 manifest beside the checkpoint
directory (``<path>.manifest.json``).  ``restore_checkpoint(...,
verify=True)`` recomputes the checksums on the restored arrays and raises
:class:`CheckpointCorruptError` on any mismatch, and
:func:`restore_latest_verified` walks the ``step_N`` series newest→oldest
so a torn or bit-flipped newest checkpoint falls back to the previous
intact one instead of crash-looping every resume.

Multi-host (``jax.process_count() > 1``) saves are TWO-PHASE: each host
writes a per-host shard manifest (``<path>.manifest.host<K>.json`` —
crc32 over its unique addressable shard bytes, keyed by the shard's
global slice, closing the old "skipped: not fully addressable" hole and
covering ZeRO-1's sharded optimizer state), then an allgather barrier
confirms every host's manifest is durable before process 0 writes the
``<path>.COMMITTED`` marker.  A torn multi-host save is therefore
DETECTABLE: ``restore_latest_verified`` refuses any step dir without its
marker, and the multi-host walk is COORDINATED — hosts vote (allgather)
on the restore step so every replica restores the SAME checkpoint (min
over hosts' newest verified; a dir any host rejects is quarantined for
all).  Shard records verify elastically: a checkpoint saved at N hosts
re-verifies at M hosts by checking every recorded global slice that is
addressable on the current topology (the reassembled view covers all of
them when the pod shrinks).

Transient I/O (``_retry_fs``): every save/restore/manifest touch of the
checkpoint filesystem retries EIO-class errnos a bounded number of
times with linear backoff — on a real pod that path is NFS/GCS-fuse,
where a dropped lease surfaces as a one-off EIO on a healthy file.
Non-transient errnos (ENOENT, EACCES, ENOSPC) propagate immediately.
"""

from __future__ import annotations

import errno
import os
import re
import time
from typing import Any, Callable

import jax

try:
    import orbax.checkpoint as ocp

    HAVE_ORBAX = True
except ImportError:  # pragma: no cover - orbax is baked into this image
    HAVE_ORBAX = False


# Errnos worth retrying: the I/O path under a checkpoint dir on a real
# pod is NFS/GCS-fuse, where a dropped lease or a congested link
# surfaces as EIO/ESTALE/EAGAIN on an otherwise healthy file — a retry
# a moment later succeeds.  ENOENT/EACCES/ENOSPC and friends are NOT
# here on purpose: a missing file, bad permission, or full disk is a
# real answer, and retrying it only delays the real error.
_TRANSIENT_ERRNOS = frozenset({
    errno.EIO, errno.EAGAIN, errno.EBUSY, errno.EINTR, errno.ESTALE,
    errno.ETIMEDOUT,
})

# Module-level knobs so tests (and unusual deployments) can tune the
# policy without threading arguments through every save/restore call.
FS_RETRIES = 3          # attempts after the first = FS_RETRIES
FS_BACKOFF_S = 0.05     # linear: sleep(FS_BACKOFF_S * attempt)


def _retry_fs(fn: Callable[[], Any], what: str):
    """Run ``fn()`` retrying TRANSIENT filesystem errors (the
    ``_TRANSIENT_ERRNOS`` set) up to ``FS_RETRIES`` times with linear
    ``FS_BACKOFF_S`` backoff; any other ``OSError`` — and the final
    transient failure — propagates unchanged.  Bounded by construction:
    a checkpoint path that stays broken must become the caller's loud
    error (save fails, restore falls back to the previous verified
    step), never a silent spin."""
    for attempt in range(FS_RETRIES + 1):
        try:
            return fn()
        except OSError as exc:
            if (exc.errno not in _TRANSIENT_ERRNOS
                    or attempt >= FS_RETRIES):
                raise
            time.sleep(FS_BACKOFF_S * (attempt + 1))


class CheckpointCorruptError(RuntimeError):
    """A checkpoint restored cleanly but its bytes do not match the
    per-leaf checksum manifest written at save time — silent corruption
    (bit flip, torn write orbax did not catch).  Typed so resume flows can
    fall back to an older checkpoint instead of crashing."""


def _checkpointer():
    return ocp.PyTreeCheckpointer()


def manifest_path(path: str | os.PathLike) -> str:
    """The integrity manifest lives BESIDE the checkpoint directory (not
    inside it): orbax's item-free restore (:func:`restore_params`) scans
    the directory to infer the tree, and a foreign file inside would be
    misread as a leaf."""
    return os.path.abspath(os.fspath(path)) + ".manifest.json"


def host_manifest_path(path: str | os.PathLike, host: int) -> str:
    """The per-host shard manifest for multi-host saves (one writer per
    file — host ``K`` checksums only the shard bytes it addressed)."""
    return os.path.abspath(os.fspath(path)) + f".manifest.host{host}.json"


def host_manifest_paths(path: str | os.PathLike) -> list[str]:
    """Every per-host shard manifest present beside ``path`` (sorted by
    host so verification order is deterministic)."""
    import glob
    import re

    base = os.path.abspath(os.fspath(path))
    found = glob.glob(base + ".manifest.host*.json")
    pat = re.compile(re.escape(base) + r"\.manifest\.host(\d+)\.json$")
    with_rank = [(int(m.group(1)), p) for p in found if (m := pat.match(p))]
    return [p for _, p in sorted(with_rank)]


def commit_marker_path(path: str | os.PathLike) -> str:
    """The two-phase-commit marker for multi-host saves: written by
    process 0 only after an allgather confirmed every host's shards and
    manifest are durable, so a torn multi-host save (one host died
    mid-write) is detectable by the marker's absence."""
    return os.path.abspath(os.fspath(path)) + ".COMMITTED"


def is_committed(path: str | os.PathLike) -> bool:
    return os.path.exists(commit_marker_path(path))


def all_hosts_ok(ok: bool, value: int = 0) -> bool:
    """Cross-host unanimity vote on a local boolean: True only if EVERY
    process passed ``ok=True`` AND every process passed the same
    ``value`` (e.g. the step number of the dir being voted on, so hosts
    whose directory listings diverged — one already sees a new save the
    other does not — reject instead of restoring different states).
    The primitive behind every replica-consistent restore decision — a
    checkpoint one host rejects must be rejected by all, or replicas
    resume from different states.  On a single process this is the
    identity (no collective dispatched)."""
    if jax.process_count() == 1:
        return ok
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    flags = np.asarray(multihost_utils.process_allgather(
        jnp.asarray([1 if ok else 0, int(value)], jnp.int32)))
    return bool(flags[:, 0].min() == 1
                and (flags[:, 1] == flags[0, 1]).all())


def gather_host_values(value: int) -> list[int]:
    """Allgather one integer per host, in rank order (identity list on a
    single process — no collective dispatched).  The alignment primitive
    for decisions that need to SEE every host's value rather than just
    unanimity — e.g. the verified walk aligning to the newest step every
    host can see."""
    if jax.process_count() == 1:
        return [int(value)]
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    flags = np.asarray(multihost_utils.process_allgather(
        jnp.asarray([int(value)], jnp.int32)))
    return [int(v) for v in flags[:, 0]]


def coordinated_any(flag: bool) -> bool:
    """True if ANY host passed True (identity on a single process — no
    collective dispatched).  The entry-gate primitive: whether to enter
    a collective restore/save protocol must itself be a collective
    decision — a per-host filesystem probe (stale shared-FS listing)
    deciding entry would leave one host inside an allgather its peer
    never joins, or one host alone inside a collective save barrier."""
    if jax.process_count() == 1:
        return flag
    return max(gather_host_values(1 if flag else 0)) == 1


def gather_host_blobs(blob: bytes) -> list[bytes]:
    """Allgather one variable-length byte payload per host, in rank
    order (identity list on a single process — no collective
    dispatched).  The bulk-transfer primitive under KV page migration
    (``tpudp/serve/disagg.py``): every host contributes its packed
    ticket batch (possibly empty) and receives every peer's, over
    exactly TWO fixed collectives — a length gather, then ONE
    max-length-padded uint8 allgather — so the rendezvous sequence is
    identical on every host no matter who has bytes to send (an idle
    host rides along with a zero-length payload rather than skipping
    the exchange and wedging its peers)."""
    if jax.process_count() == 1:
        return [bytes(blob)]
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    lengths = gather_host_values(len(blob))
    # Pad width quantized to the next power of two: process_allgather
    # compiles one program per distinct width, and migration blob sizes
    # vary round to round — exact widths would recompile the transfer
    # collective on nearly every handoff, a pause that lands mid-decode
    # on the receiving host.  The exact lengths still slice each
    # payload, so the extra pad bytes never reach a caller.
    width = 1 << (max(max(lengths), 1) - 1).bit_length()
    buf = np.zeros(width, np.uint8)
    buf[: len(blob)] = np.frombuffer(bytes(blob), np.uint8)
    gathered = np.asarray(
        multihost_utils.process_allgather(jnp.asarray(buf)))
    return [gathered[i, :n].tobytes() for i, n in enumerate(lengths)]


def invalidate_commit(path: str | os.PathLike) -> None:
    """Remove a previous save's COMMITTED marker and per-host shard
    manifests BEFORE a multi-host save rewrites ``path`` (``force=True``
    overwrite, or a shrunken pod re-saving the same step name): a stale
    marker would make the new, not-yet-barriered save look committed,
    and a stale ``manifest.host<K>.json`` from a host that no longer
    exists would fail verification against the new bytes forever.
    Process 0 only; callers barrier after."""
    if jax.process_index() != 0:
        return
    for p in [commit_marker_path(path)] + host_manifest_paths(path):
        try:
            os.unlink(p)
        except OSError:
            pass


def _sidecar_paths(path: str | os.PathLike) -> list[str]:
    """Every integrity sidecar beside the checkpoint dir at ``path``:
    the plain manifest, all per-host shard manifests, and the commit
    marker — the set that must travel with the dir on quarantine and die
    with it on prune."""
    return ([manifest_path(path), commit_marker_path(path)]
            + host_manifest_paths(path))


def leaf_checksums(state: Any) -> dict:
    """Per-leaf crc32/dtype/shape over the pytree, keyed by
    ``jax.tree_util.keystr`` path.  Leaves that are not fully addressable
    on this process (multi-host shards) are recorded as skipped — a
    checksum over a partial host view would be topology-dependent; the
    per-host shard manifests (:func:`leaf_shard_checksums`) cover them."""
    import zlib

    import numpy as np

    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        key = jax.tree_util.keystr(path)
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            out[key] = {"skipped": "not fully addressable"}
            continue
        arr = np.asarray(leaf)
        out[key] = {"crc32": zlib.crc32(arr.tobytes()) & 0xFFFFFFFF,
                    "dtype": str(arr.dtype), "shape": list(arr.shape)}
    return out


def _index_spans(index, gshape) -> list[list[int]]:
    """A shard's global slice as ``[[start, stop], ...]`` (JSON-stable;
    ``slice(None)`` normalized to the full dimension)."""
    spans = []
    for sl, dim in zip(index, gshape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        spans.append([start, stop])
    return spans


def _unique_addressable_shards(leaf):
    """This host's addressable shards deduped by global index (replicas
    of the same slice on several local devices checksum once)."""
    seen = {}
    for s in leaf.addressable_shards:
        key = str(s.index)
        if key not in seen:
            seen[key] = s
    return [seen[k] for k in sorted(seen)]


def leaf_shard_checksums(state: Any) -> dict:
    """Per-leaf records of THIS host's unique addressable shard bytes —
    the multi-host manifest payload.  Each record carries the shard's
    GLOBAL slice, so verification is topology-portable: any later
    process that can address that slice (same geometry, or the
    reassembled view after an elastic restore) can recompute the crc32.

    Leaves whose full value is identical on every host (fully
    addressable, or fully REPLICATED over the mesh) are recorded by
    process 0 only: every host writing the same whole-array record would
    make every later restore recompute the full model's checksums once
    per host manifest.  Genuinely sharded leaves are recorded by every
    host — each holds different slices."""
    import zlib

    import numpy as np

    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        key = jax.tree_util.keystr(path)
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            if (getattr(leaf, "is_fully_replicated", False)
                    and jax.process_index() != 0):
                continue  # identical full-span record on every host
            shards = []
            for s in _unique_addressable_shards(leaf):
                arr = np.asarray(s.data)
                shards.append(
                    {"index": _index_spans(s.index, leaf.shape),
                     "crc32": zlib.crc32(arr.tobytes()) & 0xFFFFFFFF})
            out[key] = {"dtype": str(leaf.dtype),
                        "gshape": list(leaf.shape), "shards": shards}
            continue
        if jax.process_index() != 0:
            continue  # fully addressable: same bytes on every host
        arr = np.asarray(leaf)
        out[key] = {"dtype": str(arr.dtype), "gshape": list(arr.shape),
                    "shards": [{"index": [[0, d] for d in arr.shape],
                                "crc32": zlib.crc32(arr.tobytes())
                                & 0xFFFFFFFF}]}
    return out


def write_manifest(path: str | os.PathLike, state: Any) -> str:
    """Write the integrity manifest for the checkpoint at ``path``.

    Single-host: the per-leaf whole-array manifest (``.manifest.json``),
    unchanged semantics.  Multi-host: EVERY host writes its own shard
    manifest (``.manifest.host<K>.json``, fsync'd — the commit barrier in
    :func:`save_checkpoint` keys off its durability); no plain manifest
    is written, the per-host set plus the COMMITTED marker replace it."""
    import json

    if jax.process_count() > 1:
        hpath = host_manifest_path(path, jax.process_index())
        payload = {"format": 2, "host": jax.process_index(),
                   "nprocs": jax.process_count(),
                   "leaves": leaf_shard_checksums(state)}

        def _write_host() -> None:
            with open(hpath, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())

        _retry_fs(_write_host, f"host manifest write ({hpath})")
        return hpath
    mpath = manifest_path(path)
    payload = {"format": 1, "leaves": leaf_checksums(state)}

    def _write() -> None:
        with open(mpath, "w") as f:
            json.dump(payload, f)

    _retry_fs(_write, f"manifest write ({mpath})")
    return mpath


def commit_after_all_hosts(path: str | os.PathLike) -> None:
    """Phase 2 of the multi-host save: barrier until every host's save +
    manifest write returned, then process 0 alone writes the COMMITTED
    marker.  Until the marker exists the step dir is not part of the
    verified series — a host dying mid-save leaves a detectably torn
    checkpoint instead of a silently short one."""
    import json
    import time

    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(
        f"tpudp_ckpt_commit:{os.path.basename(os.fspath(path))}")
    if jax.process_index() != 0:
        return

    def _write_marker() -> None:
        with open(commit_marker_path(path), "w") as f:
            json.dump({"nprocs": jax.process_count(),
                       "committed_at": time.strftime(
                           "%Y-%m-%dT%H:%M:%SZ", time.gmtime())}, f)
            f.flush()
            os.fsync(f.fileno())

    _retry_fs(_write_marker, "commit marker write")


def read_manifest(path: str | os.PathLike) -> dict | None:
    """The manifest payload for the checkpoint at ``path``, or None if
    absent/unreadable (checkpoints saved before manifests existed)."""
    import json

    def _read() -> dict:
        with open(manifest_path(path)) as f:
            return json.load(f)

    try:
        # Retried: a transient EIO here would otherwise read as "no
        # manifest" and silently skip verification of a real one.
        return _retry_fs(_read, "manifest read")
    except (FileNotFoundError, json.JSONDecodeError, OSError):
        return None


def _crc_of_slice(leaf, spans) -> int | None:
    """crc32 of the global slice ``spans`` of restored leaf ``leaf`` if
    that slice is addressable on this host, else None (another host's
    shard under the current topology — someone else verifies it)."""
    import zlib

    import numpy as np

    want = tuple(slice(s, e) for s, e in spans)
    if not isinstance(leaf, jax.Array) or leaf.is_fully_addressable:
        arr = np.asarray(leaf)
        data = arr[want] if want else arr
        return zlib.crc32(np.ascontiguousarray(data).tobytes()) & 0xFFFFFFFF
    for s in leaf.addressable_shards:
        have = _index_spans(s.index, leaf.shape)
        if all(hs <= ws and we <= he
               for (hs, he), (ws, we) in zip(have, spans)):
            local = np.asarray(s.data)
            rel = tuple(slice(ws - hs, we - hs)
                        for (hs, _), (ws, we) in zip(have, spans))
            data = local[rel] if rel else local
            return (zlib.crc32(np.ascontiguousarray(data).tobytes())
                    & 0xFFFFFFFF)
    return None


def verify_restored_coverage(path: str | os.PathLike,
                             state: Any) -> tuple[bool, str, list[bool]]:
    """Compare ``state`` (a freshly restored pytree) against the
    manifest(s) written when ``path`` was saved.  Returns ``(ok, detail,
    coverage)`` where ``coverage`` has one flag per shard record — in a
    DETERMINISTIC order (payloads in read order, leaves in file order,
    shards in record order), identical on every host because every host
    reads the same manifest files — saying whether THIS host could
    address and therefore check that record.  A checkpoint with no
    manifest of any kind verifies vacuously (legacy checkpoints carry
    none).

    Verification is topology-portable: whole-array records (single-host
    manifests) and per-shard records (multi-host host manifests) are both
    checked for every global slice this host can address on the CURRENT
    mesh — on an elastic restore at fewer hosts the reassembled view
    covers every recorded shard, so a byte flipped in any save-time
    host's shard is still caught.  On a GROWN or resharded topology a
    record may be addressable on no single host; the coordinated walk
    unions the per-host coverage and rejects a dir whose records nobody
    checked (a silent 'verified' there would cover nothing)."""
    import json

    coverage: list[bool] = []
    payloads = []
    plain = read_manifest(path)
    if plain is not None:
        payloads.append(plain)
    for hpath in host_manifest_paths(path):
        try:
            with open(hpath) as f:
                payloads.append(json.load(f))
        except (json.JSONDecodeError, OSError):
            return (False,
                    f"unreadable host manifest {os.path.basename(hpath)}",
                    coverage)
    if not payloads:
        return True, "no manifest (unverified legacy checkpoint)", coverage

    have = {jax.tree_util.keystr(p): leaf for p, leaf
            in jax.tree_util.tree_flatten_with_path(state)[0]}
    checked = 0
    for payload in payloads:
        host = payload.get("host")
        for key, rec in payload.get("leaves", {}).items():
            if key not in have:
                return False, f"leaf {key} missing from restored tree", \
                    coverage
            leaf = have[key]
            if "shards" in rec:
                records = [(s["index"], s["crc32"]) for s in rec["shards"]]
            elif "crc32" in rec:
                # format-1 whole-array record
                shape = rec.get("shape", [])
                records = [([[0, d] for d in shape], rec["crc32"])]
            else:
                continue  # recorded as skipped by a pre-shard-manifest save
            for spans, want_crc in records:
                got = _crc_of_slice(leaf, spans)
                if got is None:
                    # not addressable here; a peer must cover it
                    coverage.append(False)
                    continue
                coverage.append(True)
                checked += 1
                if got != want_crc:
                    where = f" (host {host} shard)" if host is not None else ""
                    return (False,
                            f"leaf {key}{where} checksum mismatch "
                            f"(saved {want_crc}, restored {got})", coverage)
    return True, f"{checked} shard checksums verified", coverage


def verify_restored(path: str | os.PathLike, state: Any) -> tuple[bool, str]:
    """:func:`verify_restored_coverage` without the coverage vector —
    the single-host verification entry point (one host's fully
    addressable view covers every record, so coverage is vacuous)."""
    ok, detail, _coverage = verify_restored_coverage(path, state)
    return ok, detail


def save_checkpoint(path: str | os.PathLike, state: Any, *,
                    force: bool = True, manifest: bool = True) -> str:
    """Write ``state`` (any pytree, e.g. TrainState) to ``path``.

    ``manifest=True`` (default) also writes the per-leaf checksum manifest
    beside the directory, making this checkpoint verifiable by
    ``restore_checkpoint(..., verify=True)`` and eligible as a fallback
    target for :func:`restore_latest_verified`.

    Multi-host: the save is collective (every process writes its
    addressable shards) and TWO-PHASE — each host writes its shard
    manifest, then :func:`commit_after_all_hosts` barriers and process 0
    writes the COMMITTED marker.  A host dying anywhere before the
    barrier leaves a marker-less (torn, detectable) dir."""
    if not HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not installed")
    path = os.path.abspath(os.fspath(path))
    multihost = jax.process_count() > 1
    if multihost:
        # Stale sidecars from a previous save under this name must die
        # BEFORE orbax starts writing (a leftover marker would make the
        # new save look committed while hosts are still mid-write).
        from jax.experimental import multihost_utils

        invalidate_commit(path)
        multihost_utils.sync_global_devices(
            f"tpudp_ckpt_invalidate:{os.path.basename(path)}")
    else:
        # Single-host saves must ALSO clear stale multi-host sidecars
        # under this name (a shrunken pod re-saving a step a larger pod
        # once wrote): a leftover host manifest would be verified
        # against the new bytes and reject the fresh save forever.
        invalidate_commit(path)
    _retry_fs(lambda: _checkpointer().save(path, state, force=force),
              f"checkpoint save ({path})")
    if manifest:
        write_manifest(path, state)
        if multihost:
            commit_after_all_hosts(path)
    return path


def restore_checkpoint(path: str | os.PathLike, target: Any, *,
                       verify: bool = False) -> Any:
    """Restore a pytree saved by :func:`save_checkpoint`.

    ``target`` is a matching pytree (e.g. a freshly built TrainState) used
    for structure, dtypes, and shardings; its values are not read.

    ``verify=True`` recomputes per-leaf checksums on the restored arrays
    against the save-time manifest and raises
    :class:`CheckpointCorruptError` on mismatch (a checkpoint without a
    manifest passes vacuously — there is nothing to compare).
    """
    if not HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not installed")
    path = os.path.abspath(os.fspath(path))

    # ELASTIC resume (checkpoint saved on an N-device mesh, restored on an
    # M-device one — the pod shrank after a failure, or grew): the sharding
    # must reach orbax's DESERIALIZATION layer via restore_args, so each
    # array materializes directly on the CURRENT topology; the recorded
    # sharding file names save-time devices that may no longer exist, and
    # post-hoc device_put never runs if deserialization already failed.
    # A COMMITTED target leaf is an intentional statement of the current
    # topology — its sharding is forwarded.  An UNCOMMITTED leaf (fresh
    # init_state before any mesh placement, e.g. the same-topology CLI
    # resume path) carries no placement intent: no sharding is forwarded
    # and orbax falls back to the checkpoint's recorded sharding, which is
    # only valid while the save-time devices still exist — elastic flows
    # must pass a placed target.
    def as_abstract(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=x.sharding if x.committed
                                        else None)
        return ocp.utils.to_shape_dtype_struct(x)

    abstract = jax.tree.map(as_abstract, target)
    restore_args = ocp.checkpoint_utils.construct_restore_args(abstract)
    restored = _retry_fs(
        lambda: _checkpointer().restore(path, item=abstract,
                                        restore_args=restore_args),
        f"checkpoint restore ({path})")
    if verify:
        ok, detail = verify_restored(path, restored)
        if not ok:
            raise CheckpointCorruptError(f"checkpoint {path} corrupt: {detail}")
    return restored


def step_dirs_newest_first(root: str | os.PathLike) -> list[str]:
    """All exact ``step_<digits>`` directories under ``root``, newest
    (highest N) first — the fallback walk order for
    :func:`restore_latest_verified`."""
    root = os.fspath(root)
    if not os.path.isdir(root):
        return []
    steps = sorted((int(m.group(1)), m.group(0))
                   for d in os.listdir(root) if (m := _STEP_DIR.match(d)))
    return [os.path.join(root, name) for _, name in reversed(steps)]


def quarantine_step_dir(path: str) -> None:
    """Move a rejected ``step_N`` dir (and every sidecar: manifest,
    per-host shard manifests, COMMITTED marker) aside to ``step_N.corrupt``,
    removing it from the step series: later walks must not re-count the
    same corruption, ``latest_step_dir``/pruning must not treat it as live
    state, and the bytes stay for forensics.  The COMMITTED marker MUST
    leave with the dir — a marker left behind would make a later save
    under the same step name look committed before its barrier ran.
    Rename races (multi-host: every process walks the series) are
    tolerated — whichever rename wins, the dir leaves the series."""
    import shutil

    base = os.path.abspath(os.fspath(path))
    target = base + ".corrupt"
    sidecars = _sidecar_paths(base)  # enumerate BEFORE the dir rename
    try:
        if os.path.isdir(target):
            shutil.rmtree(target)
        os.rename(base, target)
    except OSError:
        return
    for src in sidecars:
        try:
            os.replace(src, target + src[len(base):])
        except OSError:
            pass


def restore_latest_verified(root: str | os.PathLike, target: Any, *,
                            log=print) -> tuple[Any, str, list[tuple[str, str]]]:
    """Restore the newest INTACT ``step_N`` checkpoint under ``root``.

    Walks the step series newest→oldest; a directory that fails to restore
    (torn write, missing files) or fails its checksum manifest is
    QUARANTINED (renamed ``step_N.corrupt`` — out of the series, so the
    same corruption is never re-counted and pruning can't mistake it for
    live state) with a logged warning, and the walk falls back to the
    previous one — a corrupted newest checkpoint must never crash-loop
    resume (docs/RESILIENCE.md).  Returns ``(state, path, skipped)``
    where ``skipped`` lists ``(path, reason)`` for every rejected newer
    checkpoint.  Raises FileNotFoundError if no step dirs exist and
    RuntimeError if none of them is restorable."""
    multihost = jax.process_count() > 1
    pending = step_dirs_newest_first(root)
    if not pending and not multihost:
        # tpudp: lint-ok(protocol-early-exit): single-host-only raise —
        # the `not multihost` conjunct (process_count, host-uniform)
        # makes this arm unreachable on a pod; multihost exhaustion is
        # voted through the alignment gather below (-1 proposal), which
        # aborts every host together.
        raise FileNotFoundError(f"no step_N checkpoints under {os.fspath(root)!r}")

    def _barrier(tag: str) -> None:
        if multihost:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(tag)

    def _step_of(path: str) -> int:
        return int(os.path.basename(path).rsplit("_", 1)[1])

    skipped: list[tuple[str, str]] = []
    first_round = True
    while pending or multihost:
        if multihost:
            # Alignment round: every host proposes its newest remaining
            # step (-1 when exhausted) and all align to the MIN — the
            # newest step EVERY host can see.  Directory listings can
            # diverge (shared-FS attribute-cache lag, a save landing
            # between two hosts' scans); a dir a peer cannot see is
            # skipped WITHOUT quarantine (it may be perfectly healthy —
            # the peer's listing is stale, not the bytes), and because
            # exhaustion is itself a proposal, one host running out
            # aborts ALL hosts together instead of leaving peers parked
            # in a collective nobody else will join.
            while True:
                head = _step_of(pending[0]) if pending else -1
                # tpudp: lint-ok(protocol-divergent-loop): the outer
                # walk loop's condition is `pending or multihost` — on a
                # pod the multihost flag alone keeps every host in the
                # loop regardless of its per-host listing, and a host
                # whose series is exhausted proposes -1 through this
                # gather, aborting ALL hosts in the same round; trip
                # counts therefore agree pod-wide by protocol.
                proposals = gather_host_values(head)
                aligned = min(proposals)
                if aligned < 0:
                    if first_round and max(proposals) < 0:
                        raise FileNotFoundError(
                            f"no step_N checkpoints under "
                            f"{os.fspath(root)!r} (on any host)")
                    raise RuntimeError(
                        f"no step_N checkpoint under {os.fspath(root)!r} "
                        f"is restorable on every host ({len(skipped)} "
                        "tried/skipped locally; a peer exhausted its "
                        "series); refusing to silently restart from "
                        "scratch — remove the directory to train fresh")
                if head == aligned and all(p == aligned for p in proposals):
                    break
                while pending and _step_of(pending[0]) > aligned:
                    unseen = pending.pop(0)
                    skipped.append((unseen, "not visible on every host "
                                    "(divergent step listing); skipped "
                                    "without quarantine"))
                    log(f"[tpudp] WARNING: checkpoint {unseen} is not "
                        "visible on every host (divergent step listing); "
                        "skipping it WITHOUT quarantine and falling back "
                        "to the newest step all hosts can see")
            first_round = False
        path = pending[0]
        step_no = _step_of(path)
        # Phase 1 — cheap symmetric pre-check, VOTED before any host
        # enters the collective restore: a dir saved multi-host (it has
        # per-host shard manifests) without its COMMITTED marker is a
        # torn two-phase commit; alignment above pinned the step number,
        # so no host ends up alone inside orbax's collective
        # deserialization.
        reason = None
        if host_manifest_paths(path) and not is_committed(path):
            reason = "uncommitted multi-host save (torn two-phase commit)"
        if not all_hosts_ok(reason is None, step_no):
            reason = reason or ("rejected by a peer host's vote "
                                "(torn commit on another host)")
        else:
            # Phase 2 — collective restore + local shard verification,
            # then a second vote: a byte flipped in ONE host's shard is
            # seen by that host alone, and must reject the dir for all.
            state, coverage = None, None
            try:
                if multihost:
                    state = restore_checkpoint(path, target, verify=False)
                    ok, detail, coverage = verify_restored_coverage(
                        path, state)
                    if not ok:
                        raise CheckpointCorruptError(
                            f"checkpoint {path} corrupt: {detail}")
                else:
                    state = restore_checkpoint(path, target, verify=True)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                reason = f"{type(e).__name__}: {e}"
            if all_hosts_ok(reason is None, step_no):
                # tpudp: lint-ok(protocol-early-exit): the ternary's
                # arm choice is host-uniform in practice — coverage has
                # one flag per manifest shard record, and every host
                # reads the SAME manifest files in the same order
                # (verify_restored_coverage's documented contract), so
                # `coverage` is empty on every host or on none and all
                # hosts enter the coverage-union gather together.
                uncovered = (_coverage_union_uncovered(coverage)
                             if multihost and coverage else 0)
                if not uncovered:
                    return state, path, skipped
                # Every host verified fine LOCALLY, but some records were
                # addressable on no host — a grown/resharded topology
                # cannot re-verify those bytes, and accepting them would
                # report 'verified' while covering nothing.  The bytes
                # are not (known) corrupt, so skip WITHOUT quarantine.
                skipped.append((path, f"{uncovered} shard record(s) "
                                "addressable on no host (grown/resharded "
                                "topology cannot re-verify them); skipped "
                                "without quarantine"))
                log(f"[tpudp] WARNING: checkpoint {path} has {uncovered} "
                    "shard record(s) this topology cannot re-verify "
                    "(grown/resharded pod); refusing to restore it "
                    "UNVERIFIED — skipping without quarantine.  Restore "
                    "once at a geometry that covers the saved shards to "
                    "re-checkpoint for this one.")
                pending.pop(0)
                continue
            reason = reason or ("rejected by a peer host's vote "
                                "(corrupt shard on another host)")
        skipped.append((path, reason))
        log(f"[tpudp] WARNING: checkpoint {path} unrestorable "
            f"({reason}); quarantining it and falling back to the "
            "previous step dir")
        if not multihost or jax.process_index() == 0:
            quarantine_step_dir(path)
        # No host may probe the next dir while the rename is in flight.
        # The tag is keyed by the ALIGNED step number, identical on every
        # host by construction.
        _barrier(f"tpudp_ckpt_quarantine:step_{step_no}")
        pending.pop(0)
    raise RuntimeError(
        f"every step_N checkpoint under {os.fspath(root)!r} is corrupt or "
        f"torn ({len(skipped)} tried); refusing to silently restart from "
        "scratch — remove the directory to train fresh")


def _coverage_union_uncovered(coverage: list[bool]) -> int:
    """COLLECTIVE: allgather the per-host record-coverage flags from
    :func:`verify_restored_coverage` (same length on every host — all
    read the same manifest files) and return how many records NO host
    could address/check.  Zero means the union of host views re-verified
    every saved shard byte."""
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    local = jnp.asarray([1 if c else 0 for c in coverage], jnp.int32)
    allc = np.asarray(multihost_utils.process_allgather(local))
    return int((allc.max(axis=0) == 0).sum())


def restore_emergency_voted(root: str | os.PathLike, emerg: str,
                            target: Any, *, log=print) -> Any | None:
    """Restore + verify the emergency dump at ``emerg`` with the
    accept/quarantine decision UNANIMOUS across hosts (``all_hosts_ok``):
    a dump whose shard is corrupt on ONE host must be rejected by ALL
    hosts, or replicas resume from different states.  Returns the
    restored state, or None if the dump was rejected — in which case
    process 0 has quarantined it (``.corrupt``) behind a barrier and the
    caller falls back to the step_N series.  The one emergency-dump
    accept protocol, shared by the CLI resume and the supervisor's
    ``auto_resume``."""
    state, err = None, None
    try:
        state = restore_checkpoint(emerg, target, verify=True)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as e:
        err = e
    if all_hosts_ok(err is None):
        return state
    log(f"[tpudp] WARNING: emergency dump {emerg} failed "
        f"restore/verification "
        f"({err if err is not None else 'on a peer host'}); quarantining "
        "it and falling back to the epoch checkpoint series")
    if jax.process_index() == 0:
        quarantine_emergency(root)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("tpudp_emergency_quarantine")
    return None


class AsyncCheckpointWriter:
    """Checkpoint writes overlapped with training (beyond-reference; the
    reference has no checkpointing at all, SURVEY.md §5).

    ``save()`` snapshots the device arrays and returns as soon as the copy
    is staged; serialization + filesystem IO proceed on orbax's background
    threads while the TPU keeps training the next epoch.  A new ``save()``
    (and ``close()``) blocks until the previous write committed, so at most
    one write is in flight and a crash can only lose the newest checkpoint
    — the previous one is always complete on disk.

    Usage::

        writer = AsyncCheckpointWriter()
        try:
            for epoch ...:
                train_epoch(...)
                writer.save(f"{root}/step_{epoch}", trainer.state)
        finally:
            writer.close()  # join the last write
    """

    def __init__(self):
        if not HAVE_ORBAX:
            raise RuntimeError("orbax-checkpoint is not installed")
        self._ckpt = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        # Multi-host two-phase commit, DEFERRED: the COMMITTED marker may
        # only be written after every host's async write finalized, which
        # an async save() cannot wait for — the pending path is committed
        # (barrier + marker) by the next save()/wait()/close(), each of
        # which first joins the in-flight write.  Until then the dir is
        # detectably torn, exactly like a sync save killed mid-barrier.
        self._pending_commit: str | None = None

    def _commit_pending(self) -> None:
        """Barrier + COMMITTED marker for the previous multi-host save.
        Callers must have joined that write (``wait_until_finished``)
        first — the marker asserts durability on EVERY host."""
        if self._pending_commit is None:
            return
        path, self._pending_commit = self._pending_commit, None
        commit_after_all_hosts(path)

    def save(self, path: str | os.PathLike, state: Any, *,
             force: bool = True, manifest: bool = True) -> str:
        path = os.path.abspath(os.fspath(path))
        if self._pending_commit is not None:
            self._ckpt.wait_until_finished()
            self._commit_pending()
        multihost = manifest and jax.process_count() > 1
        if multihost:
            # Same stale-sidecar invalidation as the sync saver: a
            # leftover marker under this name would mark the new
            # in-flight write committed before any byte landed.
            from jax.experimental import multihost_utils

            invalidate_commit(path)
            multihost_utils.sync_global_devices(
                f"tpudp_async_ckpt_invalidate:{os.path.basename(path)}")
        elif manifest:
            # Same stale-sidecar hazard as the sync saver: a shrunken
            # pod's single-host re-save must not inherit a larger pod's
            # host manifests under this name.
            invalidate_commit(path)
        self._ckpt.save(path, state, force=force)
        if manifest:
            # Checksums must be computed NOW, before the caller's next
            # donating step invalidates the device buffers (orbax staged
            # its own device->host copy inside save for the same reason).
            # The manifest may exist before the directory finalizes; a
            # crash mid-write then leaves a torn dir whose verification
            # fails, which is exactly the signal the fallback walk needs.
            write_manifest(path, state)
            if multihost:
                self._pending_commit = path
        return path

    def wait(self) -> None:
        """Block until every started save has committed to disk (and, on
        multi-host, carries its COMMITTED marker)."""
        self._ckpt.wait_until_finished()
        self._commit_pending()

    def close(self) -> None:
        """Join outstanding writes and release the background threads."""
        self._ckpt.wait_until_finished()
        self._commit_pending()
        self._ckpt.close()

    def __enter__(self) -> "AsyncCheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_EMERGENCY_SENTINEL = "emergency.COMPLETE"


def _emergency_sentinel_path(root: str | os.PathLike) -> str:
    return os.path.join(os.fspath(root), _EMERGENCY_SENTINEL)


def clear_emergency_sentinel(root: str | os.PathLike) -> None:
    """Invalidate the emergency dump BEFORE a new dump starts writing (or
    after a restore consumes it) — a stale sentinel next to a half-written
    dump would make the truncated dump look restorable."""
    try:
        os.unlink(_emergency_sentinel_path(root))
    except FileNotFoundError:
        pass


def write_emergency_sentinel(root: str | os.PathLike,
                             step: int | None = None,
                             per_epoch_batches: int | None = None) -> None:
    """Mark the emergency dump complete.  Call ONLY after the orbax save
    returned (finalization done): the dumping thread is abandoned after a
    timeout and the process exits (tpudp/cli.py), so a dump directory can
    be left half-written — the sentinel is the commit record that
    distinguishes a restorable dump from a truncated one.

    ``per_epoch_batches`` records the interrupted run's loader length so a
    resume can verify the step counter still maps onto the same batch grid
    — a relaunch with a different --batch-size or train-set size would
    otherwise silently re-train or drop batches (round-3 advisor)."""
    import json
    import time

    with open(_emergency_sentinel_path(root), "w") as f:
        json.dump({"step": step,
                   "per_epoch_batches": per_epoch_batches,
                   "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime())}, f)


def read_emergency_sentinel(root: str | os.PathLike) -> dict | None:
    """The sentinel's JSON payload, or None if absent/unreadable (dumps
    from before the sentinel carried data, or accepted via orbax's own
    finalization metadata)."""
    import json

    try:
        with open(_emergency_sentinel_path(root)) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError, OSError):
        return None


def consume_emergency(root: str | os.PathLike) -> str:
    """Consume a restored emergency dump: rename ``root/emergency`` to
    ``emergency.restored`` (replacing any previous one) and clear the
    sentinel, so later resumes fall back to the ``step_N`` series.  The
    single implementation behind the CLI resume, ``auto_resume``, and the
    supervisor's in-process step recovery.  Multi-host integrity sidecars
    (per-host shard manifests, COMMITTED marker) leave with the dir: a
    stale host manifest left at the base name would be read against the
    NEXT dump's bytes (e.g. a single-host dump after the pod shrank) and
    reject every future dump at this root forever."""
    root = os.fspath(root)
    emerg = os.path.join(root, "emergency")
    consumed = emerg + ".restored"
    sidecars = _sidecar_paths(emerg)  # enumerate BEFORE the rename
    if os.path.isdir(consumed):
        import shutil

        shutil.rmtree(consumed)
    os.rename(emerg, consumed)
    for src in sidecars:
        try:
            os.replace(src, consumed + src[len(os.path.abspath(emerg)):])
        except OSError:
            pass
    clear_emergency_sentinel(root)
    return consumed


def quarantine_emergency(root: str | os.PathLike) -> str | None:
    """Move a corrupt/unverifiable emergency dump aside (to
    ``emergency.corrupt``, bytes kept for forensics) and clear its
    sentinel so resume falls back to the ``step_N`` series instead of
    crash-looping.  Returns the quarantine path, or None if the rename
    failed (the sentinel is still cleared, which alone stops the loop)."""
    root = os.fspath(root)
    emerg = os.path.join(root, "emergency")
    target = emerg + ".corrupt"
    sidecars = _sidecar_paths(emerg)  # enumerate BEFORE the rename
    moved = None
    try:
        if os.path.isdir(target):
            import shutil

            shutil.rmtree(target)
        os.rename(emerg, target)
        moved = target
        # Sidecars leave with the dir (see consume_emergency): a stale
        # host manifest at the base name would reject every future dump.
        for src in sidecars:
            try:
                os.replace(src,
                           target + src[len(os.path.abspath(emerg)):])
            except OSError:
                pass
    except OSError:
        pass
    clear_emergency_sentinel(root)
    return moved


def emergency_dir(root: str | os.PathLike) -> str | None:
    """Return the watchdog's emergency-dump directory if a COMPLETE one
    exists.

    The watchdog saves a mid-epoch TrainState to ``root/emergency`` when it
    detects a hang (see tpudp/cli.py); callers restore it in preference to
    the epoch-level ``step_N`` series and then consume (rename) it.  The
    dump counts only if its sentinel (written after orbax finalization)
    is present: the dump thread is abandoned on timeout, and restoring a
    truncated dump would crash-loop every subsequent resume (round-2 judge
    finding) — without the sentinel the dump is ignored (with a warning)
    and the caller falls back to the epoch ``step_N`` series."""
    root = os.fspath(root)
    path = os.path.join(root, "emergency")
    if not os.path.isdir(path):
        return None
    if os.path.exists(_emergency_sentinel_path(root)):
        return path
    # No sentinel — accept orbax's own finalization metadata as the
    # completeness signal instead (covers dumps written before the
    # sentinel existed: orbax's atomic commit writes _CHECKPOINT_METADATA
    # only at finalization).
    if os.path.exists(os.path.join(path, "_CHECKPOINT_METADATA")):
        return path
    # Truncated.  Move it aside so the ignore is one-shot (bytes kept for
    # manual forensics) instead of re-warning on every subsequent resume.
    quarantined = path + ".truncated"
    try:
        if os.path.isdir(quarantined):
            import shutil

            shutil.rmtree(quarantined)
        os.rename(path, quarantined)
        moved = f"; moved to {quarantined}"
    except OSError as e:
        moved = f"; could not move aside ({e})"
    print(f"[tpudp] WARNING: ignoring emergency dump {path} — no "
          "completion sentinel or orbax metadata (the dump was "
          f"interrupted mid-write){moved}; falling back to the epoch "
          "checkpoint series")
    return None


_STEP_DIR = re.compile(r"^step_(\d+)$")


def prune_step_dirs(root: str | os.PathLike, keep: int) -> list[str]:
    """Delete all but the newest ``keep`` ``step_N`` checkpoints under
    ``root``; returns the deleted paths.  Only exact ``step_<digits>``
    directories are candidates — orbax tmp dirs and the emergency dump are
    never touched, and the newest VERIFIABLE checkpoint (one carrying an
    integrity manifest or orbax's finalization metadata) is never deleted
    even when it falls outside the keep window: if the newer retained dirs
    are all torn, that dir is the only restorable state left and pruning
    it would make the next resume impossible (docs/RESILIENCE.md).
    A pruned dir's sidecars (manifest, per-host shard manifests, commit
    marker) are deleted with it.  Residual window: SILENT rot of a
    never-yet-restored newest dir keeps its manifest, so the protection
    can still pick it while ``keep=1`` deletes the intact older dir —
    restore-time rejection quarantines corrupt dirs out of the series,
    but only once a restore has actually run; prefer ``keep >= 2`` when
    the storage is suspect.

    Multi-host: ONLY process 0 deletes (enforced here — on any other
    process this is a no-op, so a caller that forgets the rank guard
    cannot race N deleters against each other), and a dir carrying
    per-host shard manifests but no COMMITTED marker is never deleted:
    the marker is the two-phase-commit proof that every host finished
    writing, so a marker-less dir may still be mid-write by a peer (the
    cross-host prune race) — it is skipped and left for the verified
    walk to quarantine as torn."""
    import shutil

    root = os.fspath(root)
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    if jax.process_index() != 0:
        return []
    newest_first = step_dirs_newest_first(root)  # the one scan the
    # restore-fallback walk uses too — prune and restore can't disagree
    # about what the series contains
    protected = next(
        (path for path in newest_first
         if os.path.exists(manifest_path(path))
         or (host_manifest_paths(path) and is_committed(path))
         or os.path.exists(os.path.join(path, "_CHECKPOINT_METADATA"))),
        None)
    deleted = []
    for path in list(reversed(newest_first))[:-keep]:
        if path == protected:
            continue
        if host_manifest_paths(path) and not is_committed(path):
            # Possibly still being written by another host (its manifest
            # landed, the commit barrier has not): deleting under a
            # writer tears the save AND the writer.  Leave it; the
            # verified walk quarantines it if it really is torn.
            continue
        sidecars = _sidecar_paths(path)  # enumerate BEFORE the rmtree
        try:
            shutil.rmtree(path)
        except OSError as e:
            print(f"[tpudp] WARNING: could not prune checkpoint {path}: {e}")
            continue
        for sidecar in sidecars:
            try:
                os.unlink(sidecar)
            except FileNotFoundError:
                pass
            except OSError as e:  # same tolerance as the rmtree above: a
                # housekeeping failure must never kill (or, under the
                # supervisor, fault-retry) the training run
                print(f"[tpudp] WARNING: could not remove sidecar of "
                      f"pruned checkpoint {path}: {e}")
        deleted.append(path)
    return deleted


def ensure_writable(root: str | os.PathLike) -> str:
    """Fail-fast probe for --save-checkpoint flags: verify orbax is
    importable and the destination is creatable/writable BEFORE any
    compute is spent — a save error discovered after a long training run
    loses the run (round-4 review finding)."""
    if not HAVE_ORBAX:
        raise RuntimeError(
            "orbax-checkpoint is not installed; --save-checkpoint cannot "
            "work — aborting before training rather than after")
    root = os.path.abspath(os.fspath(root))
    os.makedirs(root, exist_ok=True)
    probe = os.path.join(root, ".write_probe")
    with open(probe, "w") as f:
        f.write("ok")
    os.unlink(probe)
    return root


def restore_params(path: str | os.PathLike):
    """Restore ONLY the ``params`` subtree of a saved TrainState.

    Decode/eval tools need the weights, not the optimizer state — and a
    full-TrainState ``restore_checkpoint`` target must structurally match
    the optimizer the checkpoint was saved with (clip/skip wrappers add
    state leaves), which a standalone tool cannot know.  Restoring the
    raw tree target-free and slicing ``params`` sidesteps the mismatch.
    """
    if not HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not installed")
    raw = _checkpointer().restore(os.path.abspath(os.fspath(path)))
    try:
        return raw["params"]
    except (KeyError, TypeError, IndexError):
        raise ValueError(
            f"{os.fspath(path)!r} holds no 'params' subtree — not a saved "
            "TrainState?") from None


def latest_step_dir(root: str | os.PathLike) -> str | None:
    """Return the highest-numbered ``step_N`` subdirectory, or None.

    Only exact ``step_<digits>`` names count — orbax leaves
    ``step_N.orbax-checkpoint-tmp-*`` directories behind after an
    interrupted save (and the resilience layer quarantines corrupt dirs
    as ``step_N.corrupt``), and those must never be selected (or
    parsed)."""
    dirs = step_dirs_newest_first(root)
    return dirs[0] if dirs else None

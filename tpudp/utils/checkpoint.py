"""Checkpoint / resume via orbax.

The reference has NO checkpointing (SURVEY.md §5: "training state lives and
dies with the process") — this is a beyond-reference capability: save and
restore the full :class:`tpudp.train.TrainState` (params, BatchNorm stats,
optimizer state, step counter) so training resumes exactly where it stopped.
Sharded arrays round-trip with their shardings on multi-device meshes.
"""

from __future__ import annotations

import os
import re
from typing import Any

import jax

try:
    import orbax.checkpoint as ocp

    HAVE_ORBAX = True
except ImportError:  # pragma: no cover - orbax is baked into this image
    HAVE_ORBAX = False


def _checkpointer():
    return ocp.PyTreeCheckpointer()


def save_checkpoint(path: str | os.PathLike, state: Any, *, force: bool = True) -> str:
    """Write ``state`` (any pytree, e.g. TrainState) to ``path``."""
    if not HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not installed")
    path = os.path.abspath(os.fspath(path))
    _checkpointer().save(path, state, force=force)
    return path


def restore_checkpoint(path: str | os.PathLike, target: Any) -> Any:
    """Restore a pytree saved by :func:`save_checkpoint`.

    ``target`` is a matching pytree (e.g. a freshly built TrainState) used
    for structure, dtypes, and shardings; its values are not read.
    """
    if not HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not installed")
    path = os.path.abspath(os.fspath(path))

    # ELASTIC resume (checkpoint saved on an N-device mesh, restored on an
    # M-device one — the pod shrank after a failure, or grew): the sharding
    # must reach orbax's DESERIALIZATION layer via restore_args, so each
    # array materializes directly on the CURRENT topology; the recorded
    # sharding file names save-time devices that may no longer exist, and
    # post-hoc device_put never runs if deserialization already failed.
    # A COMMITTED target leaf is an intentional statement of the current
    # topology — its sharding is forwarded.  An UNCOMMITTED leaf (fresh
    # init_state before any mesh placement, e.g. the same-topology CLI
    # resume path) carries no placement intent: no sharding is forwarded
    # and orbax falls back to the checkpoint's recorded sharding, which is
    # only valid while the save-time devices still exist — elastic flows
    # must pass a placed target.
    def as_abstract(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=x.sharding if x.committed
                                        else None)
        return ocp.utils.to_shape_dtype_struct(x)

    abstract = jax.tree.map(as_abstract, target)
    restore_args = ocp.checkpoint_utils.construct_restore_args(abstract)
    return _checkpointer().restore(path, item=abstract,
                                   restore_args=restore_args)


class AsyncCheckpointWriter:
    """Checkpoint writes overlapped with training (beyond-reference; the
    reference has no checkpointing at all, SURVEY.md §5).

    ``save()`` snapshots the device arrays and returns as soon as the copy
    is staged; serialization + filesystem IO proceed on orbax's background
    threads while the TPU keeps training the next epoch.  A new ``save()``
    (and ``close()``) blocks until the previous write committed, so at most
    one write is in flight and a crash can only lose the newest checkpoint
    — the previous one is always complete on disk.

    Usage::

        writer = AsyncCheckpointWriter()
        try:
            for epoch ...:
                train_epoch(...)
                writer.save(f"{root}/step_{epoch}", trainer.state)
        finally:
            writer.close()  # join the last write
    """

    def __init__(self):
        if not HAVE_ORBAX:
            raise RuntimeError("orbax-checkpoint is not installed")
        self._ckpt = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())

    def save(self, path: str | os.PathLike, state: Any, *,
             force: bool = True) -> str:
        path = os.path.abspath(os.fspath(path))
        self._ckpt.save(path, state, force=force)
        return path

    def wait(self) -> None:
        """Block until every started save has committed to disk."""
        self._ckpt.wait_until_finished()

    def close(self) -> None:
        """Join outstanding writes and release the background threads."""
        self._ckpt.close()

    def __enter__(self) -> "AsyncCheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_EMERGENCY_SENTINEL = "emergency.COMPLETE"


def _emergency_sentinel_path(root: str | os.PathLike) -> str:
    return os.path.join(os.fspath(root), _EMERGENCY_SENTINEL)


def clear_emergency_sentinel(root: str | os.PathLike) -> None:
    """Invalidate the emergency dump BEFORE a new dump starts writing (or
    after a restore consumes it) — a stale sentinel next to a half-written
    dump would make the truncated dump look restorable."""
    try:
        os.unlink(_emergency_sentinel_path(root))
    except FileNotFoundError:
        pass


def write_emergency_sentinel(root: str | os.PathLike,
                             step: int | None = None,
                             per_epoch_batches: int | None = None) -> None:
    """Mark the emergency dump complete.  Call ONLY after the orbax save
    returned (finalization done): the dumping thread is abandoned after a
    timeout and the process exits (tpudp/cli.py), so a dump directory can
    be left half-written — the sentinel is the commit record that
    distinguishes a restorable dump from a truncated one.

    ``per_epoch_batches`` records the interrupted run's loader length so a
    resume can verify the step counter still maps onto the same batch grid
    — a relaunch with a different --batch-size or train-set size would
    otherwise silently re-train or drop batches (round-3 advisor)."""
    import json
    import time

    with open(_emergency_sentinel_path(root), "w") as f:
        json.dump({"step": step,
                   "per_epoch_batches": per_epoch_batches,
                   "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime())}, f)


def read_emergency_sentinel(root: str | os.PathLike) -> dict | None:
    """The sentinel's JSON payload, or None if absent/unreadable (dumps
    from before the sentinel carried data, or accepted via orbax's own
    finalization metadata)."""
    import json

    try:
        with open(_emergency_sentinel_path(root)) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError, OSError):
        return None


def emergency_dir(root: str | os.PathLike) -> str | None:
    """Return the watchdog's emergency-dump directory if a COMPLETE one
    exists.

    The watchdog saves a mid-epoch TrainState to ``root/emergency`` when it
    detects a hang (see tpudp/cli.py); callers restore it in preference to
    the epoch-level ``step_N`` series and then consume (rename) it.  The
    dump counts only if its sentinel (written after orbax finalization)
    is present: the dump thread is abandoned on timeout, and restoring a
    truncated dump would crash-loop every subsequent resume (round-2 judge
    finding) — without the sentinel the dump is ignored (with a warning)
    and the caller falls back to the epoch ``step_N`` series."""
    root = os.fspath(root)
    path = os.path.join(root, "emergency")
    if not os.path.isdir(path):
        return None
    if os.path.exists(_emergency_sentinel_path(root)):
        return path
    # No sentinel — accept orbax's own finalization metadata as the
    # completeness signal instead (covers dumps written before the
    # sentinel existed: orbax's atomic commit writes _CHECKPOINT_METADATA
    # only at finalization).
    if os.path.exists(os.path.join(path, "_CHECKPOINT_METADATA")):
        return path
    # Truncated.  Move it aside so the ignore is one-shot (bytes kept for
    # manual forensics) instead of re-warning on every subsequent resume.
    quarantined = path + ".truncated"
    try:
        if os.path.isdir(quarantined):
            import shutil

            shutil.rmtree(quarantined)
        os.rename(path, quarantined)
        moved = f"; moved to {quarantined}"
    except OSError as e:
        moved = f"; could not move aside ({e})"
    print(f"[tpudp] WARNING: ignoring emergency dump {path} — no "
          "completion sentinel or orbax metadata (the dump was "
          f"interrupted mid-write){moved}; falling back to the epoch "
          "checkpoint series")
    return None


_STEP_DIR = re.compile(r"^step_(\d+)$")


def prune_step_dirs(root: str | os.PathLike, keep: int) -> list[str]:
    """Delete all but the newest ``keep`` ``step_N`` checkpoints under
    ``root``; returns the deleted paths.  Only exact ``step_<digits>``
    directories are candidates — orbax tmp dirs and the emergency dump are
    never touched.  Multi-host callers should invoke this on process 0
    only, after the save for the newest step has committed (the sync
    saver and AsyncCheckpointWriter's serialized saves both guarantee the
    PREVIOUS step is durable by then, so the retained set is always
    restorable)."""
    import shutil

    root = os.fspath(root)
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    if not os.path.isdir(root):
        return []
    steps = sorted((int(m.group(1)), m.group(0))
                   for d in os.listdir(root) if (m := _STEP_DIR.match(d)))
    deleted = []
    for _, name in steps[:-keep]:
        path = os.path.join(root, name)
        try:
            shutil.rmtree(path)
        except OSError as e:
            print(f"[tpudp] WARNING: could not prune checkpoint {path}: {e}")
            continue
        deleted.append(path)
    return deleted


def ensure_writable(root: str | os.PathLike) -> str:
    """Fail-fast probe for --save-checkpoint flags: verify orbax is
    importable and the destination is creatable/writable BEFORE any
    compute is spent — a save error discovered after a long training run
    loses the run (round-4 review finding)."""
    if not HAVE_ORBAX:
        raise RuntimeError(
            "orbax-checkpoint is not installed; --save-checkpoint cannot "
            "work — aborting before training rather than after")
    root = os.path.abspath(os.fspath(root))
    os.makedirs(root, exist_ok=True)
    probe = os.path.join(root, ".write_probe")
    with open(probe, "w") as f:
        f.write("ok")
    os.unlink(probe)
    return root


def restore_params(path: str | os.PathLike):
    """Restore ONLY the ``params`` subtree of a saved TrainState.

    Decode/eval tools need the weights, not the optimizer state — and a
    full-TrainState ``restore_checkpoint`` target must structurally match
    the optimizer the checkpoint was saved with (clip/skip wrappers add
    state leaves), which a standalone tool cannot know.  Restoring the
    raw tree target-free and slicing ``params`` sidesteps the mismatch.
    """
    if not HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not installed")
    raw = _checkpointer().restore(os.path.abspath(os.fspath(path)))
    try:
        return raw["params"]
    except (KeyError, TypeError, IndexError):
        raise ValueError(
            f"{os.fspath(path)!r} holds no 'params' subtree — not a saved "
            "TrainState?") from None


def latest_step_dir(root: str | os.PathLike) -> str | None:
    """Return the highest-numbered ``step_N`` subdirectory, or None.

    Only exact ``step_<digits>`` names count — orbax leaves
    ``step_N.orbax-checkpoint-tmp-*`` directories behind after an
    interrupted save, and those must never be selected (or parsed)."""
    root = os.fspath(root)
    if not os.path.isdir(root):
        return None
    steps = [m for d in os.listdir(root) if (m := _STEP_DIR.match(d))]
    if not steps:
        return None
    best = max(steps, key=lambda m: int(m.group(1)))
    return os.path.join(root, best.group(0))

"""Checkpoint / resume via orbax, with per-leaf integrity manifests.

The reference has NO checkpointing (SURVEY.md §5: "training state lives and
dies with the process") — this is a beyond-reference capability: save and
restore the full :class:`tpudp.train.TrainState` (params, BatchNorm stats,
optimizer state, step counter) so training resumes exactly where it stopped.
Sharded arrays round-trip with their shardings on multi-device meshes.

Integrity (the resilience layer's restore oracle, docs/RESILIENCE.md):
every save also writes a per-leaf crc32 manifest beside the checkpoint
directory (``<path>.manifest.json``).  ``restore_checkpoint(...,
verify=True)`` recomputes the checksums on the restored arrays and raises
:class:`CheckpointCorruptError` on any mismatch, and
:func:`restore_latest_verified` walks the ``step_N`` series newest→oldest
so a torn or bit-flipped newest checkpoint falls back to the previous
intact one instead of crash-looping every resume.
"""

from __future__ import annotations

import os
import re
from typing import Any

import jax

try:
    import orbax.checkpoint as ocp

    HAVE_ORBAX = True
except ImportError:  # pragma: no cover - orbax is baked into this image
    HAVE_ORBAX = False


class CheckpointCorruptError(RuntimeError):
    """A checkpoint restored cleanly but its bytes do not match the
    per-leaf checksum manifest written at save time — silent corruption
    (bit flip, torn write orbax did not catch).  Typed so resume flows can
    fall back to an older checkpoint instead of crashing."""


def _checkpointer():
    return ocp.PyTreeCheckpointer()


def manifest_path(path: str | os.PathLike) -> str:
    """The integrity manifest lives BESIDE the checkpoint directory (not
    inside it): orbax's item-free restore (:func:`restore_params`) scans
    the directory to infer the tree, and a foreign file inside would be
    misread as a leaf."""
    return os.path.abspath(os.fspath(path)) + ".manifest.json"


def leaf_checksums(state: Any) -> dict:
    """Per-leaf crc32/dtype/shape over the pytree, keyed by
    ``jax.tree_util.keystr`` path.  Leaves that are not fully addressable
    on this process (multi-host shards) are recorded as skipped — a
    checksum over a partial host view would be topology-dependent."""
    import zlib

    import numpy as np

    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        key = jax.tree_util.keystr(path)
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            out[key] = {"skipped": "not fully addressable"}
            continue
        arr = np.asarray(leaf)
        out[key] = {"crc32": zlib.crc32(arr.tobytes()) & 0xFFFFFFFF,
                    "dtype": str(arr.dtype), "shape": list(arr.shape)}
    return out


def write_manifest(path: str | os.PathLike, state: Any) -> str:
    """Write the per-leaf checksum manifest for the checkpoint at ``path``
    (process 0 only on multi-host — one writer per file)."""
    import json

    mpath = manifest_path(path)
    if jax.process_index() != 0:
        return mpath
    with open(mpath, "w") as f:
        json.dump({"format": 1, "leaves": leaf_checksums(state)}, f)
    return mpath


def read_manifest(path: str | os.PathLike) -> dict | None:
    """The manifest payload for the checkpoint at ``path``, or None if
    absent/unreadable (checkpoints saved before manifests existed)."""
    import json

    try:
        with open(manifest_path(path)) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError, OSError):
        return None


def verify_restored(path: str | os.PathLike, state: Any) -> tuple[bool, str]:
    """Compare ``state`` (a freshly restored pytree) against the manifest
    written when ``path`` was saved.  Returns ``(ok, detail)``; a missing
    manifest verifies vacuously (legacy checkpoints carry none)."""
    manifest = read_manifest(path)
    if manifest is None:
        return True, "no manifest (unverified legacy checkpoint)"
    want = manifest.get("leaves", {})
    have = leaf_checksums(state)
    for key, rec in want.items():
        if "crc32" not in rec:
            continue  # skipped at save time (non-addressable leaf)
        got = have.get(key)
        if got is None:
            return False, f"leaf {key} missing from restored tree"
        if got.get("crc32") != rec["crc32"]:
            return False, (f"leaf {key} checksum mismatch "
                           f"(saved {rec['crc32']}, restored {got.get('crc32')})")
    return True, f"{len(want)} leaves verified"


def save_checkpoint(path: str | os.PathLike, state: Any, *,
                    force: bool = True, manifest: bool = True) -> str:
    """Write ``state`` (any pytree, e.g. TrainState) to ``path``.

    ``manifest=True`` (default) also writes the per-leaf checksum manifest
    beside the directory, making this checkpoint verifiable by
    ``restore_checkpoint(..., verify=True)`` and eligible as a fallback
    target for :func:`restore_latest_verified`."""
    if not HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not installed")
    path = os.path.abspath(os.fspath(path))
    _checkpointer().save(path, state, force=force)
    if manifest:
        write_manifest(path, state)
    return path


def restore_checkpoint(path: str | os.PathLike, target: Any, *,
                       verify: bool = False) -> Any:
    """Restore a pytree saved by :func:`save_checkpoint`.

    ``target`` is a matching pytree (e.g. a freshly built TrainState) used
    for structure, dtypes, and shardings; its values are not read.

    ``verify=True`` recomputes per-leaf checksums on the restored arrays
    against the save-time manifest and raises
    :class:`CheckpointCorruptError` on mismatch (a checkpoint without a
    manifest passes vacuously — there is nothing to compare).
    """
    if not HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not installed")
    path = os.path.abspath(os.fspath(path))

    # ELASTIC resume (checkpoint saved on an N-device mesh, restored on an
    # M-device one — the pod shrank after a failure, or grew): the sharding
    # must reach orbax's DESERIALIZATION layer via restore_args, so each
    # array materializes directly on the CURRENT topology; the recorded
    # sharding file names save-time devices that may no longer exist, and
    # post-hoc device_put never runs if deserialization already failed.
    # A COMMITTED target leaf is an intentional statement of the current
    # topology — its sharding is forwarded.  An UNCOMMITTED leaf (fresh
    # init_state before any mesh placement, e.g. the same-topology CLI
    # resume path) carries no placement intent: no sharding is forwarded
    # and orbax falls back to the checkpoint's recorded sharding, which is
    # only valid while the save-time devices still exist — elastic flows
    # must pass a placed target.
    def as_abstract(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=x.sharding if x.committed
                                        else None)
        return ocp.utils.to_shape_dtype_struct(x)

    abstract = jax.tree.map(as_abstract, target)
    restore_args = ocp.checkpoint_utils.construct_restore_args(abstract)
    restored = _checkpointer().restore(path, item=abstract,
                                       restore_args=restore_args)
    if verify:
        ok, detail = verify_restored(path, restored)
        if not ok:
            raise CheckpointCorruptError(f"checkpoint {path} corrupt: {detail}")
    return restored


def step_dirs_newest_first(root: str | os.PathLike) -> list[str]:
    """All exact ``step_<digits>`` directories under ``root``, newest
    (highest N) first — the fallback walk order for
    :func:`restore_latest_verified`."""
    root = os.fspath(root)
    if not os.path.isdir(root):
        return []
    steps = sorted((int(m.group(1)), m.group(0))
                   for d in os.listdir(root) if (m := _STEP_DIR.match(d)))
    return [os.path.join(root, name) for _, name in reversed(steps)]


def quarantine_step_dir(path: str) -> None:
    """Move a rejected ``step_N`` dir (and its manifest) aside to
    ``step_N.corrupt``, removing it from the step series: later walks must
    not re-count the same corruption, ``latest_step_dir``/pruning must not
    treat it as live state, and the bytes stay for forensics.  Rename
    races (multi-host: every process walks the series) are tolerated —
    whichever rename wins, the dir leaves the series."""
    import shutil

    target = path + ".corrupt"
    try:
        if os.path.isdir(target):
            shutil.rmtree(target)
        os.rename(path, target)
    except OSError:
        return
    try:
        os.replace(manifest_path(path), manifest_path(target))
    except OSError:
        pass


def restore_latest_verified(root: str | os.PathLike, target: Any, *,
                            log=print) -> tuple[Any, str, list[tuple[str, str]]]:
    """Restore the newest INTACT ``step_N`` checkpoint under ``root``.

    Walks the step series newest→oldest; a directory that fails to restore
    (torn write, missing files) or fails its checksum manifest is
    QUARANTINED (renamed ``step_N.corrupt`` — out of the series, so the
    same corruption is never re-counted and pruning can't mistake it for
    live state) with a logged warning, and the walk falls back to the
    previous one — a corrupted newest checkpoint must never crash-loop
    resume (docs/RESILIENCE.md).  Returns ``(state, path, skipped)``
    where ``skipped`` lists ``(path, reason)`` for every rejected newer
    checkpoint.  Raises FileNotFoundError if no step dirs exist and
    RuntimeError if none of them is restorable."""
    dirs = step_dirs_newest_first(root)
    if not dirs:
        raise FileNotFoundError(f"no step_N checkpoints under {os.fspath(root)!r}")
    skipped: list[tuple[str, str]] = []
    for path in dirs:
        try:
            state = restore_checkpoint(path, target, verify=True)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            reason = f"{type(e).__name__}: {e}"
            skipped.append((path, reason))
            log(f"[tpudp] WARNING: checkpoint {path} unrestorable "
                f"({reason}); quarantining it and falling back to the "
                "previous step dir")
            quarantine_step_dir(path)
            continue
        return state, path, skipped
    raise RuntimeError(
        f"every step_N checkpoint under {os.fspath(root)!r} is corrupt or "
        f"torn ({len(skipped)} tried); refusing to silently restart from "
        "scratch — remove the directory to train fresh")


class AsyncCheckpointWriter:
    """Checkpoint writes overlapped with training (beyond-reference; the
    reference has no checkpointing at all, SURVEY.md §5).

    ``save()`` snapshots the device arrays and returns as soon as the copy
    is staged; serialization + filesystem IO proceed on orbax's background
    threads while the TPU keeps training the next epoch.  A new ``save()``
    (and ``close()``) blocks until the previous write committed, so at most
    one write is in flight and a crash can only lose the newest checkpoint
    — the previous one is always complete on disk.

    Usage::

        writer = AsyncCheckpointWriter()
        try:
            for epoch ...:
                train_epoch(...)
                writer.save(f"{root}/step_{epoch}", trainer.state)
        finally:
            writer.close()  # join the last write
    """

    def __init__(self):
        if not HAVE_ORBAX:
            raise RuntimeError("orbax-checkpoint is not installed")
        self._ckpt = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())

    def save(self, path: str | os.PathLike, state: Any, *,
             force: bool = True, manifest: bool = True) -> str:
        path = os.path.abspath(os.fspath(path))
        self._ckpt.save(path, state, force=force)
        if manifest:
            # Checksums must be computed NOW, before the caller's next
            # donating step invalidates the device buffers (orbax staged
            # its own device->host copy inside save for the same reason).
            # The manifest may exist before the directory finalizes; a
            # crash mid-write then leaves a torn dir whose verification
            # fails, which is exactly the signal the fallback walk needs.
            write_manifest(path, state)
        return path

    def wait(self) -> None:
        """Block until every started save has committed to disk."""
        self._ckpt.wait_until_finished()

    def close(self) -> None:
        """Join outstanding writes and release the background threads."""
        self._ckpt.close()

    def __enter__(self) -> "AsyncCheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_EMERGENCY_SENTINEL = "emergency.COMPLETE"


def _emergency_sentinel_path(root: str | os.PathLike) -> str:
    return os.path.join(os.fspath(root), _EMERGENCY_SENTINEL)


def clear_emergency_sentinel(root: str | os.PathLike) -> None:
    """Invalidate the emergency dump BEFORE a new dump starts writing (or
    after a restore consumes it) — a stale sentinel next to a half-written
    dump would make the truncated dump look restorable."""
    try:
        os.unlink(_emergency_sentinel_path(root))
    except FileNotFoundError:
        pass


def write_emergency_sentinel(root: str | os.PathLike,
                             step: int | None = None,
                             per_epoch_batches: int | None = None) -> None:
    """Mark the emergency dump complete.  Call ONLY after the orbax save
    returned (finalization done): the dumping thread is abandoned after a
    timeout and the process exits (tpudp/cli.py), so a dump directory can
    be left half-written — the sentinel is the commit record that
    distinguishes a restorable dump from a truncated one.

    ``per_epoch_batches`` records the interrupted run's loader length so a
    resume can verify the step counter still maps onto the same batch grid
    — a relaunch with a different --batch-size or train-set size would
    otherwise silently re-train or drop batches (round-3 advisor)."""
    import json
    import time

    with open(_emergency_sentinel_path(root), "w") as f:
        json.dump({"step": step,
                   "per_epoch_batches": per_epoch_batches,
                   "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime())}, f)


def read_emergency_sentinel(root: str | os.PathLike) -> dict | None:
    """The sentinel's JSON payload, or None if absent/unreadable (dumps
    from before the sentinel carried data, or accepted via orbax's own
    finalization metadata)."""
    import json

    try:
        with open(_emergency_sentinel_path(root)) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError, OSError):
        return None


def consume_emergency(root: str | os.PathLike) -> str:
    """Consume a restored emergency dump: rename ``root/emergency`` to
    ``emergency.restored`` (replacing any previous one) and clear the
    sentinel, so later resumes fall back to the ``step_N`` series.  The
    single implementation behind the CLI resume, ``auto_resume``, and the
    supervisor's in-process step recovery."""
    root = os.fspath(root)
    emerg = os.path.join(root, "emergency")
    consumed = emerg + ".restored"
    if os.path.isdir(consumed):
        import shutil

        shutil.rmtree(consumed)
    os.rename(emerg, consumed)
    clear_emergency_sentinel(root)
    return consumed


def quarantine_emergency(root: str | os.PathLike) -> str | None:
    """Move a corrupt/unverifiable emergency dump aside (to
    ``emergency.corrupt``, bytes kept for forensics) and clear its
    sentinel so resume falls back to the ``step_N`` series instead of
    crash-looping.  Returns the quarantine path, or None if the rename
    failed (the sentinel is still cleared, which alone stops the loop)."""
    root = os.fspath(root)
    emerg = os.path.join(root, "emergency")
    target = emerg + ".corrupt"
    moved = None
    try:
        if os.path.isdir(target):
            import shutil

            shutil.rmtree(target)
        os.rename(emerg, target)
        moved = target
    except OSError:
        pass
    clear_emergency_sentinel(root)
    return moved


def emergency_dir(root: str | os.PathLike) -> str | None:
    """Return the watchdog's emergency-dump directory if a COMPLETE one
    exists.

    The watchdog saves a mid-epoch TrainState to ``root/emergency`` when it
    detects a hang (see tpudp/cli.py); callers restore it in preference to
    the epoch-level ``step_N`` series and then consume (rename) it.  The
    dump counts only if its sentinel (written after orbax finalization)
    is present: the dump thread is abandoned on timeout, and restoring a
    truncated dump would crash-loop every subsequent resume (round-2 judge
    finding) — without the sentinel the dump is ignored (with a warning)
    and the caller falls back to the epoch ``step_N`` series."""
    root = os.fspath(root)
    path = os.path.join(root, "emergency")
    if not os.path.isdir(path):
        return None
    if os.path.exists(_emergency_sentinel_path(root)):
        return path
    # No sentinel — accept orbax's own finalization metadata as the
    # completeness signal instead (covers dumps written before the
    # sentinel existed: orbax's atomic commit writes _CHECKPOINT_METADATA
    # only at finalization).
    if os.path.exists(os.path.join(path, "_CHECKPOINT_METADATA")):
        return path
    # Truncated.  Move it aside so the ignore is one-shot (bytes kept for
    # manual forensics) instead of re-warning on every subsequent resume.
    quarantined = path + ".truncated"
    try:
        if os.path.isdir(quarantined):
            import shutil

            shutil.rmtree(quarantined)
        os.rename(path, quarantined)
        moved = f"; moved to {quarantined}"
    except OSError as e:
        moved = f"; could not move aside ({e})"
    print(f"[tpudp] WARNING: ignoring emergency dump {path} — no "
          "completion sentinel or orbax metadata (the dump was "
          f"interrupted mid-write){moved}; falling back to the epoch "
          "checkpoint series")
    return None


_STEP_DIR = re.compile(r"^step_(\d+)$")


def prune_step_dirs(root: str | os.PathLike, keep: int) -> list[str]:
    """Delete all but the newest ``keep`` ``step_N`` checkpoints under
    ``root``; returns the deleted paths.  Only exact ``step_<digits>``
    directories are candidates — orbax tmp dirs and the emergency dump are
    never touched, and the newest VERIFIABLE checkpoint (one carrying an
    integrity manifest or orbax's finalization metadata) is never deleted
    even when it falls outside the keep window: if the newer retained dirs
    are all torn, that dir is the only restorable state left and pruning
    it would make the next resume impossible (docs/RESILIENCE.md).
    A pruned dir's manifest file is deleted with it.  Residual window:
    SILENT rot of a never-yet-restored newest dir keeps its manifest, so
    the protection can still pick it while ``keep=1`` deletes the intact
    older dir — restore-time rejection quarantines corrupt dirs out of
    the series, but only once a restore has actually run; prefer
    ``keep >= 2`` when the storage is suspect.  Multi-host callers
    should invoke this on process 0 only, after the save for the newest
    step has committed (the sync saver and AsyncCheckpointWriter's
    serialized saves both guarantee the PREVIOUS step is durable by then,
    so the retained set is always restorable)."""
    import shutil

    root = os.fspath(root)
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    newest_first = step_dirs_newest_first(root)  # the one scan the
    # restore-fallback walk uses too — prune and restore can't disagree
    # about what the series contains
    protected = next(
        (path for path in newest_first
         if os.path.exists(manifest_path(path))
         or os.path.exists(os.path.join(path, "_CHECKPOINT_METADATA"))),
        None)
    deleted = []
    for path in list(reversed(newest_first))[:-keep]:
        if path == protected:
            continue
        try:
            shutil.rmtree(path)
        except OSError as e:
            print(f"[tpudp] WARNING: could not prune checkpoint {path}: {e}")
            continue
        try:
            os.unlink(manifest_path(path))
        except FileNotFoundError:
            pass
        except OSError as e:  # same tolerance as the rmtree above: a
            # housekeeping failure must never kill (or, under the
            # supervisor, fault-retry) the training run
            print(f"[tpudp] WARNING: could not remove manifest of pruned "
                  f"checkpoint {path}: {e}")
        deleted.append(path)
    return deleted


def ensure_writable(root: str | os.PathLike) -> str:
    """Fail-fast probe for --save-checkpoint flags: verify orbax is
    importable and the destination is creatable/writable BEFORE any
    compute is spent — a save error discovered after a long training run
    loses the run (round-4 review finding)."""
    if not HAVE_ORBAX:
        raise RuntimeError(
            "orbax-checkpoint is not installed; --save-checkpoint cannot "
            "work — aborting before training rather than after")
    root = os.path.abspath(os.fspath(root))
    os.makedirs(root, exist_ok=True)
    probe = os.path.join(root, ".write_probe")
    with open(probe, "w") as f:
        f.write("ok")
    os.unlink(probe)
    return root


def restore_params(path: str | os.PathLike):
    """Restore ONLY the ``params`` subtree of a saved TrainState.

    Decode/eval tools need the weights, not the optimizer state — and a
    full-TrainState ``restore_checkpoint`` target must structurally match
    the optimizer the checkpoint was saved with (clip/skip wrappers add
    state leaves), which a standalone tool cannot know.  Restoring the
    raw tree target-free and slicing ``params`` sidesteps the mismatch.
    """
    if not HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not installed")
    raw = _checkpointer().restore(os.path.abspath(os.fspath(path)))
    try:
        return raw["params"]
    except (KeyError, TypeError, IndexError):
        raise ValueError(
            f"{os.fspath(path)!r} holds no 'params' subtree — not a saved "
            "TrainState?") from None


def latest_step_dir(root: str | os.PathLike) -> str | None:
    """Return the highest-numbered ``step_N`` subdirectory, or None.

    Only exact ``step_<digits>`` names count — orbax leaves
    ``step_N.orbax-checkpoint-tmp-*`` directories behind after an
    interrupted save (and the resilience layer quarantines corrupt dirs
    as ``step_N.corrupt``), and those must never be selected (or
    parsed)."""
    dirs = step_dirs_newest_first(root)
    return dirs[0] if dirs else None

"""Silent-data-corruption defense: replica fingerprints, majority-vote
localization, and deterministic bit-flip injectors.

Every detector the resilience stack owns fires on LOUD faults — NaN
windows, escaped exceptions, hangs, crc-mismatched checkpoints — but a
chip that computes wrong-but-finite numbers sails through all of them,
and the DP all-reduce then SPREADS the corruption to every replica
before the next checkpoint seals it in.  At pod scale this is the
dominant unhandled fault class (arXiv:2204.06514's TPUv4 regime).  The
repo is unusually well-armed against it:

  * Data-parallel replication makes post-update parameters a free
    dual-modular-redundancy check — healthy replicas hold bit-identical
    bytes, so any per-replica checksum disagreement IS corruption, and
    with three or more replicas a majority vote NAMES the bad one.
  * The bit-exact trajectory discipline (arXiv:2509.07003) that already
    referees every recovery path is exactly the oracle an SDC responder
    needs: restore the newest verified checkpoint and deterministically
    replay, and the repaired run is bit-identical to one that never saw
    the flip.

Three pieces live here; the policy/vote glue lives in
``tpudp/resilience.py`` (``ResiliencePolicy(sdc_check_every=N)``) and
the serving canary in ``tpudp/serve/engine.py`` (``Engine(
canary_every_s=...)``):

  * :func:`traced_fingerprint` — the IN-STEP fingerprint: an exact
    wraparound-u32 checksum over the raw bits of every leaf, computed
    inside the jitted train step and carried as the optional
    ``TrainState.sdc_fp`` leaf (the ``obs_norms`` zero-sync piggyback
    pattern).  The host fetches it at the window-edge seam where it
    already synchronizes for ``loss_sum``, so designated hot paths gain
    ZERO new host syncs.  Bit-exact by construction: float sums would
    round a low-mantissa flip away in a large model; an integer
    checksum of the bit pattern cannot.
  * :func:`vote_fp_shards` — the CHEAP detection half: each device's
    shard of the logically-replicated ``sdc_fp`` leaf is the checksum
    THAT device computed over its own bytes, so majority-voting the
    (2,)-u32 shards names a divergent replica while moving ~8 bytes
    per device — never the model.  :func:`replica_fingerprints` /
    :func:`vote_shard_groups` / :func:`localize_minority` are the
    raw-BYTE localization half, run only AFTER a checksum mismatch:
    per-replica checksums from the actual addressable shard bytes (the
    same shard-level view ``tpudp/utils/consistency.py`` compares),
    majority-voted per replication group to name the corrupt device.
    Works under plain DP (params replicated per device) and the PP
    schedule's ZeRO-1 layout (params all-gathered each step; the
    1/DP-sharded optimizer state is excluded exactly like
    ``fingerprint()`` excludes it, with checkpoint shard manifests
    covering those bytes instead).
  * :class:`BitFlipParams` / :class:`BitFlipGrads` — deterministic
    injectors with a ``(step, replica, bit)`` schedule, driving the
    unit matrix (``tests/test_sdc.py``) and the ``sdc_soak`` bench
    stage (``benchmarks/resilience_bench.py --sdc``).  The serving
    analogue (``BitFlipLogits``) lives in ``tpudp/serve/faults.py``.

Response grading (implemented by the Supervisor): a first detection
rolls back to the newest verified checkpoint and replays the window —
the existing bit-exact path.  A clean re-check classifies the flip
TRANSIENT (a cosmic-ray event: continue, params repaired
bit-identically); the SAME replica diverging again after a bit-exact
replay classifies the chip PERSISTENT — the host is quarantined
(:data:`SDC_QUARANTINE_EXIT`, plus an on-disk marker naming it) and the
relaunch harness resumes at reduced geometry through the elastic
verified restore + ``ShardedSampler(batch_contiguous=)``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

#: Exit code when a PERSISTENT SDC verdict quarantines this host: the
#: process exits for the scheduler/relauncher, which excludes the host
#: named in the ``sdc_quarantine.json`` marker and relaunches the pod
#: at reduced geometry into the elastic verified restore.  Distinct
#: from the watchdog's 42 and the vote layer's 43 so the soak can
#: attribute the exit to the SDC path.
SDC_QUARANTINE_EXIT = 44

#: Marker file (under ``ResiliencePolicy.checkpoint_dir``) written
#: before a quarantine exit; the relaunch harness reads it to shrink
#: the geometry around the named host.
QUARANTINE_MARKER = "sdc_quarantine.json"


class SdcDetected(RuntimeError):
    """Replica fingerprints disagree: some chip computed wrong-but-
    finite numbers.  Raised at the window-edge check; the supervisor
    routes it through the divergence-class recovery (restore newest
    verified checkpoint + bit-exact replay), whose re-check grades the
    fault transient or persistent.  ``replica`` names the minority
    replica when the vote could localize one (None on a 2-replica tie
    — corruption proven, culprit unknown)."""

    def __init__(self, message: str, *, step: int | None = None,
                 replica=None, fingerprints=None):
        super().__init__(message)
        self.step = step
        self.replica = replica
        self.fingerprints = dict(fingerprints or {})


class SdcPersistentError(RuntimeError):
    """The SAME replica diverged again after a bit-exact replay — a
    persistently bad chip, not a transient flip.  Escalates out of the
    supervisor (single-host) or hard-exits with
    :data:`SDC_QUARANTINE_EXIT` (multi-host) after the quarantine
    marker is written."""

    def __init__(self, message: str, *, replica=None):
        super().__init__(message)
        self.replica = replica


def _np_bits_u32(a: np.ndarray) -> np.ndarray:
    """The raw bits of ``a`` widened to uint32 (uint64 splits into two
    u32 halves so no bit goes unchecked)."""
    a = np.ascontiguousarray(a)
    if a.dtype == np.bool_:
        a = a.astype(np.uint8)
    nbytes = a.dtype.itemsize
    if nbytes >= 8:
        v = a.view(np.uint64).ravel()
        return ((v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
                .astype(np.uint64)
                + (v >> np.uint64(32)).astype(np.uint32).astype(np.uint64)
                ).astype(np.uint32)
    view = {1: np.uint8, 2: np.uint16, 4: np.uint32}[nbytes]
    return a.view(view).ravel().astype(np.uint32)


def np_fingerprint(arrays) -> np.ndarray:
    """Host-side twin of :func:`traced_fingerprint`: exact wraparound-
    u32 checksum + element count over numpy arrays.  Shared by the
    per-replica shard walk and the tests' oracles (the two must agree
    bit-for-bit on identical bytes)."""
    total = np.uint64(0)
    count = np.uint64(0)
    for a in arrays:
        bits = _np_bits_u32(np.asarray(a))
        total = (total + np.uint64(bits.sum(dtype=np.uint64))) \
            & np.uint64(0xFFFFFFFF)
        count = (count + np.uint64(bits.size)) & np.uint64(0xFFFFFFFF)
    return np.array([total, count], dtype=np.uint64)


def traced_fingerprint(tree):
    """The in-step fingerprint: ``[checksum, count]`` (u32, stacked) of
    every leaf's raw bits, safe to call INSIDE a jitted step.  Integer
    wraparound sums are exact and order-independent, so a single
    flipped bit anywhere in ``tree`` changes the checksum with
    certainty (a float accumulator would round a low-mantissa flip away
    at scale), and healthy replicas — which hold bit-identical bytes —
    produce bit-identical fingerprints."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    total = jnp.zeros((), jnp.uint32)
    count = jnp.zeros((), jnp.uint32)
    for leaf in jax.tree.leaves(tree):
        a = jnp.asarray(leaf)
        if a.dtype == jnp.bool_:
            a = a.astype(jnp.uint8)
        nbytes = a.dtype.itemsize
        if nbytes >= 8:
            v = lax.bitcast_convert_type(a, jnp.uint64)
            bits = ((v & jnp.uint64(0xFFFFFFFF))
                    + (v >> jnp.uint64(32))).astype(jnp.uint32)
        else:
            view = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}[nbytes]
            bits = lax.bitcast_convert_type(a, view).astype(jnp.uint32)
        total = total + jnp.sum(bits, dtype=jnp.uint32)
        count = count + jnp.uint32(a.size & 0xFFFFFFFF)
    return jnp.stack([total, count])


def _leaf_paths(tree):
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        yield jax.tree_util.keystr(path), leaf


def replica_fingerprints(tree) -> dict:
    """Per-replica checksums from the actual shard bytes on this host:
    ``{replica_key: np.array([checksum, count])}`` where a replica key
    is ``"p<process>/d<device>"``.  For every leaf that is REPLICATED
    across local devices (each device holds the same logical slice),
    each device's copy is checksummed into ITS replica's fingerprint —
    healthy replicas therefore agree bit-for-bit and a corrupted
    device's fingerprint stands out.  Genuinely sharded leaves (ZeRO-1
    optimizer state: a different slice per device) are excluded, the
    same rule as ``tpudp.utils.consistency.fingerprint`` — their bytes
    are covered by the per-host checkpoint shard manifests.  A leaf
    sharded over SOME devices but replicated within groups contributes
    each group's bytes to its members, so partial replication still
    gets DMR cover."""
    import jax

    proc = jax.process_index()
    sums: dict = {}
    counts: dict = {}

    def _add(dev, bits_sum: int, n: int) -> None:
        key = f"p{proc}/d{getattr(dev, 'id', dev)}"
        sums[key] = (sums.get(key, 0) + bits_sum) & 0xFFFFFFFF
        counts[key] = (counts.get(key, 0) + n) & 0xFFFFFFFF

    for _name, leaf in _leaf_paths(tree):
        if not isinstance(leaf, jax.Array):
            continue
        shards = getattr(leaf, "addressable_shards", None)
        if not shards:
            continue
        by_index: dict = {}
        for s in shards:
            by_index.setdefault(str(s.index), []).append(s)
        # replicated = some index group holds >1 device, or the leaf is
        # fully replicated with a single local device (still this
        # replica's copy — it participates in the cross-host vote)
        for group in by_index.values():
            if len(group) < 2 and len(by_index) > 1:
                # a uniquely-held slice of a sharded leaf: excluded
                continue
            for s in group:
                bits = _np_bits_u32(np.asarray(s.data))
                _add(s.device, int(bits.sum(dtype=np.uint64)), bits.size)
    return {k: np.array([sums[k], counts[k]], dtype=np.uint64)
            for k in sorted(sums)}


def vote_shard_groups(tree) -> tuple[list, list]:
    """Majority-vote the raw shard bytes per REPLICATION GROUP and name
    corrupt devices: for every leaf, devices holding the same logical
    slice (same shard index) form one group, each member's bytes are
    checksummed, and the group's minority members are suspects.  Voting
    within groups — not across all devices flat — is what makes this
    correct under PP x DP layouts, where stage-0 and stage-1 devices
    legitimately hold DIFFERENT bytes but each stage's DP copies must
    match.  Returns ``(minority_keys, majority_keys)`` over
    ``"p<process>/d<device>"`` keys; a device minority in ANY group is
    a suspect.  Single-member groups (genuinely sharded slices, or a
    single local device) have no redundancy and are skipped — the
    checkpoint manifests and the cross-host in-step fingerprint cover
    those."""
    import jax

    proc = jax.process_index()
    minority: set = set()
    majority: set = set()
    for _name, leaf in _leaf_paths(tree):
        if not isinstance(leaf, jax.Array):
            continue
        shards = getattr(leaf, "addressable_shards", None)
        if not shards:
            continue
        by_index: dict = {}
        for s in shards:
            by_index.setdefault(str(s.index), []).append(s)
        for group in by_index.values():
            if len(group) < 2:
                continue
            fps = {f"p{proc}/d{getattr(s.device, 'id', s.device)}":
                   np_fingerprint([np.asarray(s.data)]) for s in group}
            g_min, g_maj = localize_minority(fps)
            minority.update(g_min)
            majority.update(g_maj)
    majority -= minority  # corrupt in ANY group outranks clean elsewhere
    return sorted(minority), sorted(majority)


def vote_fp_shards(fp_leaf) -> tuple[list, list]:
    """Majority-vote the per-device shards of the in-step ``sdc_fp``
    leaf — the cheap DETECTION path.  Each device's shard of the
    logically-replicated fingerprint is the checksum THAT device
    computed over its own params/optimizer bytes inside the step
    (under the PP schedule the pipe-axis psum makes it the pipeline
    total, still replicated across healthy DP columns), so healthy
    replicas hold bit-identical shards and a corrupt replica's shard
    stands out.  Voting these fetches ~8 bytes per device instead of
    the model: the raw-byte walk (:func:`vote_shard_groups`) is
    reserved for localizing AFTER a mismatch.  Returns
    ``(minority_keys, majority_keys)`` over ``"p<process>/d<device>"``
    keys; fewer than two local shards yields no vote (the cross-host
    fingerprint exchange covers single-device hosts)."""
    import jax

    proc = jax.process_index()
    shards = getattr(fp_leaf, "addressable_shards", None)
    if not shards or len(shards) < 2:
        return [], []
    fps = {f"p{proc}/d{getattr(s.device, 'id', s.device)}":
           np.asarray(s.data) for s in shards}
    return localize_minority(fps)


def localize_minority(fps: dict) -> tuple[list, list]:
    """Majority vote over replica fingerprints: returns
    ``(minority_keys, majority_keys)``.  Empty minority = all replicas
    agree.  A strict majority (> half) is required to NAME the bad
    replica; without one (the 2-replica disagreement, or a 2-2 split)
    corruption is still proven but unlocalizable — every key lands in
    ``minority_keys`` and ``majority_keys`` is empty, which callers
    treat as "roll back, cannot quarantine"."""
    if not fps:
        return [], []
    groups: dict = {}
    for k, v in fps.items():
        groups.setdefault(np.asarray(v).tobytes(), []).append(k)
    if len(groups) == 1:
        return [], sorted(fps)
    best = max(groups.values(), key=len)
    if len(best) * 2 <= len(fps):
        return sorted(fps), []  # no strict majority: unlocalizable
    minority = sorted(k for k in fps if k not in best)
    return minority, sorted(best)


# -- deterministic injectors -------------------------------------------


@dataclass(frozen=True)
class BitFlip:
    """One scheduled flip: at trainer step ``step`` (the injector's own
    monotonic step counter — deterministic, no device fetch), flip bit
    ``bit`` of the target leaf's first element on replica ``replica``
    (an index into this host's addressable replica devices)."""

    step: int
    replica: int = 0
    bit: int = 0


def _first_float_leaf(tree):
    """Deterministic target choice: the first floating leaf in path
    order — the same leaf every run, so a soak seed replays exactly."""
    import jax
    import jax.numpy as jnp

    best = None
    for name, leaf in _leaf_paths(tree):
        if isinstance(leaf, jax.Array) and jnp.issubdtype(
                leaf.dtype, jnp.floating) and leaf.size > 0:
            if best is None or name < best[0]:
                best = (name, leaf)
    if best is None:
        raise ValueError("no floating-point leaf to corrupt")
    return best


def flip_bit_on_replica(leaf, replica: int, bit: int):
    """Flip ``bit`` of element 0 of ``leaf`` on ONE replica's buffer,
    leaving every other replica's bytes untouched — the
    replicated-by-assumption, divergent-in-fact state a real SDC event
    produces.  Reassembles the array from per-device buffers under the
    ORIGINAL sharding (``jax.make_array_from_single_device_arrays``),
    so the step programs keep running; only the bytes lie."""
    import jax

    shards = list(leaf.addressable_shards)
    if not shards:
        raise ValueError("leaf has no addressable shards to corrupt")
    replica = replica % len(shards)
    bufs = []
    for i, s in enumerate(shards):
        a = np.array(s.data)  # owning copy
        if i == replica:
            flat = a.reshape(-1)
            nbytes = a.dtype.itemsize
            if nbytes >= 8:
                v = flat[:1].copy().view(np.uint64)
                flat[0:1] = (v ^ np.uint64(1 << (bit % 64))).view(a.dtype)
            else:
                # Reduce the bit index to the dtype's OWN width: an
                # out-of-range index must wrap to a real bit, never
                # silently no-op above the word while the injector
                # records the flip as fired.
                view = _np_bits_u32(flat[:1].copy())
                word = int(view[0]) ^ (1 << (bit % (8 * nbytes)))
                store = {1: np.uint8, 2: np.uint16, 4: np.uint32}[nbytes]
                flat[0:1] = np.array([word], store).view(a.dtype)
            a = flat.reshape(a.shape)
        bufs.append(jax.device_put(a, s.device))
    if len(shards) == 1:
        return bufs[0]
    return jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding, bufs)


class _BitFlipInjector:
    """Shared mechanics of the trainer-side injectors: a deterministic
    ``(step, replica, bit)`` schedule applied through the
    ``Trainer(sdc_fault_hook=...)`` seam (called after each train step
    as ``state = hook(state)``).  Steps are counted by the injector
    itself — monotonic across rollback replays, so a one-shot schedule
    entry fires ONCE ever (the replay is clean → transient verdict)
    while ``persist_from=K`` re-corrupts every step from its K-th call
    onward (the replay re-diverges → persistent verdict).  ``fired``
    records ``(step, replica, bit)`` for soak accounting."""

    def __init__(self, schedule=(), *, persist_from: int | None = None,
                 replica: int = 0, bit: int = 0):
        self.schedule = tuple(
            e if isinstance(e, BitFlip) else BitFlip(*e) for e in schedule)
        if persist_from is not None and persist_from < 0:
            raise ValueError(f"persist_from must be >= 0, got {persist_from}")
        self.persist_from = persist_from
        self.replica = replica
        self.bit = bit
        self.fired: list[tuple[int, int, int]] = []
        self._calls = 0

    def _target(self, state):
        raise NotImplementedError

    def _rebuild(self, state, leaf_name, new_leaf):
        raise NotImplementedError

    def __call__(self, state):
        self._calls += 1
        step = self._calls
        flips = [f for f in self.schedule
                 if f.step == step and (f.step, f.replica, f.bit)
                 not in self.fired]
        if self.persist_from is not None and step >= self.persist_from:
            flips.append(BitFlip(step, self.replica, self.bit))
        for f in flips:
            name, leaf = self._target(state)
            state = self._rebuild(
                state, name, flip_bit_on_replica(leaf, f.replica, f.bit))
            self.fired.append((f.step, f.replica, f.bit))
        return state


def _replace_leaf(tree, name: str, new_leaf):
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    leaves = [new_leaf if jax.tree_util.keystr(p) == name else x
              for p, x in flat]
    return jax.tree_util.tree_unflatten(treedef, leaves)


class BitFlipParams(_BitFlipInjector):
    """Flip a bit in one replica's POST-UPDATE parameter bytes — the
    corrupted-weight case.  Detected by the very next fingerprint check
    (params are fingerprinted directly)."""

    def _target(self, state):
        return _first_float_leaf(state.params)

    def _rebuild(self, state, name, new_leaf):
        return state.replace(
            params=_replace_leaf(state.params, name, new_leaf))


class BitFlipGrads(_BitFlipInjector):
    """Flip a bit in one replica's OPTIMIZER-STATE bytes (the momentum
    trace — where a corrupted gradient lands and keeps poisoning every
    later update).  Detected through the optimizer-state half of the
    fingerprint; distinct from :class:`BitFlipParams` because the
    params stay healthy until the next update applies the poisoned
    trace."""

    def _target(self, state):
        return _first_float_leaf(state.opt_state)

    def _rebuild(self, state, name, new_leaf):
        return state.replace(
            opt_state=_replace_leaf(state.opt_state, name, new_leaf))

"""tpudp — TPU-native distributed data-parallel training framework.

A from-scratch JAX/XLA re-design of the capability surface of the CS744
distributed-data-parallel reference (rawahars/CS744-Distributed-Data-Parallel):
the four-part ladder of gradient-synchronization strategies

  * ``none``        — single-device baseline           (reference ``src/Part 1``)
  * ``coordinator`` — gather → mean → broadcast        (reference ``src/Part 2a/main.py:117-127``)
  * ``allreduce``   — collective all-reduce, mean      (reference ``src/Part 2b/main.py:116-119``)
  * ``ring``        — hand-rolled ring all-reduce      (north-star extra; built from lax.ppermute)
  * ``auto``        — compiler-scheduled sync in jit   (reference ``src/Part 3/main.py:61`` / DDP)
  * ``allreduce_bf16`` — bfloat16-compressed collective (beyond-reference; half the wire bytes)

running SPMD over a ``jax.sharding.Mesh`` with XLA collectives on ICI/DCN —
no process groups, no Gloo, no torch.distributed.
"""

__version__ = "0.1.0"

from tpudp.mesh import make_mesh, make_mesh_nd, initialize_distributed  # noqa: F401
from tpudp.train import Trainer, TrainState, make_train_step, make_eval_step  # noqa: F401
from tpudp.parallel.sync import SYNC_STRATEGIES  # noqa: F401
from tpudp.strategy import STRATEGIES, build_strategy  # noqa: F401

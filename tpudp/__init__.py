"""tpudp — TPU-native distributed data-parallel training framework.

A from-scratch JAX/XLA re-design of the capability surface of the CS744
distributed-data-parallel reference (rawahars/CS744-Distributed-Data-Parallel):
the four-part ladder of gradient-synchronization strategies

  * ``none``        — single-device baseline           (reference ``src/Part 1``)
  * ``coordinator`` — gather → mean → broadcast        (reference ``src/Part 2a/main.py:117-127``)
  * ``allreduce``   — collective all-reduce, mean      (reference ``src/Part 2b/main.py:116-119``)
  * ``ring``        — hand-rolled ring all-reduce      (north-star extra; built from lax.ppermute)
  * ``auto``        — compiler-scheduled sync in jit   (reference ``src/Part 3/main.py:61`` / DDP)
  * ``allreduce_bf16`` — bfloat16-compressed collective (beyond-reference; half the wire bytes)

running SPMD over a ``jax.sharding.Mesh`` with XLA collectives on ICI/DCN —
no process groups, no Gloo, no torch.distributed.
"""

__version__ = "0.1.0"

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # Compat shim: jax.shard_map graduated from jax.experimental in newer
    # releases; on older jax the experimental entry point is the same
    # transform with `check_rep` where the graduated API says `check_vma`.
    # Installed once at package import so every tpudp module (and the
    # benches) can use the modern spelling unconditionally.
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def _compat_shard_map(f, *, mesh, in_specs, out_specs,
                          check_vma: bool = True, **kwargs):
        kwargs.setdefault("check_rep", check_vma)
        return _experimental_shard_map(f, mesh=mesh, in_specs=in_specs,
                                       out_specs=out_specs, **kwargs)

    _jax.shard_map = _compat_shard_map

if not hasattr(_jax.lax, "axis_size"):
    # Same vintage gap: lax.axis_size (static size of a named mesh axis)
    # graduated later; on this jax the equivalent is core.axis_frame,
    # which returns the bound axis size directly.  The ring/pipeline/
    # compress rungs use the result in static shape math (`range(n)`,
    # padding arithmetic), so the shim must return a Python int — and it
    # does (verified under shard_map).
    _jax.lax.axis_size = _jax.core.axis_frame

from tpudp.mesh import make_mesh, make_mesh_nd, initialize_distributed  # noqa: F401
from tpudp.train import Trainer, TrainState, make_train_step, make_eval_step  # noqa: F401
from tpudp.parallel.sync import SYNC_STRATEGIES  # noqa: F401
from tpudp.strategy import STRATEGIES, build_strategy  # noqa: F401

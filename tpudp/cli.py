"""CLI shared by the Part entrypoints.

Maps the reference's flags (``src/Part 2a/main.py:156-175``: ``--master``
required IP, ``--num-nodes``, ``--rank``, ``--epochs``; hardcoded port 6585
and global batch 256 at ``:172-173``) onto the SPMD world:

  * ``--master``/``--rank``/``--num-nodes`` become the
    ``jax.distributed.initialize`` coordinator/process_id/num_processes —
    OPTIONAL on a single host, where one process already owns all devices
    (the reference requires one manually-launched process per node).
  * world size for gradient math is the device-mesh size, not a process
    count; ``--num-devices`` restricts the mesh for ladder comparisons.
"""

from __future__ import annotations

import argparse

import jax

from tpudp.data import DataLoader, ShardedSampler, load_cifar10
from tpudp.mesh import DATA_AXIS, initialize_distributed, make_mesh
from tpudp.train import Trainer

GLOBAL_BATCH_SIZE = 256  # reference constant, src/Part 2a/main.py:173
PORT = 6585  # reference constant, src/Part 2a/main.py:172


def build_parser(description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--master", type=str, default=None,
                   help="coordinator IP for multi-host (reference --master)")
    p.add_argument("--num-nodes", type=int, default=None,
                   help="number of host processes (reference --num-nodes)")
    p.add_argument("--rank", type=int, default=None,
                   help="this host's process id (reference --rank)")
    p.add_argument("--epochs", type=int, default=1,
                   help="epochs to train (reference default 1)")
    p.add_argument("--num-devices", type=int, default=None,
                   help="restrict the mesh to N devices (default: all)")
    p.add_argument("--batch-size", type=int, default=GLOBAL_BATCH_SIZE,
                   help="GLOBAL batch size (split across devices)")
    p.add_argument("--data-root", type=str, default="./data")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timing-mode", choices=["fused", "split"], default="fused")
    p.add_argument("--dtype", choices=["float32", "bfloat16"], default="float32")
    p.add_argument("--model", choices=["vgg11", "vgg13", "vgg16", "vgg19"],
                   default="vgg11",
                   help="VGG variant (reference default VGG-11; the "
                        "reference's config table defines 13/16/19 but "
                        "never exports them — src/Part 1/model.py:3-8,49-50 "
                        "— tpudp makes the whole table launchable)")
    p.add_argument("--checkpoint-dir", type=str, default=None,
                   help="save TrainState each epoch and auto-resume from the "
                        "latest checkpoint (beyond-reference capability)")
    p.add_argument("--checkpoint-async", action="store_true",
                   help="overlap checkpoint writes with the next epoch's "
                        "training (orbax async; the epoch barrier no longer "
                        "waits for filesystem IO)")
    p.add_argument("--keep-checkpoints", type=int, default=None, metavar="N",
                   help="retain only the newest N epoch checkpoints, "
                        "deleting older step_* dirs after each save")
    p.add_argument("--platform", type=str, default=None,
                   help="force a JAX platform (e.g. 'cpu' with "
                        "XLA_FLAGS=--xla_force_host_platform_device_count=N "
                        "to simulate an N-chip mesh on one host)")
    p.add_argument("--synthetic-train-size", type=int, default=50_000,
                   help="synthetic-fallback train set size (smoke runs)")
    p.add_argument("--synthetic-test-size", type=int, default=10_000)
    p.add_argument("--data-backend", choices=["auto", "native", "numpy"],
                   default="auto",
                   help="host augmentation backend: fused C++/OpenMP kernel "
                        "(tpudp/native) or bit-identical numpy")
    p.add_argument("--eval-only", action="store_true",
                   help="restore the latest checkpoint from "
                        "--checkpoint-dir, run the test-set evaluation "
                        "(reference eval loop, src/Part 2a/main.py:130-145) "
                        "and exit without training")
    p.add_argument("--sync-bn", action="store_true",
                   help="cross-replica BatchNorm (torch SyncBatchNorm "
                        "analogue): psum batch statistics over the data "
                        "axis so N devices at batch B/N normalize exactly "
                        "like one device at batch B. Default keeps the "
                        "reference's local-stats semantics (src/Part "
                        "2a/main.py:59-68). shard_map rungs only")
    p.add_argument("--spmd-mode", choices=["shard_map", "gspmd"],
                   default=None,
                   help="Part 3 (auto rung) only: how the compiler-"
                        "scheduled sync is obtained. 'shard_map' (default) "
                        "runs per-device with an explicit psum XLA overlaps "
                        "— BatchNorm keeps the reference's LOCAL per-rank "
                        "batch statistics (DDP syncs gradients only, src/"
                        "Part 3/main.py:61). 'gspmd' lets XLA's partitioner "
                        "insert the collectives from sharding annotations; "
                        "note BatchNorm then normalizes over the GLOBAL "
                        "batch (SyncBN-like semantics — pinned by tests/"
                        "test_train.py::test_gspmd_bn_is_syncbn_semantics)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize activations during backward "
                        "(jax.checkpoint): identical gradients, lower peak "
                        "HBM, one extra forward's FLOPs")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="split each device batch into N sequential "
                        "microbatches, accumulating gradients before the "
                        "sync+update (trade steps for activation memory; "
                        "beyond-reference capability)")
    p.add_argument("--prefetch", type=int, default=2,
                   help="batches prepared ahead on a background thread "
                        "(reference DataLoader num_workers=2 analogue); "
                        "0 disables")
    p.add_argument("--verify-replicas", action="store_true",
                   help="after each epoch, assert every replicated "
                        "param/BN-stat shard is bit-identical across "
                        "devices (torch DDP's parameter-verification "
                        "analogue; catches silent DP desync — "
                        "tpudp/utils/consistency.py)")
    p.add_argument("--metrics-jsonl", type=str, default=None, metavar="PATH",
                   help="append machine-readable metrics (one JSON line per "
                        "train window / eval / epoch) to PATH, alongside the "
                        "reference-format prints; process 0 only")
    p.add_argument("--profile-dir", type=str, default=None,
                   help="capture an XLA/TPU profiler trace of the training "
                        "run into this directory (TensorBoard trace-viewer "
                        "format; beyond-reference capability)")
    p.add_argument("--step-timeout", type=float, default=None,
                   help="failure detection: exit if training makes no "
                        "iteration progress for this many seconds (wedged "
                        "collective, dead peer host) so the scheduler can "
                        "restart + --checkpoint-dir resume. Must exceed one "
                        "full log window (log-every steps) plus first-step "
                        "compile time. The reference hangs forever in this "
                        "case (SURVEY.md §5); default: disabled. With "
                        "--resilience the hang recovers IN-PROCESS instead "
                        "of exiting")
    p.add_argument("--resilience", action="store_true",
                   help="run training under the in-process fault supervisor "
                        "(tpudp/resilience.py, docs/RESILIENCE.md): NaN/"
                        "spike windows roll back to the last verified "
                        "checkpoint and replay deterministically, step "
                        "faults and hangs retry in-process after an "
                        "emergency dump, loader failures restart the "
                        "pipeline at the exact batch offset. Requires "
                        "--checkpoint-dir; the trajectory stays "
                        "bit-identical to an uninterrupted run")
    p.add_argument("--max-rollbacks", type=int, default=None, metavar="N",
                   help="divergence-rollback budget before the original "
                        "error escalates (--resilience only; default 3)")
    p.add_argument("--spike-factor", type=float, default=None, metavar="X",
                   help="roll back when a window loss exceeds X times the "
                        "trailing-median window loss (--resilience only; "
                        "default: spike detection off, NaN windows still "
                        "roll back)")
    p.add_argument("--flight-dir", type=str, default=None, metavar="DIR",
                   help="observability (tpudp.obs): dump the flight "
                        "recorder — the last N train/eval spans and "
                        "recovery events — into per-host "
                        "flightrec-*.json under DIR on watchdog "
                        "timeouts, rollbacks, and vote timeouts "
                        "(default: the TPUDP_FLIGHT_DIR env var; unset "
                        "= dumps disabled)")
    p.add_argument("--metrics-port", type=int, default=None, metavar="N",
                   help="observability (tpudp.obs): serve a Prometheus-"
                        "style text endpoint with the live Trainer."
                        "metrics() snapshot on localhost:N/metrics "
                        "(process 0 only; 0 picks a free port)")
    return p


def run_part(sync: str, description: str, *, spmd_mode: str = "shard_map",
             single_device: bool = False, argv=None) -> Trainer:
    """Shared Part-N driver: parse flags, build mesh/data/model, fit."""
    import jax.numpy as jnp

    from tpudp.models import VGG11, VGG13, VGG16, VGG19

    args = build_parser(description).parse_args(argv)
    if args.spmd_mode is not None:
        if sync != "auto":
            raise SystemExit(
                "error: --spmd-mode applies only to the Part 3 'auto' rung "
                "(the other Parts' sync strategies are explicit shard_map "
                "collectives by definition)")
        spmd_mode = args.spmd_mode
    if args.checkpoint_async and not args.checkpoint_dir:
        raise SystemExit(
            "error: --checkpoint-async requires --checkpoint-dir (nothing "
            "would be checkpointed otherwise)")
    if args.keep_checkpoints is not None and args.keep_checkpoints < 1:
        raise SystemExit(
            f"error: --keep-checkpoints must be >= 1 "
            f"(got {args.keep_checkpoints})")
    if args.keep_checkpoints and not args.checkpoint_dir:
        raise SystemExit(
            "error: --keep-checkpoints requires --checkpoint-dir")
    if args.sync_bn and (single_device or spmd_mode != "shard_map"):
        # Decidable from flags alone — fail before distributed init /
        # dataset load, next to the other pure-argument checks.
        raise SystemExit(
            "error: --sync-bn needs a shard_map rung (Parts 2a/2b) — the "
            "mesh axis is not bound in single-device or gspmd modes")
    if args.eval_only and not args.checkpoint_dir:
        raise SystemExit(
            "error: --eval-only requires --checkpoint-dir (there is no "
            "model to evaluate otherwise)")
    if args.resilience and not args.checkpoint_dir:
        raise SystemExit(
            "error: --resilience requires --checkpoint-dir (rollback and "
            "step recovery restore from the step_N series under it)")
    if (args.max_rollbacks is not None or args.spike_factor is not None) \
            and not args.resilience:
        raise SystemExit(
            "error: --max-rollbacks/--spike-factor configure the "
            "--resilience supervisor; pass --resilience too")
    if args.max_rollbacks is not None and args.max_rollbacks < 0:
        raise SystemExit(
            f"error: --max-rollbacks must be >= 0 (got {args.max_rollbacks})")
    if args.spike_factor is not None and args.spike_factor <= 1.0:
        raise SystemExit(
            f"error: --spike-factor must be > 1.0 (got {args.spike_factor}) "
            "— a window loss always 'exceeds' a sub-unit multiple of the "
            "median and every window would roll back")
    if args.platform:  # must precede the first device query
        jax.config.update("jax_platforms", args.platform)
    initialize_distributed(args.master, args.num_nodes, args.rank, PORT)
    # Persistent executable cache (see tpudp/utils/compile_cache.py): a
    # trainer relaunched on the relay-gated TPU skips the train-step
    # compile RPC after the first successful run.  No-ops on the CPU
    # backend (--platform cpu smoke runs).  AFTER distributed init — the
    # helper resolves the backend, and jax.distributed.initialize must
    # precede the first backend touch on multi-host.
    from tpudp.utils.compile_cache import enable_persistent_cache
    from tpudp.utils.device_lock import acquire_for_process

    # Fail fast if another live client (e.g. the watcher) is on the relay
    # — two concurrent clients wedge it (device_lock.py).  The helper
    # self-skips when jax_platforms is cpu-pinned (--platform cpu smoke
    # runs, the test suite's conftest); any accelerator pin still locks.
    acquire_for_process()
    enable_persistent_cache()

    mesh = None if single_device else make_mesh(args.num_devices)
    world = 1 if mesh is None else mesh.size
    num_hosts = jax.process_count()
    host_id = jax.process_index()
    # --resilience runs multi-host too: the supervisor's recovery
    # decisions are COORDINATED (allgathered outcome votes, worst
    # severity wins; the verified-restore walk votes per step dir), so
    # every host resumes the same state — docs/RESILIENCE.md
    # "Multi-host recovery".

    if args.batch_size % world or args.batch_size % num_hosts:
        raise SystemExit(
            f"error: --batch-size {args.batch_size} must be divisible by the "
            f"device count ({world}) and host count ({num_hosts}) — "
            f"per-device batches need equal static shapes"
        )

    train_set, test_set, synthetic = load_cifar10(
        args.data_root,
        synthetic_train_size=args.synthetic_train_size,
        synthetic_test_size=args.synthetic_test_size,
    )
    if synthetic:
        print("[tpudp] CIFAR-10 not found on disk; using synthetic stand-in data")

    # Per-host batch: the reference computes per-rank batch = global/world
    # (src/Part 2a/main.py:22); here host-level sharding divides by process
    # count and the mesh sharding divides across local devices.
    host_batch = args.batch_size // num_hosts
    train_loader = DataLoader(
        train_set, host_batch,
        sampler=ShardedSampler(len(train_set.images), num_hosts, host_id,
                               shuffle=True, seed=args.seed),
        train=True, seed=args.seed, backend=args.data_backend,
    )
    test_loader = DataLoader(
        test_set, host_batch,
        sampler=ShardedSampler(len(test_set.images), num_hosts, host_id,
                               shuffle=False),
        train=False, backend=args.data_backend,
    )
    data_backend = train_loader.backend  # before any wrapper hides it
    if args.prefetch > 0:
        from tpudp.data.prefetch import Prefetcher

        train_loader = Prefetcher(train_loader, depth=args.prefetch)
        test_loader = Prefetcher(test_loader, depth=args.prefetch)

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    factory = {"vgg11": VGG11, "vgg13": VGG13, "vgg16": VGG16,
               "vgg19": VGG19}[args.model]
    model = factory(dtype=dtype,
                    bn_axis=DATA_AXIS if args.sync_bn else None)
    watchdog = None
    if args.step_timeout:
        from tpudp.utils.watchdog import Watchdog

        # Under --resilience the watchdog must NOT kill: the hang surfaces
        # as StepHangError at the next beat and the supervisor recovers
        # in-process (dump, restore, re-arm) instead of a full relaunch.
        outcome = ("recovering in-process" if args.resilience
                   else "exiting for scheduler restart")
        watchdog = Watchdog(
            timeout_s=args.step_timeout,
            kill=not args.resilience,
            on_hang=[lambda: print(
                f"[tpudp] FAILURE DETECTED: step exceeded "
                f"{args.step_timeout}s (wedged collective or dead peer); "
                f"{outcome}", flush=True)],
        ).start()
    trainer = Trainer(model, mesh, sync, seed=args.seed,
                      spmd_mode=spmd_mode, timing_mode=args.timing_mode,
                      watchdog=watchdog, grad_accum=args.grad_accum,
                      remat=args.remat, metrics_jsonl=args.metrics_jsonl,
                      verify_replicas=args.verify_replicas,
                      flight_dir=args.flight_dir)
    metrics_server = None
    if args.metrics_port is not None and jax.process_index() == 0:
        from tpudp.obs import MetricsServer

        metrics_server = MetricsServer(args.metrics_port, trainer.metrics)
        print(f"[tpudp] metrics endpoint: "
              f"http://127.0.0.1:{metrics_server.port}/metrics")
    print(f"[tpudp] model={args.model} sync={sync} devices={world} "
          f"hosts={num_hosts} "
          f"global_batch={args.batch_size} dtype={args.dtype} "
          f"data={data_backend}+prefetch{args.prefetch}")
    print(f"[tpudp] train samples={len(train_set.images)} "
          f"test samples={len(test_set.images)}")

    start_epoch = 0
    skip_first = 0  # mid-epoch fast-forward (emergency-dump resume)
    restored = False
    epoch_end_fn = None
    async_writer = None
    if args.checkpoint_dir:
        import os

        from tpudp.utils.checkpoint import (coordinated_any, emergency_dir,
                                            latest_step_dir,
                                            restore_latest_verified,
                                            save_checkpoint)

        # Entry into each collective restore protocol is itself a
        # collective decision (coordinated_any): a per-host listing probe
        # deciding entry would leave the host that sees a checkpoint
        # alone inside an allgather its stale-listing peer never joins.
        if coordinated_any(latest_step_dir(args.checkpoint_dir)
                           is not None):
            # Verified restore with fallback: a torn or bit-flipped newest
            # checkpoint (killed mid-save, disk rot) must never crash-loop
            # the resume — walk back to the newest intact step dir
            # (tpudp/utils/checkpoint.py::restore_latest_verified).
            # Multi-host, the walk is COORDINATED: hosts align on the
            # newest step every host sees, then vote per step dir
            # (unanimity) so every process resumes the SAME checkpoint —
            # a shard corrupt on one host rejects the dir for all, and
            # process 0 alone quarantines it.
            trainer.state, used, _skipped = restore_latest_verified(
                args.checkpoint_dir, trainer.state, log=print)
            start_epoch = int(used.rsplit("_", 1)[1])
            restored = True
            print(f"[tpudp] resumed from {used} (epoch {start_epoch})")
        # An emergency dump (watchdog-triggered, mid-epoch) is newer than any
        # epoch checkpoint: prefer its weights, then consume it so later
        # resumes fall back to the regular epoch series.
        emerg = emergency_dir(args.checkpoint_dir)
        if coordinated_any(emerg is not None) and emerg is None:
            # Stale listing on this host; the dump's location is fixed,
            # and the voted restore below decides its fate for all hosts.
            emerg = os.path.join(args.checkpoint_dir, "emergency")
        # tpudp: lint-ok(protocol-early-exit): `emerg` is host-uniform
        # by protocol at this point — coordinated_any above agreed on
        # whether a dump exists, and hosts with a stale listing were
        # fixed up to the shared dump path, so every host takes the
        # same arm here (the voted restore inside decides its fate).
        if emerg:
            # Refuse a mismatched relaunch BEFORE the dump is consumed:
            # the fast-forward below maps the optimizer-step counter onto
            # the loader's batch grid, which only works if this relaunch
            # has the same batches/epoch as the interrupted run (a changed
            # --batch-size or train-set size would silently re-train or
            # drop batches — round-3 advisor).  Old sentinels without the
            # field skip the check (nothing to compare against).
            from tpudp.utils.checkpoint import read_emergency_sentinel

            sent = read_emergency_sentinel(args.checkpoint_dir) or {}
            dumped_pe = sent.get("per_epoch_batches")
            if (not args.eval_only and dumped_pe is not None
                    and dumped_pe != len(train_loader)):
                # tpudp: lint-ok(protocol-early-exit): every host reads
                # the SAME sentinel file and computes the same loader
                # length from the same dataset/--batch-size, so a
                # batch-grid mismatch aborts the whole pod together —
                # no peer proceeds to the voted restore alone.
                raise SystemExit(
                    f"error: emergency dump at {emerg} was written with "
                    f"{dumped_pe} batches/epoch but this relaunch has "
                    f"{len(train_loader)} (different --batch-size or "
                    "train-set size) — the dump's step counter cannot be "
                    "mapped to a resume position on this batch grid. "
                    "Relaunch with the original configuration, or remove "
                    "the dump directory to restart the epoch from the "
                    "last step_N checkpoint.")
            # verify=True: the dump carries a checksum manifest (per-host
            # shard manifests on multi-host); a dump whose sentinel
            # committed but whose bytes rotted must fall back to the step
            # series, never crash-loop the resume.  Multi-host, the
            # accept/quarantine decision is UNANIMOUS: a shard corrupt on
            # one host rejects the dump for all, so no per-process
            # decision can leave hosts resuming different states
            # (tpudp/utils/checkpoint.py::restore_emergency_voted — the
            # same protocol auto_resume uses).
            from tpudp.utils.checkpoint import restore_emergency_voted

            dump_state = restore_emergency_voted(
                args.checkpoint_dir, emerg, trainer.state, log=print)
            if dump_state is not None:
                trainer.state = dump_state
            else:
                emerg = None
        # tpudp: lint-ok(protocol-early-exit): same justification as
        # the first `if emerg:` above — after the coordinated_any
        # fixup, emerg is None on every host or on none (and the voted
        # restore's outcome is collectively agreed), so all hosts take
        # the same arm into the consume barrier.
        if emerg:
            restored = True
            if args.eval_only:
                # Read-only use: evaluating the dump must not consume it —
                # the NEXT training restart still needs the mid-epoch state.
                print(f"[tpudp] evaluating emergency dump {emerg} "
                      "(left in place for the next training resume)")
            elif jax.process_count() > 1:
                # All processes must finish reading before rank 0 consumes
                # the directory.
                from jax.experimental import multihost_utils

                # tpudp: lint-ok(divergent-collective): the branch
                # condition is the OUTCOME of restore_emergency_voted —
                # a collectively-agreed value, identical on every host
                # by protocol, so all hosts take the same arm.
                multihost_utils.sync_global_devices("tpudp_emergency_restore")
            if not args.eval_only and jax.process_index() == 0:
                from tpudp.utils.checkpoint import consume_emergency

                consume_emergency(args.checkpoint_dir)
            if not args.eval_only:
                # Fast-forward instead of re-running the epoch head: the
                # dump's optimizer-step counter is one per loader batch
                # and the sampler order is deterministic per (seed,
                # epoch), so the counter alone fixes the resume position
                # — epoch = step // per_epoch, batches into it = step %
                # per_epoch.  Derived from the counter rather than the
                # step_N series on purpose: with --checkpoint-async the
                # dump can be AHEAD of the newest finalized epoch
                # checkpoint (the write was still in flight at the hang),
                # and anchoring on the stale series would silently
                # re-train the next epoch's head.  No batch is trained
                # twice, none is dropped.
                per_epoch = len(train_loader)
                start_epoch = int(trainer.state.step) // per_epoch
                skip_first = int(trainer.state.step) % per_epoch
                print(f"[tpudp] resumed mid-epoch state from emergency dump "
                      f"{emerg} (epoch {start_epoch}: fast-forwarding "
                      f"{skip_first}/{per_epoch} already-trained batches)")

        if args.checkpoint_async and not args.eval_only:
            # BEFORE the watchdog dump hook: the dump closure must drain
            # this writer's in-flight epoch-end save first — two orbax
            # writers interleaving in one root can tear both checkpoints.
            from tpudp.utils.checkpoint import AsyncCheckpointWriter

            async_writer = AsyncCheckpointWriter()

        if watchdog is not None and not args.resilience:
            # Failure recovery (VERDICT r1 #9): a detected hang dumps the
            # live TrainState before the process exits, so a wedged
            # collective loses at most the current epoch's progress since
            # the last completed step, not everything since the last epoch.
            # The closure (shared with the resilience supervisor's step
            # recovery) invalidates the previous dump's sentinel first,
            # waits out any overlapped async epoch-end write, saves, then
            # commits the sentinel only after orbax finalized.
            # NOT registered under --resilience: the supervisor dumps at
            # recovery time itself, and a second writer firing from the
            # watchdog thread into the same emergency root would race it
            # (two orbax writers in one root can tear both).
            from tpudp.resilience import make_emergency_dump

            _save = make_emergency_dump(
                args.checkpoint_dir, lambda: trainer.state,
                len(train_loader), async_writer=async_writer,
                log=lambda s: print(s, flush=True))

            def _emergency_dump() -> None:
                import threading

                # Bounded: saving fetches device buffers, and on a truly
                # wedged device that fetch can hang — the dump must never
                # stop the watchdog from killing the process.
                th = threading.Thread(target=_save, daemon=True)
                th.start()
                th.join(timeout=60.0)

            watchdog.on_hang.append(_emergency_dump)

        if watchdog is not None and args.resilience:
            # Hard-exit backstop: kill=False recovery only works for
            # stalls that RETURN (the StepHangError surfaces at the next
            # beat).  A truly wedged collective (dead peer) never returns
            # to a beat, so without this the process would hang forever —
            # strictly worse than the kill=True path it replaced.  If the
            # supervisor has not recovered (re-armed clears _hang_seen)
            # within a grace period, exit for the scheduler exactly like
            # the non-resilient watchdog.
            hang_gen = [0]  # per-hang generation: a stale backstop from
            # an already-recovered hang must not fire during a LATER
            # hang's still-in-grace recovery (that hang spawned its own
            # backstop with a fresh full grace period)

            def _hard_exit_backstop() -> None:
                import threading
                import time as _time

                hang_gen[0] += 1
                my_gen = hang_gen[0]

                def _backstop() -> None:
                    _time.sleep(max(args.step_timeout, 60.0))
                    if watchdog._hang_seen.is_set() and hang_gen[0] == my_gen:
                        print("[tpudp] hang NOT recovered in-process "
                              "(wedged collective?); exiting for "
                              "scheduler restart", flush=True)
                        os._exit(42)

                threading.Thread(target=_backstop, daemon=True).start()

            watchdog.on_hang.append(_hard_exit_backstop)

        def epoch_end_fn(epoch: int) -> None:
            path = os.path.join(args.checkpoint_dir, f"step_{epoch + 1}")
            if async_writer is not None:
                async_writer.save(path, trainer.state)
                print(f"[tpudp] checkpoint {path} writing in background")
            else:
                save_checkpoint(path, trainer.state)
                print(f"[tpudp] saved checkpoint {path}")
            if args.keep_checkpoints and jax.process_index() == 0:
                # By now the PREVIOUS step's write is durable (sync save, or
                # the async writer's serialized-saves guarantee), so pruning
                # older dirs always leaves a restorable latest checkpoint.
                from tpudp.utils.checkpoint import prune_step_dirs

                for gone in prune_step_dirs(args.checkpoint_dir,
                                            args.keep_checkpoints):
                    print(f"[tpudp] pruned old checkpoint {gone}")

    if args.eval_only:
        if not restored:
            raise SystemExit(
                f"error: --eval-only found no checkpoint under "
                f"{args.checkpoint_dir!r} — evaluating random weights "
                "would report meaningless metrics")
        from tpudp.utils.profiler import trace

        if watchdog is not None:
            watchdog.arm()  # fit() normally arms; eval-only must too
        try:
            with trace(args.profile_dir):
                trainer.evaluate(test_loader)
        finally:
            if watchdog is not None:
                watchdog.disarm()
                watchdog.stop()
        if args.profile_dir:
            print(f"[tpudp] profiler trace written to {args.profile_dir}")
        if metrics_server is not None:
            metrics_server.close()
        return trainer

    from tpudp.utils.profiler import trace

    resilience = None
    if args.resilience:
        from tpudp.resilience import ResiliencePolicy

        resilience = ResiliencePolicy(
            checkpoint_dir=args.checkpoint_dir,
            spike_factor=args.spike_factor,
            # epoch_end_fn above already saves step_{epoch+1} into the
            # same root; the supervisor must not double-write it.
            save_epoch_checkpoints=False,
            checkpoint_writer=async_writer,
            **({"max_rollbacks": args.max_rollbacks}
               if args.max_rollbacks is not None else {}),
        )

    try:
        with trace(args.profile_dir):
            trainer.fit(train_loader, test_loader, epochs=args.epochs,
                        start_epoch=start_epoch, epoch_end_fn=epoch_end_fn,
                        skip_batches_first_epoch=skip_first,
                        resilience=resilience)
    finally:
        if async_writer is not None:
            async_writer.close()  # join the last epoch's write
    if resilience is not None:
        s = trainer.stats
        print(f"[tpudp] resilience summary: {s.get('rollbacks', 0)} "
              f"rollbacks, {s.get('step_retries', 0)} step retries, "
              f"{s.get('ckpt_fallbacks', 0)} checkpoint fallbacks, "
              f"{s.get('loader_restarts', 0)} loader restarts")
    if watchdog is not None:
        watchdog.stop()
    if metrics_server is not None:
        metrics_server.close()
    if args.profile_dir:
        print(f"[tpudp] profiler trace written to {args.profile_dir}")
    return trainer

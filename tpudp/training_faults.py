"""Deterministic fault injection for the TRAINING stack — the trainer
analogue of ``tpudp/serve/faults.py``, and the resilience layer's test
fixtures plus the kill/resume soak's building blocks.

The supervisor's recovery claims (divergence rollback, in-process step
retry, loader containment, checkpoint-corruption fallback —
``tpudp/resilience.py``) are only worth anything if they are exercised by
REPRODUCIBLE faults: which batch is poisoned, which device call raises,
which checkpoint byte flips is fixed by constructor arguments, so a
failing soak seed replays exactly.

Three injection seams:

  * **Batch corruption** — :class:`CorruptingLoader` wraps any loader and
    poisons specific batch DRAWS (a global monotonically increasing draw
    counter): ``nan_at`` yields NaN images (NaN grads -> NaN params ->
    the ``check_finite`` window check fires — the divergence scenario),
    ``spike_at`` scales images by ``spike_scale`` (a finite loss spike
    for the trailing-median detector).  One-shot by construction: a
    rollback's deterministic replay re-draws batches under NEW counter
    values, so the poison never re-fires and the replay is clean —
    exactly how a transient production fault behaves.
  * **Step faults** — :class:`RaisingStep` and :class:`StallingStep` are
    ``Trainer(step_fault_hook=...)`` callables invoked as
    ``hook(kind, index)`` immediately before each jitted device call
    (``kind`` in ``{"train", "eval"}``; ``index`` is the trainer's
    monotonically increasing device-call counter, so a retried step gets
    a NEW index and a one-shot fault stays one-shot).  Raising simulates
    a device-step failure (XLA error, preempted TPU); sleeping simulates
    a wedged step for the watchdog to catch.
  * **Loader faults** — :class:`RaisingLoader` raises from the data
    pipeline at a specific draw, standing in for a dying loader /
    ``Prefetcher`` worker; the supervisor must restart the pipeline at
    the exact batch offset with host-RNG replay.

Plus :func:`corrupt_checkpoint`: deterministic on-disk corruption (byte
flip / truncation / manifest tamper) driving the verified-restore
fallback tests and the soak's corrupt-checkpoint phase.

Used by ``tests/test_resilience.py`` and the ``train_soak`` stage
(``benchmarks/resilience_bench.py``, registered in
``tools/bench_gaps.py``).
"""

from __future__ import annotations

import os
import time

import numpy as np


class InjectedTrainingFault(RuntimeError):
    """Raised by the injectors below — typed so tests can tell an
    injected failure from an organic one."""


class _LoaderWrapper:
    """Forwards the loader protocol (set_epoch/__len__/set_place) so a
    wrapped loader still composes with the Trainer and the Prefetcher."""

    def __init__(self, loader):
        self.loader = loader

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    def set_place(self, fn) -> None:
        if hasattr(self.loader, "set_place"):
            self.loader.set_place(fn)

    def __len__(self) -> int:
        return len(self.loader)


class CorruptingLoader(_LoaderWrapper):
    """Poisons specific batch draws.  ``nan_at``/``spike_at`` are
    collections of GLOBAL draw indices (0-based, counted across epochs and
    across pipeline restarts — the counter only moves forward, so a
    replayed batch is drawn under a new index and the fault is one-shot).

    ``fired`` records ``(kind, draw_index)`` for the soak's accounting:
    every fired fault must have a matching recovery in the event log."""

    def __init__(self, loader, *, nan_at=(), spike_at=(),
                 spike_scale: float = 1e4):
        super().__init__(loader)
        self.nan_at = set(nan_at)
        self.spike_at = set(spike_at)
        self.spike_scale = spike_scale
        self.draws = 0
        self.fired: list[tuple[str, int]] = []

    def __iter__(self):
        for images, labels, weights in self.loader:
            i = self.draws
            self.draws += 1
            if i in self.nan_at:
                self.fired.append(("nan", i))
                images = np.asarray(images) * np.float32(np.nan)
            elif i in self.spike_at:
                self.fired.append(("spike", i))
                images = np.asarray(images) * np.float32(self.spike_scale)
            yield images, labels, weights


class RaisingLoader(_LoaderWrapper):
    """Raises :class:`InjectedTrainingFault` instead of yielding the
    draws in ``fail_at`` (global draw indices; the failed draw is counted,
    so the restarted pipeline's replay passes it under a new index —
    one-shot, like a worker that died once)."""

    def __init__(self, loader, fail_at=()):
        super().__init__(loader)
        self.fail_at = set(fail_at)
        self.draws = 0
        self.fired: list[tuple[str, int]] = []

    def __iter__(self):
        for batch in self.loader:
            i = self.draws
            self.draws += 1
            if i in self.fail_at:
                self.fired.append(("loader", i))
                raise InjectedTrainingFault(
                    f"injected loader failure at draw {i}")
            yield batch


class RaisingStep:
    """Step-raise hook: raises :class:`InjectedTrainingFault` when the
    trainer's device-call ``index`` is in ``fail_at`` (optionally
    restricted to one ``kind``).  The hook runs before the device call,
    so the injected failure lands exactly where a real one would: inside
    the supervisor's step-recovery region.  ``persist_from`` instead
    fails EVERY call from that index on — the permanent-fault case the
    same-step escalation budget exists for."""

    def __init__(self, fail_at=(), kind: str | None = None,
                 persist_from: int | None = None):
        self.fail_at = set(fail_at)
        self.kind = kind
        self.persist_from = persist_from
        self.fired: list[tuple[str, int]] = []

    def __call__(self, kind: str, index: int) -> None:
        hit = index in self.fail_at or (
            self.persist_from is not None and index >= self.persist_from)
        if hit and (self.kind is None or kind == self.kind):
            self.fired.append((kind, index))
            raise InjectedTrainingFault(
                f"injected step fault at {kind} call {index}")


class StallingStep:
    """Step-stall hook: sleeps ``delay_s`` before the configured device
    calls — a deterministic stand-in for a wedged TPU step, used to
    exercise heartbeat-watchdog hang recovery (the sleep happens between
    two ``beat()`` calls, so a ``kill=False`` watchdog surfaces
    ``StepHangError`` at the next beat)."""

    def __init__(self, stall_at, delay_s: float, kind: str | None = None):
        self.stall_at = set(stall_at)
        self.delay_s = delay_s
        self.kind = kind
        self.fired: list[tuple[str, int]] = []

    def __call__(self, kind: str, index: int) -> None:
        if index in self.stall_at and (self.kind is None
                                       or kind == self.kind):
            self.fired.append((kind, index))
            time.sleep(self.delay_s)


def corrupt_checkpoint(path: str | os.PathLike, mode: str = "flip") -> str:
    """Deterministically corrupt the checkpoint at ``path``; returns the
    file touched.  Modes:

    * ``"flip"`` — XOR-flips one byte in the middle of the largest data
      file (silent bit rot: orbax may restore cleanly, the manifest
      checksum catches it; or orbax's own framing fails — either way the
      verified-restore fallback must engage)
    * ``"flip_shard"`` — same flip, but targeted at the largest file
      under the checkpoint's ``d/`` subtree — the OCDBT payload domain
      where a MULTI-HOST save's shard bytes live (the largest file
      overall in that layout is often process metadata whose flip orbax
      shrugs off).  This is "one host's shard rotted": the per-host
      crc32 shard manifests must catch it, on the saved geometry and on
      the reassembled view after an elastic restore.  Falls back to the
      plain flip when no ``d/`` subtree exists (single-host layouts).
    * ``"truncate"`` — cuts the largest file in half (torn write)
    * ``"manifest"`` — tampers a checksum in the sidecar manifest (the
      paranoid case: manifest and data disagree)
    """
    path = os.path.abspath(os.fspath(path))
    if mode == "manifest":
        import json

        from tpudp.utils.checkpoint import manifest_path

        mpath = manifest_path(path)
        with open(mpath) as f:
            manifest = json.load(f)
        leaves = manifest.get("leaves", {})
        for key in sorted(leaves):
            if "crc32" in leaves[key]:
                leaves[key]["crc32"] ^= 0x1
                break
        else:
            raise ValueError(f"no checksummed leaf in {mpath}")
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        return mpath
    if mode not in ("flip", "flip_shard", "truncate"):
        raise ValueError(f"unknown corruption mode {mode!r}")
    walk_root = path
    if mode == "flip_shard" and os.path.isdir(os.path.join(path, "d")):
        walk_root = os.path.join(path, "d")
    files = []
    for dirpath, _dirs, names in os.walk(walk_root):
        for name in names:
            p = os.path.join(dirpath, name)
            files.append((os.path.getsize(p), p))
    if not files:
        raise ValueError(f"no files under checkpoint dir {walk_root}")
    _, target = max(files)  # largest file = the biggest leaf's payload
    size = os.path.getsize(target)
    if mode == "truncate":
        with open(target, "r+b") as f:
            f.truncate(max(size // 2, 1))
        return target
    with open(target, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    return target

"""Per-row masked token sampling — the serve engine's sampling op.

``tpudp.models.generate._truncate_logits`` bakes one ``(temperature,
top_k, top_p)`` combination into the compiled program as Python statics —
right for ``generate()``, where the whole batch shares one request's
params.  A continuous-batching engine multiplexes requests with
DIFFERENT sampling params through one fixed-shape decode step, so here
they are TRACED ``(n,)`` arrays: admitting a request with a new
temperature or top-k must never recompile the step (the static-shape
invariant of tpudp.serve).

Per-row semantics match the static op row-wise:

  * ``temperature[i] == 0``  -> greedy argmax (top_k/top_p ignored);
  * ``top_k[i] == 0``        -> top-k disabled (keep the whole vocab);
  * ``top_p[i] == 1``        -> nucleus disabled;
  * the nucleus always keeps the highest-probability token, and
    truncation applies AFTER temperature scaling — both exactly like
    ``_truncate_logits``.

The dynamic top-k cannot use ``lax.top_k`` (its k is a static shape
parameter), so it is a rank mask off a descending sort of the vocab
axis; the nucleus then runs the static op's prefix-mass scan over the
top-k-MASKED distribution (the same composition order as
``_truncate_logits``: k-truncate, renormalize, then p-truncate).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def sample_tokens(logits: jnp.ndarray, temperature: jnp.ndarray,
                  top_k: jnp.ndarray, top_p: jnp.ndarray,
                  keys: jnp.ndarray) -> jnp.ndarray:
    """Sample one token per row from ``logits`` ``(n, vocab)`` fp32.

    ``temperature`` ``(n,)`` >= 0 (0 = greedy), ``top_k`` ``(n,)`` int32
    (0 = disabled), ``top_p`` ``(n,)`` in (0, 1] (1 = disabled), ``keys``
    ``(n, 2)`` uint32 — one PRNG key per row, so each row's draw stream
    is independent of its neighbours (a serve slot's sampled tokens must
    not depend on which other requests are co-resident).

    Returns ``(n,)`` int32 token ids.  All params are traced values —
    any combination runs through one compiled program.
    """
    n, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Scale first (like generate(): logits/T, THEN truncate).  Greedy rows
    # divide by 1 — their value never reaches the output anyway.
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t

    def _truncate(scaled):
        # Top-k FIRST, then the nucleus over the top-k-RENORMALIZED
        # distribution — the same composition order as _truncate_logits
        # (which masks to -inf before the nucleus softmax), so the two
        # ops keep identical token sets.  One descending sort serves
        # both: the k-masked -infs sink to the tail and contribute
        # exactly 0 nucleus mass.
        sorted_scaled = jnp.sort(scaled, axis=-1)[..., ::-1]

        # Dynamic top-k: keep rows' logits >= their k-th largest value.
        kth_idx = jnp.clip(top_k[:, None] - 1, 0, v - 1)
        kth = jnp.take_along_axis(sorted_scaled, kth_idx, axis=-1)
        keep_k = (top_k[:, None] <= 0) | (scaled >= kth)
        masked_k = jnp.where(keep_k, scaled, -jnp.inf)

        # Nucleus: keep ranks whose PRECEDING cumulative mass is < top_p
        # (so the argmax is always kept); cutoff = worst kept sorted
        # logit.  sorted_k re-sorts the MASKED array rather than rank-
        # masking sorted_scaled: `scaled >= kth` keeps ties at the k-th
        # value just like _truncate_logits, and only a sort of the
        # tie-inclusive mask reproduces its nucleus mass exactly.  Both
        # sorts sit behind the any_trunc cond — untruncated steps pay
        # neither.
        sorted_k = jnp.sort(masked_k, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_k, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        preceding = jnp.concatenate(
            [jnp.zeros_like(cum[..., :1]), cum[..., :-1]], -1)
        in_nucleus = preceding < top_p[:, None]
        cutoff = jnp.min(jnp.where(in_nucleus, sorted_k, jnp.inf),
                         axis=-1, keepdims=True)
        keep_p = (top_p[:, None] >= 1.0) | (masked_k >= cutoff)
        return jnp.where(keep_p, masked_k, -jnp.inf)

    def _with_sampling(scaled):
        # The vocab sort is the expensive piece (XLA CPU sorts are slow,
        # and even on TPU it is pure overhead for untruncated rows), so
        # it runs only when some sampled row actually truncates.
        any_trunc = jnp.any((temperature > 0)
                            & ((top_k > 0) | (top_p < 1.0)))
        masked = lax.cond(any_trunc, _truncate, lambda s: s, scaled)
        sampled = jax.vmap(
            lambda key, row: jax.random.categorical(key, row))(keys, masked)
        return jnp.where(temperature > 0, sampled.astype(jnp.int32), greedy)

    # Both gates are DATA (traced), not statics: one compiled program
    # serves every mix, but an all-greedy step pays argmax only.
    return lax.cond(jnp.any(temperature > 0), _with_sampling,
                    lambda scaled: greedy, scaled)


def split_keys(keys: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Split ``(n, 2)`` uint32 keys row-wise into (carry, subkey) pairs.

    The serve decode step draws with the subkeys and commits the carries
    only for rows that actually sampled this step, so a request's key
    chain advances once per OWN token — its draws are reproducible
    regardless of admission order or co-resident requests."""
    split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    return split[:, 0], split[:, 1]

"""Per-row masked token sampling + speculative-window verification — the
serve engine's sampling ops.

``tpudp.models.generate._truncate_logits`` bakes one ``(temperature,
top_k, top_p)`` combination into the compiled program as Python statics —
right for ``generate()``, where the whole batch shares one request's
params.  A continuous-batching engine multiplexes requests with
DIFFERENT sampling params through one fixed-shape decode step, so here
they are TRACED arrays: admitting a request with a new temperature or
top-k must never recompile the step (the static-shape invariant of
tpudp.serve).

:func:`truncate_logits` is the ONE implementation of top-k/top-p
truncation — ``generate()``'s static wrapper broadcasts its Python ints
into arrays and calls it, so the static and per-row paths cannot drift
(a parity test pins them).  Row-wise semantics:

  * ``temperature[i] == 0``  -> greedy argmax (top_k/top_p ignored);
  * ``top_k[i] == 0``        -> top-k disabled (keep the whole vocab);
  * ``top_p[i] == 1``        -> nucleus disabled;
  * the nucleus always keeps the highest-probability token, and
    truncation applies AFTER temperature scaling.

The dynamic top-k cannot use ``lax.top_k`` (its k is a static shape
parameter), so it is a rank mask off a descending sort of the vocab
axis; the nucleus then runs the prefix-mass scan over the top-k-MASKED
distribution (k-truncate, renormalize, then p-truncate).

:func:`verify_tokens` is the speculative-decoding acceptance rule over a
``k+1``-token window (tpudp.serve.speculate): greedy rows accept the
longest draft prefix matching the target argmax — bit-identical to
non-speculative decode — and sampled rows run standard rejection
sampling against the truncated target distribution, which preserves the
per-token output distribution exactly for deterministic drafters.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def truncate_logits(scaled: jnp.ndarray, top_k: jnp.ndarray,
                    top_p: jnp.ndarray) -> jnp.ndarray:
    """Mask ``scaled`` ``(..., vocab)`` outside the per-row top-k set /
    top-p nucleus to -inf.  ``top_k``/``top_p`` are traced arrays shaped
    like the leading dims (``top_k <= 0`` / ``top_p >= 1`` disable that
    truncation for the row).

    Top-k FIRST, then the nucleus over the top-k-RENORMALIZED
    distribution.  One descending sort serves the k rank mask; the
    k-masked -infs sink to the tail of the second sort and contribute
    exactly 0 nucleus mass.  ``scaled >= kth`` keeps ties at the k-th
    value, and only a sort of the tie-inclusive mask reproduces the
    nucleus mass over that exact token set, which is why the masked
    array is re-sorted rather than rank-masked.
    """
    v = scaled.shape[-1]
    sorted_scaled = jnp.sort(scaled, axis=-1)[..., ::-1]

    # Dynamic top-k: keep rows' logits >= their k-th largest value.
    kth_idx = jnp.clip(top_k[..., None] - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_scaled, kth_idx, axis=-1)
    keep_k = (top_k[..., None] <= 0) | (scaled >= kth)
    masked_k = jnp.where(keep_k, scaled, -jnp.inf)

    # Nucleus: keep ranks whose PRECEDING cumulative mass is < top_p (so
    # the argmax is always kept); cutoff = worst kept sorted logit.
    sorted_k = jnp.sort(masked_k, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_k, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    preceding = jnp.concatenate(
        [jnp.zeros_like(cum[..., :1]), cum[..., :-1]], -1)
    in_nucleus = preceding < top_p[..., None]
    cutoff = jnp.min(jnp.where(in_nucleus, sorted_k, jnp.inf),
                     axis=-1, keepdims=True)
    keep_p = (top_p[..., None] >= 1.0) | (masked_k >= cutoff)
    return jnp.where(keep_p, masked_k, -jnp.inf)


def sample_tokens(logits: jnp.ndarray, temperature: jnp.ndarray,
                  top_k: jnp.ndarray, top_p: jnp.ndarray,
                  keys: jnp.ndarray) -> jnp.ndarray:
    """Sample one token per row from ``logits`` ``(n, vocab)`` fp32.

    ``temperature`` ``(n,)`` >= 0 (0 = greedy), ``top_k`` ``(n,)`` int32
    (0 = disabled), ``top_p`` ``(n,)`` in (0, 1] (1 = disabled), ``keys``
    ``(n, 2)`` uint32 — one PRNG key per row, so each row's draw stream
    is independent of its neighbours (a serve slot's sampled tokens must
    not depend on which other requests are co-resident).

    Returns ``(n,)`` int32 token ids.  All params are traced values —
    any combination runs through one compiled program.
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Scale first (like generate(): logits/T, THEN truncate).  Greedy rows
    # divide by 1 — their value never reaches the output anyway.
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t

    def _with_sampling(scaled):
        # The vocab sorts are the expensive piece (XLA CPU sorts are
        # slow, and even on TPU they are pure overhead for untruncated
        # rows), so they run only when some sampled row truncates.
        any_trunc = jnp.any((temperature > 0)
                            & ((top_k > 0) | (top_p < 1.0)))
        masked = lax.cond(any_trunc,
                          lambda s: truncate_logits(s, top_k, top_p),
                          lambda s: s, scaled)
        sampled = jax.vmap(
            lambda key, row: jax.random.categorical(key, row))(keys, masked)
        return jnp.where(temperature > 0, sampled.astype(jnp.int32), greedy)

    # Both gates are DATA (traced), not statics: one compiled program
    # serves every mix, but an all-greedy step pays argmax only.
    return lax.cond(jnp.any(temperature > 0), _with_sampling,
                    lambda scaled: greedy, scaled)


def verify_tokens(logits: jnp.ndarray, draft: jnp.ndarray,
                  n_draft: jnp.ndarray, temperature: jnp.ndarray,
                  top_k: jnp.ndarray, top_p: jnp.ndarray,
                  keys: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Accept/reject a speculative window per row; emit its tokens.

    ``logits`` ``(n, W, vocab)`` fp32 are the target model's logits for a
    window of ``W = k+1`` fed tokens ``[last, d_0 .. d_{k-1}]``, so window
    slot ``j`` predicts the token AFTER draft ``j``'s position.  ``draft``
    ``(n, k)`` int32 holds the proposed tokens, ``n_draft`` ``(n,)`` how
    many are real for the row (0 = plain decode: the row just emits one
    token from slot 0).  Sampling params are per-row like
    :func:`sample_tokens`; ``keys`` ``(n, 2)`` are THIS window's subkeys
    (the caller owns the carry chain, advancing it once per verify step).

    Returns ``(tokens (n, W) int32, n_emitted (n,) int32)`` — the row's
    emitted tokens are ``tokens[:n_emitted]``; ``n_emitted - 1`` of the
    drafts were accepted and the final token is the free correction/bonus
    token from the rejecting (or last) window slot.

    Greedy rows accept the longest draft prefix equal to the target
    argmax and emit the argmax tokens themselves — bit-identical to
    feeding one token at a time.  Sampled rows use standard speculative
    rejection sampling with the drafter as a DETERMINISTIC (point-mass)
    proposal: accept ``d_j`` with probability ``p_j(d_j)``; on rejection
    resample from ``p_j`` with ``d_j`` masked out (the renormalized
    residual ``max(p - q, 0)``), which preserves the per-token target
    distribution exactly.  A draft outside the row's truncation set has
    ``p = 0`` and is always rejected.
    """
    n, W, v = logits.shape
    k = W - 1
    targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (n, W)
    draft_w = jnp.concatenate(
        [draft, jnp.zeros((n, 1), jnp.int32)], axis=1)       # (n, W)
    jidx = jnp.arange(k)[None, :]

    def _finish(accept):
        """Longest accepted prefix -> (a, out-template); the final token
        is filled in by the caller branch."""
        ok = accept & (jidx < n_draft[:, None])
        a = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1)
        return a

    def _emit(a, final):
        out = jnp.where(jnp.arange(W)[None, :] < a[:, None], draft_w,
                        final[:, None])
        return out.astype(jnp.int32), (a + 1).astype(jnp.int32)

    def _all_greedy(_):
        a = _finish(draft == targets[:, :k])
        final = jnp.take_along_axis(targets, a[:, None], axis=1)[:, 0]
        return _emit(a, final)

    def _with_sampling(_):
        safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None, None]
        scaled = logits / safe_t
        # Same truncation gate as sample_tokens: the window-shaped vocab
        # sorts run only when some sampled row actually truncates.
        any_trunc = jnp.any((temperature > 0)
                            & ((top_k > 0) | (top_p < 1.0)))
        kw = jnp.broadcast_to(top_k[:, None], (n, W))
        pw = jnp.broadcast_to(top_p[:, None], (n, W))
        masked = lax.cond(any_trunc,
                          lambda s: truncate_logits(s, kw, pw),
                          lambda s: s, scaled)
        probs = jax.nn.softmax(masked, axis=-1)
        p_draft = jnp.take_along_axis(
            probs[:, :k], draft[..., None], axis=-1)[..., 0]  # (n, k)
        # Acceptance uniforms come from the row subkey's split children;
        # they only influence rows that actually drafted (j < n_draft).
        subs = jax.vmap(lambda key: jax.random.split(key, max(k, 1)))(keys)
        u = jax.vmap(jax.vmap(jax.random.uniform))(subs[:, :k])
        accept = jnp.where(temperature[:, None] > 0, u < p_draft,
                           draft == targets[:, :k])
        a = _finish(accept)
        # Correction (rejection at slot a: resample with the rejected
        # draft masked out) or bonus (all accepted: slot n_draft as-is).
        row = jnp.take_along_axis(masked, a[:, None, None], axis=1)[:, 0]
        d_a = jnp.take_along_axis(draft_w, a[:, None], axis=1)[:, 0]
        rejected = a < n_draft
        corr = jnp.where(rejected[:, None]
                         & (jnp.arange(v)[None, :] == d_a[:, None]),
                         -jnp.inf, row)
        # The final draw uses the row's window subkey ITSELF — the exact
        # key sample_tokens would use in the decode step — so a row with
        # no drafts samples bit-identically whether the scheduler
        # dispatched a verify or a decode program this step (a request's
        # draw stream must never depend on co-residents' drafting).
        drawn = jax.vmap(jax.random.categorical)(keys, corr)
        final = jnp.where(temperature > 0, drawn.astype(jnp.int32),
                          jnp.take_along_axis(targets, a[:, None],
                                              axis=1)[:, 0])
        return _emit(a, final)

    return lax.cond(jnp.any(temperature > 0), _with_sampling, _all_greedy,
                    None)


def tree_depths(parents: tuple) -> tuple:
    """Static depth per tree node from a static ``parents`` tuple
    (``parents[0] == -1`` for the root; ``parents[j] < j`` — nodes are
    topologically ordered).  Plain Python: runs at trace time only."""
    depths = []
    for j, p in enumerate(parents):
        if j == 0:
            if p != -1:
                raise ValueError("parents[0] must be -1 (the root)")
            depths.append(0)
            continue
        if not 0 <= p < j:
            raise ValueError(
                f"parents[{j}] must be in [0, {j}) (topological order), "
                f"got {p}")
        depths.append(depths[p] + 1)
    return tuple(depths)


def verify_tree_tokens(logits: jnp.ndarray, cand: jnp.ndarray,
                       parents: tuple, n_cand: jnp.ndarray,
                       temperature: jnp.ndarray, top_k: jnp.ndarray,
                       top_p: jnp.ndarray, keys: jnp.ndarray):
    """Accept/reject a speculative token TREE per row; emit one
    root-to-leaf path's tokens.

    The tree generalizes :func:`verify_tokens`'s single draft sequence
    to a static shape of candidate branches scored by ONE forward:
    ``parents`` (a static tuple, ``parents[0] == -1``) names each
    node's parent; node 0 is the row's last committed token and nodes
    ``1..T`` are candidates whose tokens sit in ``cand`` ``(n, T)``.
    ``logits`` ``(n, T+1, vocab)`` are the target model's logits at
    every node (node ``j`` predicts the token AFTER node ``j``);
    ``n_cand`` rows with 0 run the plain no-draft decode.

    Walking from the root, each node's children are tried in node-index
    order.  Greedy rows accept the first child matching the current
    node's argmax — on a chain-shaped tree this is bit-identical to
    :func:`verify_tokens`'s greedy rule.  Sampled rows run sequential
    multi-candidate rejection sampling (the SpecInfer rule with
    point-mass proposals): accept child ``c`` with probability
    ``p(c)``; on rejection zero ``c``'s mass out of the residual and
    try the next sibling; when no child survives, the final token draws
    from the last residual with the row's window subkey ITSELF — so a
    no-candidate row samples bit-identically to the plain decode step,
    and on a chain the whole procedure is bit-identical to
    :func:`verify_tokens`.  Distribution-preserving either way.

    Returns ``(tokens (n, D+1) int32, n_emitted (n,) int32, path
    (n, D+1) int32)`` where ``D`` is the tree's max depth: the row
    emits ``tokens[:n_emitted]`` (``n_emitted - 1`` accepted candidates
    plus the final correction/bonus token) and ``path[d]`` is the
    accepted NODE id at depth ``d`` (``path[0] == 0``) — the caller
    commits exactly those nodes' KV.
    """
    n, Tp1, v = logits.shape
    T = Tp1 - 1
    depths = tree_depths(parents)
    W = max(depths) + 1
    targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (n, T+1)
    sampled = temperature > 0
    safe_t = jnp.where(sampled, temperature, 1.0)[:, None, None]
    scaled = logits / safe_t
    any_trunc = jnp.any(sampled & ((top_k > 0) | (top_p < 1.0)))
    kw = jnp.broadcast_to(top_k[:, None], (n, Tp1))
    pw = jnp.broadcast_to(top_p[:, None], (n, Tp1))
    masked = lax.cond(any_trunc,
                      lambda s: truncate_logits(s, kw, pw),
                      lambda s: s, scaled)
    # Acceptance uniforms: the window subkey's split children, one per
    # candidate node, in node order — on a chain this is exactly
    # verify_tokens' schedule (split(key, k), uniform per slot).
    subs = jax.vmap(lambda key: jax.random.split(key, max(T, 1)))(keys)
    u = jax.vmap(jax.vmap(jax.random.uniform))(subs[:, :T])  # (n, T)

    cur = jnp.zeros((n,), jnp.int32)          # current path node
    acc_d = jnp.zeros((n,), jnp.int32)        # accepted depth so far
    res = masked[:, 0]                        # residual logits at cur
    out = jnp.zeros((n, W), jnp.int32)
    path = jnp.zeros((n, W), jnp.int32)
    for j in range(1, T + 1):                 # static unroll (small T)
        pj, dj = parents[j], depths[j]
        tok = cand[:, j - 1]
        # Node j is in play iff the walk currently sits at its parent
        # (an accepted sibling moved `cur` past it; a deeper walk never
        # returns) and the row drafted this node.
        at = (cur == pj) & (j - 1 < n_cand)
        tgt = jnp.take_along_axis(targets, cur[:, None], axis=1)[:, 0]
        p_tok = jnp.take_along_axis(jax.nn.softmax(res, axis=-1),
                                    tok[:, None], axis=1)[:, 0]
        acc = at & jnp.where(sampled, u[:, j - 1] < p_tok, tok == tgt)
        rej = at & ~acc
        cur = jnp.where(acc, j, cur)
        acc_d = jnp.where(acc, dj, acc_d)
        out = out.at[:, dj - 1].set(jnp.where(acc, tok, out[:, dj - 1]))
        path = path.at[:, dj].set(jnp.where(acc, j, path[:, dj]))
        # Accept: the residual resets to the child's own distribution.
        # Reject: the sibling's mass is zeroed out of the residual (the
        # renormalized max(p - q, 0) of a point-mass proposal) before
        # the next sibling — or the final draw — is tried.
        res = jnp.where(
            acc[:, None], masked[:, j],
            jnp.where(rej[:, None] & (jnp.arange(v)[None, :]
                                      == tok[:, None]),
                      -jnp.inf, res))
    final_g = jnp.take_along_axis(targets, cur[:, None], axis=1)[:, 0]
    drawn = jax.vmap(jax.random.categorical)(keys, res)
    final = jnp.where(sampled, drawn.astype(jnp.int32), final_g)
    fin_col = jnp.arange(W)[None, :] == acc_d[:, None]
    out = jnp.where(fin_col, final[:, None], out)
    return out, (acc_d + 1).astype(jnp.int32), path


def split_keys(keys: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Split ``(n, 2)`` uint32 keys row-wise into (carry, subkey) pairs.

    The serve decode/verify steps draw with the subkeys and commit the
    carries only for rows that actually sampled this step, so a request's
    key chain advances once per OWN sampling event — its draws are
    reproducible regardless of admission order or co-resident requests."""
    split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    return split[:, 0], split[:, 1]

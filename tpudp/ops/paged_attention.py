"""Gather-free paged attention — index KV pages inside the attention
contraction, never materializing a slot's dense logical view.

PR 13's paged engine bought its capacity win (one shared refcounted page
pool, per-slot block tables, copy-on-write prefix reuse) by paying HBM
bandwidth every step: each paged program ran ``gather_pages`` (table →
full ``(layers, slots, max_len, kv_heads, dh)`` dense view), the exact
dense math, then ``scatter_pages`` — so a decode step that adds ONE
token's worth of state still streamed every live page through HBM
twice and held the whole view live across the forward.  Decode on TPU
is HBM-bandwidth-bound, not FLOP-bound (PAPERS.md arXiv:2204.06514), so
that traffic was the paged engine's perf ceiling.  This module removes
it: attention reads K/V **through the block table**, block-wise over
``(pages, page_size)`` tiles, one layer at a time.

Two backends behind one op:

  * ``impl='einsum'`` (the engine default) — **bit-exact**: per-page
    tiles ``pool[table]`` feed the contraction directly
    (``...d,bptkd->...pt``) and the flattened ``(pages·page_size)``
    logit axis gets exactly the dense path's visibility mask, fp32
    softmax, and P·V einsum.  XLA canonicalizes the ``(p, t)``
    contraction to the same gemm as the dense ``max_len`` axis, so fp
    outputs are **bitwise identical** to the dense math — which is what
    preserves the PR 13 parity oracle (paged ≡ dense ≡ ``generate()``)
    while the dense view and its scatter are gone (the committed budget
    ledger pins the peak-live drop).
  * ``impl='kernel'`` — a Pallas paged-decode kernel: grid over
    ``(slot, kv_pages)``, online-softmax carry (running max /
    denominator / output accumulator) in VMEM scratch exactly like the
    flash kernel, the block table and per-slot positions ride as
    SCALAR PREFETCH so each grid step's page is DMA'd straight from the
    pool by table value, ``-1`` (unmapped) entries skip their compute
    via ``pl.when``, and int8 pages dequantize in-kernel.  Tolerance-
    bounded like flash (online softmax rounds differently from the XLA
    chain), so the engine treats it as an explicit opt-in
    (``Engine(paged_attn='kernel')``).  Runs in interpret mode off-TPU
    so the same code is unit-testable on the CPU host.

The op covers both attention families the decode twins use: the GPT-2
MHA einsum forms and LLaMA's grouped (GQA) forms — selected by
``grouped`` so each family's paged math mirrors ITS dense twin
op-for-op (the bitwise contract is per-family).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_INF = -1e30
_LANES = 128  # per-row online-softmax scratch, broadcast over one lane tile
# The window/tree kernels' m/l stats keep one value per row; a narrow
# 8-lane declaration is enough (an f32 VMEM tile is (8, 128) — the
# array is lane-padded physically either way, but the narrow shape
# keeps the committed budget ledger honest about bytes the kernel
# actually carries).
_STAT_LANES = 8


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def page_tiles(pages, table, dtype):
    """Per-slot ``(b, M, T, kv, dh)`` K/V tiles indexed by the block
    table — the read half of the gather-free contract.  ``pages`` is
    the per-layer page buffer pair ``(k, v)`` (fp) or quadruple
    ``(k, v, k_scale, v_scale)`` (int8; dequantized here with exactly
    ``generate.gather_pages``'s math, so int8 tile values match the
    gather path's bit-for-bit).  Unmapped table entries (``-1``) clamp
    to the trailing scratch page; its garbage only ever lands at
    positions the visibility mask excludes — the same standing contract
    as the dense arena's garbage-beyond-``pos`` rows."""
    scratch = pages[0].shape[0] - 1
    tbl = jnp.where(table >= 0, table, scratch)
    if len(pages) == 4:
        k8, v8, ks, vs = pages
        k = (k8[tbl].astype(jnp.float32) * ks[tbl][..., None]).astype(dtype)
        v = (v8[tbl].astype(jnp.float32) * vs[tbl][..., None]).astype(dtype)
        return k, v
    k, v = pages
    return k[tbl].astype(dtype), v[tbl].astype(dtype)


def _einsum_paged(q, pages, table, pos, *, dtype, grouped):
    """The bit-exact blockwise path.  ``q``: ``(b, cur, h, dh)``;
    ``pos``: ``(b,)`` per-row depths (window position ``j`` attends
    keys ``<= pos + j`` — one contraction per position, the vmapped
    form that keeps a k+1 verify window bitwise equal to k+1 single
    steps) or a scalar (the prefill window: ONE batched contraction
    over the whole window, mirroring the scalar-``pos`` dense path)."""
    b, cur, h, dh = q.shape
    kt, vt = page_tiles(pages, table, dtype)  # (b, M, T, kv, dh)
    kv = kt.shape[3]
    max_len = kt.shape[1] * kt.shape[2]
    scale = dh ** -0.5
    pos = jnp.asarray(pos)

    if grouped:
        g = h // kv
        qg = q.reshape(b, cur, kv, g, dh)
        if pos.ndim:
            q_pos = pos[:, None] + jnp.arange(cur)  # (b, cur)

            def _attend(qj, pj):  # qj (b, kv, g, dh), pj (b,)
                lg = (jnp.einsum("bkgd,bptkd->bkgpt", qj, kt)
                      * scale).reshape(b, kv, g, max_len)
                vis = jnp.arange(max_len)[None, None, None, :] \
                    <= pj[:, None, None, None]
                lg = jnp.where(vis, lg, jnp.finfo(lg.dtype).min)
                pr = jax.nn.softmax(lg.astype(jnp.float32),
                                    axis=-1).astype(dtype)
                return jnp.einsum("bkgpt,bptkd->bkgd",
                                  pr.reshape(b, kv, g, *kt.shape[1:3]), vt)

            out = jax.vmap(_attend, in_axes=(1, 1), out_axes=1)(qg, q_pos)
        else:
            lg = (jnp.einsum("bqkgd,bptkd->bkgqpt", qg, kt)
                  * scale).reshape(b, kv, g, cur, max_len)
            q_pos = pos + jnp.arange(cur)[:, None]
            visible = jnp.arange(max_len)[None, :] <= q_pos
            lg = jnp.where(visible[None, None, None], lg,
                           jnp.finfo(lg.dtype).min)
            pr = jax.nn.softmax(lg.astype(jnp.float32),
                                axis=-1).astype(dtype)
            out = jnp.einsum("bkgqpt,bptkd->bqkgd",
                             pr.reshape(b, kv, g, cur, *kt.shape[1:3]), vt)
        return out.reshape(b, cur, h, dh)

    if pos.ndim:
        q_pos = pos[:, None] + jnp.arange(cur)  # (b, cur)

        def _attend(qj, pj):  # qj (b, h, dh), pj (b,)
            lg = (jnp.einsum("bhd,bpthd->bhpt", qj, kt)
                  * scale).reshape(b, h, max_len)
            vis = jnp.arange(max_len)[None, None, :] <= pj[:, None, None]
            lg = jnp.where(vis, lg, jnp.finfo(lg.dtype).min)
            pr = jax.nn.softmax(lg.astype(jnp.float32),
                                axis=-1).astype(dtype)
            return jnp.einsum("bhpt,bpthd->bhd",
                              pr.reshape(b, h, *kt.shape[1:3]), vt)

        return jax.vmap(_attend, in_axes=(1, 1), out_axes=1)(q, q_pos)

    lg = (jnp.einsum("bqhd,bpthd->bhqpt", q, kt)
          * scale).reshape(b, h, cur, max_len)
    q_pos = pos + jnp.arange(cur)[:, None]
    visible = jnp.arange(max_len)[None, :] <= q_pos
    lg = jnp.where(visible[None, None], lg, jnp.finfo(lg.dtype).min)
    pr = jax.nn.softmax(lg.astype(jnp.float32), axis=-1).astype(dtype)
    return jnp.einsum("bhqpt,bpthd->bqhd",
                      pr.reshape(b, h, cur, *kt.shape[1:3]), vt)


# ------------------------------------------------------- Pallas kernel


def _decode_kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
                   kv: int, groups: int, page_tokens: int, n_pages: int,
                   scale: float, int8: bool):
    """One ``(slot, page)`` grid step of the paged-decode kernel.

    The block specs already fetched THIS slot's page ``m`` by table
    value (the index maps read the scalar-prefetched table), so the
    kernel body only runs the online-softmax recurrence over the page's
    ``page_tokens`` keys — running max / denominator / accumulator
    carried in VMEM scratch across the page axis, exactly the flash
    kernel's recurrence with the K-block stream replaced by a
    table-indirected page stream."""
    import jax.lax as lax
    from jax.experimental import pallas as pl

    if int8:  # int8 payloads ride two extra per-vector scale blocks
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    s = pl.program_id(0)
    m = pl.program_id(1)
    h = kv * groups

    @pl.when(m == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    mapped = tbl_ref[s * n_pages + m] >= 0

    @pl.when(mapped)  # -1 (unmapped) pages: skip — nothing to attend
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # (h, dh)
        k_blk = k_ref[0].astype(jnp.float32)      # (T, kv, dh)
        v_blk = v_ref[0].astype(jnp.float32)
        if int8:
            k_blk = k_blk * ks_ref[0].astype(jnp.float32)[..., None]
            v_blk = v_blk * vs_ref[0].astype(jnp.float32)[..., None]
        # Query head j attends KV head j // groups (the GQA mapping;
        # groups == 1 is MHA).  Static per-KV-head 2D dots keep the MXU
        # happy — kv is a small compile-time constant.
        rows = []
        for ki in range(kv):
            qk = q[ki * groups:(ki + 1) * groups]  # (g, dh)
            rows.append(jnp.dot(qk, k_blk[:, ki, :].T,
                                preferred_element_type=jnp.float32))
        s_blk = jnp.concatenate(rows, axis=0)  # (h, T)
        k_pos = m * page_tokens + lax.broadcasted_iota(
            jnp.int32, (h, page_tokens), 1)
        s_blk = jnp.where(k_pos <= pos_ref[s], s_blk, _NEG_INF)
        m_prev = jnp.max(m_ref[...], axis=-1, keepdims=True)  # (h, 1)
        l_prev = jnp.max(l_ref[...], axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.max(s_blk, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s_blk - m_new)  # (h, T)
        l_ref[...] = jnp.broadcast_to(
            l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True),
            l_ref.shape)
        pv = []
        for ki in range(kv):
            pv.append(jnp.dot(p[ki * groups:(ki + 1) * groups],
                              v_blk[:, ki, :],
                              preferred_element_type=jnp.float32))
        acc_ref[...] = acc_ref[...] * alpha + jnp.concatenate(pv, axis=0)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(m == n_pages - 1)
    def _finalize():
        l_safe = jnp.maximum(jnp.max(l_ref[...], axis=-1, keepdims=True),
                             1e-30)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


# tpudp: kernel-program(serve.decode_paged_kernel)
def _kernel_paged(q, pages, table, pos, *, dtype, interpret, layer=None):
    """Dispatch one decode step (``cur == 1``) through the Pallas
    paged-decode kernel.  ``q``: ``(b, 1, h, dh)``; the grid is
    ``(b, M)`` with the online-softmax carry persisting across the
    inner (page) axis; the table row and per-slot positions are scalar
    prefetch, so each page block is DMA'd by TABLE VALUE — the gather
    never exists even as a transient.

    With ``layer`` (the engine's whole-pool mode) ``pages`` carry the
    FULL stacked pool ``(layers, ...)`` and the BlockSpec picks the
    stratum (a ``None`` block axis, squeezed out of the refs) — the
    layer slice is never materialized as an XLA value, so nothing
    beyond the pool itself is ever live at the call."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, cur, h, dh = q.shape
    assert cur == 1, "the paged-decode kernel is a 1-token decode kernel"
    int8 = len(pages) == 4
    lx = () if layer is None else (layer,)
    pb = (None,) * len(lx)  # layer block axis, squeezed out of the refs
    k_pages, v_pages = pages[0], pages[1]
    n_real = k_pages.shape[len(lx)] - 1  # trailing page is write scratch
    page_tokens = k_pages.shape[1 + len(lx)]
    kv = k_pages.shape[2 + len(lx)]
    n_pages = table.shape[1]
    groups = h // kv
    scale = dh ** -0.5
    scratch_page = n_real

    tbl = jnp.asarray(table, jnp.int32).reshape(-1)
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))

    def page_map(s, m, tbl_ref, pos_ref):
        t = tbl_ref[s * n_pages + m]
        return (*lx, jnp.where(t >= 0, t, scratch_page), 0, 0, 0)

    def scale_map(s, m, tbl_ref, pos_ref):
        t = tbl_ref[s * n_pages + m]
        return (*lx, jnp.where(t >= 0, t, scratch_page), 0, 0)

    kernel = functools.partial(
        _decode_kernel, kv=kv, groups=groups, page_tokens=page_tokens,
        n_pages=n_pages, scale=scale, int8=int8)
    ins = (pages[0], pages[1]) + ((pages[2], pages[3]) if int8 else ())
    in_specs = [
        pl.BlockSpec((1, h, dh), lambda s, m, t, p: (s, 0, 0)),
        pl.BlockSpec((*pb, 1, page_tokens, kv, dh), page_map),
        pl.BlockSpec((*pb, 1, page_tokens, kv, dh), page_map),
    ]
    if int8:
        in_specs += [pl.BlockSpec((*pb, 1, page_tokens, kv), scale_map),
                     pl.BlockSpec((*pb, 1, page_tokens, kv), scale_map)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, dh), lambda s, m, t, p: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, dh), jnp.float32),
            pltpu.VMEM((h, _LANES), jnp.float32),
            pltpu.VMEM((h, _LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, dh), dtype),
        interpret=interpret,
    )(tbl, pos, q[:, 0], *ins)
    return out[:, None]


def _window_tile(width: int) -> int:
    """Largest query-tile width ≤ 32 dividing the window — the chunk
    axis of the prefill grid (``chunk_tiles × kv_pages``).  Verify
    windows (k+1 ≤ 32) always fit one tile."""
    for cand in range(min(width, 32), 0, -1):
        if width % cand == 0:
            return cand
    return width


def _window_kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
                   kv: int, groups: int, width: int, page_tokens: int,
                   n_pages: int, scale: float, int8: bool):
    """One ``(slot, query-tile, page)`` grid step of the paged
    flash-window kernel — the multi-token generalization of
    ``_decode_kernel`` that covers chunked prefill (scalar base
    position, ``width`` = chunk tile) and the k+1 speculative verify
    window (vector base positions, one tile).

    Query rows are flattened KV-head-major — row
    ``r = ki·(width·groups) + j·groups + gi`` — so each KV head's rows
    are one contiguous 2D dot against its page slice, and the causal
    in-window mask is per ROW: window position ``j`` sees keys
    ``<= pos[slot] + j`` (the engine writes the window's K/V into pages
    BEFORE attending, so in-window causality and cache visibility are
    the same comparison)."""
    import jax.lax as lax
    from jax.experimental import pallas as pl

    if int8:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    s = pl.program_id(0)
    t = pl.program_id(1)
    m = pl.program_id(2)
    rows = kv * width * groups
    dh = q_ref.shape[-1]

    @pl.when(m == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    mapped = tbl_ref[s * n_pages + m] >= 0

    @pl.when(mapped)  # -1 (unmapped) pages: skip — nothing to attend
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # (width, h, dh)
        k_blk = k_ref[0].astype(jnp.float32)      # (T, kv, dh)
        v_blk = v_ref[0].astype(jnp.float32)
        if int8:
            k_blk = k_blk * ks_ref[0].astype(jnp.float32)[..., None]
            v_blk = v_blk * vs_ref[0].astype(jnp.float32)[..., None]
        blocks = []
        for ki in range(kv):
            qk = q[:, ki * groups:(ki + 1) * groups, :].reshape(
                width * groups, dh)
            blocks.append(jnp.dot(qk, k_blk[:, ki, :].T,
                                  preferred_element_type=jnp.float32))
        s_blk = jnp.concatenate(blocks, axis=0)  # (rows, T)
        k_pos = m * page_tokens + lax.broadcasted_iota(
            jnp.int32, (rows, page_tokens), 1)
        row_ids = lax.broadcasted_iota(jnp.int32, (rows, page_tokens), 0)
        win_j = t * width + (row_ids % (width * groups)) // groups
        s_blk = jnp.where(k_pos <= pos_ref[s] + win_j, s_blk, _NEG_INF)
        m_prev = jnp.max(m_ref[...], axis=-1, keepdims=True)  # (rows, 1)
        l_prev = jnp.max(l_ref[...], axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.max(s_blk, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s_blk - m_new)  # (rows, T)
        l_ref[...] = jnp.broadcast_to(
            l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True),
            l_ref.shape)
        pv = []
        for ki in range(kv):
            pv.append(jnp.dot(
                p[ki * width * groups:(ki + 1) * width * groups],
                v_blk[:, ki, :], preferred_element_type=jnp.float32))
        acc_ref[...] = acc_ref[...] * alpha + jnp.concatenate(pv, axis=0)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(m == n_pages - 1)
    def _finalize():
        l_safe = jnp.maximum(jnp.max(l_ref[...], axis=-1, keepdims=True),
                             1e-30)
        out = acc_ref[...] / l_safe  # (rows, dh), kv-head-major
        for ki in range(kv):
            blk = out[ki * width * groups:(ki + 1) * width * groups]
            o_ref[0, :, ki * groups:(ki + 1) * groups, :] = (
                blk.reshape(width, groups, dh).astype(o_ref.dtype))


# tpudp: kernel-program(serve.verify_paged_kernel)
def _window_paged(q, pages, table, pos, *, dtype, interpret, layer=None):
    """Dispatch a multi-token window (k+1 verify, vector ``pos``; or a
    prefill chunk, scalar ``pos``) through the flash-window kernel.
    Grid ``(b, chunk_tiles, M)`` with the online-softmax carry
    persisting across the inner page axis — the prefill grid the ISSUE
    names, with verify as the one-tile case.  ``layer`` selects a
    stratum of a full stacked pool via the BlockSpec (see
    :func:`_kernel_paged`) — no layer slice is ever materialized."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, cur, h, dh = q.shape
    int8 = len(pages) == 4
    lx = () if layer is None else (layer,)
    pb = (None,) * len(lx)
    k_pages = pages[0]
    page_tokens = k_pages.shape[1 + len(lx)]
    kv = k_pages.shape[2 + len(lx)]
    n_pages = table.shape[1]
    groups = h // kv
    scale = dh ** -0.5
    scratch_page = k_pages.shape[len(lx)] - 1
    width = _window_tile(cur)
    q_tiles = cur // width
    rows = kv * width * groups

    tbl = jnp.asarray(table, jnp.int32).reshape(-1)
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))

    def page_map(s, t, m, tbl_ref, pos_ref):
        pg = tbl_ref[s * n_pages + m]
        return (*lx, jnp.where(pg >= 0, pg, scratch_page), 0, 0, 0)

    def scale_map(s, t, m, tbl_ref, pos_ref):
        pg = tbl_ref[s * n_pages + m]
        return (*lx, jnp.where(pg >= 0, pg, scratch_page), 0, 0)

    kernel = functools.partial(
        _window_kernel, kv=kv, groups=groups, width=width,
        page_tokens=page_tokens, n_pages=n_pages, scale=scale, int8=int8)
    ins = (pages[0], pages[1]) + ((pages[2], pages[3]) if int8 else ())
    in_specs = [
        pl.BlockSpec((1, width, h, dh),
                     lambda s, t, m, tb, p: (s, t, 0, 0)),
        pl.BlockSpec((*pb, 1, page_tokens, kv, dh), page_map),
        pl.BlockSpec((*pb, 1, page_tokens, kv, dh), page_map),
    ]
    if int8:
        in_specs += [pl.BlockSpec((*pb, 1, page_tokens, kv), scale_map),
                     pl.BlockSpec((*pb, 1, page_tokens, kv), scale_map)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, q_tiles, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, width, h, dh),
                               lambda s, t, m, tb, p: (s, t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rows, dh), jnp.float32),
            pltpu.VMEM((rows, _STAT_LANES), jnp.float32),
            pltpu.VMEM((rows, _STAT_LANES), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, cur, h, dh), dtype),
        interpret=interpret,
    )(tbl, pos, q, *ins)


def _tree_kernel(tbl_ref, pos_ref, anc_ref, q_ref, k_ref, v_ref,
                 wk_ref, wv_ref, o_ref, acc_ref, m_ref, l_ref, *,
                 kv: int, groups: int, t1: int, page_tokens: int,
                 n_pages: int, scale: float):
    """One ``(slot, page-or-window)`` grid step of the tree-verify
    kernel.  Steps ``m < n_pages`` stream the slot's CACHE pages with
    strict visibility ``k_pos < pos0[slot]`` (tree nodes occupy
    ``pos0..``, so committed state is everything strictly before); the
    extra final step ``m == n_pages`` folds the T+1 in-flight window
    keys into the same online softmax under the ancestor-or-self mask,
    which rides as a scalar-prefetched per-shape constant (the parents
    tuple is static engine config, part of the compile key)."""
    import jax.lax as lax
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    m = pl.program_id(1)
    rows = kv * t1 * groups
    dh = q_ref.shape[-1]

    @pl.when(m == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _scores(k_src):
        q = q_ref[0].astype(jnp.float32) * scale  # (t1, h, dh)
        blocks = []
        for ki in range(kv):
            qk = q[:, ki * groups:(ki + 1) * groups, :].reshape(
                t1 * groups, dh)
            blocks.append(jnp.dot(qk, k_src[:, ki, :].T,
                                  preferred_element_type=jnp.float32))
        return jnp.concatenate(blocks, axis=0)  # (rows, n_keys)

    def _update(s_blk, v_src):
        m_prev = jnp.max(m_ref[...], axis=-1, keepdims=True)
        l_prev = jnp.max(l_ref[...], axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.max(s_blk, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s_blk - m_new)
        l_ref[...] = jnp.broadcast_to(
            l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True),
            l_ref.shape)
        pv = []
        for ki in range(kv):
            pv.append(jnp.dot(
                p[ki * t1 * groups:(ki + 1) * t1 * groups],
                v_src[:, ki, :], preferred_element_type=jnp.float32))
        acc_ref[...] = acc_ref[...] * alpha + jnp.concatenate(pv, axis=0)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)

    mi = jnp.minimum(m, n_pages - 1)  # keep the SMEM read in bounds
    mapped = (m < n_pages) & (tbl_ref[s * n_pages + mi] >= 0)

    @pl.when(mapped)
    def _cache_page():
        k_blk = k_ref[0].astype(jnp.float32)  # (T, kv, dh)
        v_blk = v_ref[0].astype(jnp.float32)
        s_blk = _scores(k_blk)
        k_pos = mi * page_tokens + lax.broadcasted_iota(
            jnp.int32, (rows, page_tokens), 1)
        s_blk = jnp.where(k_pos < pos_ref[s], s_blk, _NEG_INF)
        _update(s_blk, v_blk)

    @pl.when(m == n_pages)
    def _window_block():
        wk = wk_ref[0].astype(jnp.float32)  # (t1, kv, dh)
        wv = wv_ref[0].astype(jnp.float32)
        s_blk = _scores(wk)  # (rows, t1)
        anc = jnp.array([[anc_ref[j * t1 + c] for c in range(t1)]
                         for j in range(t1)])  # (t1, t1) from SMEM
        per_node = jnp.broadcast_to(
            anc[:, None, :], (t1, groups, t1)).reshape(t1 * groups, t1)
        mask = jnp.broadcast_to(
            per_node[None], (kv, t1 * groups, t1)).reshape(rows, t1)
        s_blk = jnp.where(mask > 0, s_blk, _NEG_INF)
        _update(s_blk, wv)
        l_safe = jnp.maximum(jnp.max(l_ref[...], axis=-1, keepdims=True),
                             1e-30)
        out = acc_ref[...] / l_safe
        for ki in range(kv):
            blk = out[ki * t1 * groups:(ki + 1) * t1 * groups]
            o_ref[0, :, ki * groups:(ki + 1) * groups, :] = (
                blk.reshape(t1, groups, dh).astype(o_ref.dtype))


# tpudp: kernel-program(serve.tree_verify_paged_kernel)
def _tree_paged(q, pages, table, pos0, wk, wv, anc, *, dtype, interpret):
    """Dispatch the static tree-verify forward through the tree kernel:
    grid ``(b, M + 1)`` — the cache pages plus ONE extra grid step for
    the in-flight window keys (never written to pages; rejected
    branches must leave zero pool bytes, so the window rides as its own
    VMEM block).  fp pools only — int8 pools fall back to the einsum
    tree path at the engine layer."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if len(pages) == 4:
        raise NotImplementedError(
            "the tree-verify kernel reads fp pages only; int8 pools take "
            "the einsum fallback (Engine records the dispatch)")
    b, t1, h, dh = q.shape
    k_pages = pages[0]
    page_tokens, kv = k_pages.shape[1], k_pages.shape[2]
    n_pages = table.shape[1]
    groups = h // kv
    scale = dh ** -0.5
    scratch_page = k_pages.shape[0] - 1

    tbl = jnp.asarray(table, jnp.int32).reshape(-1)
    pos0 = jnp.broadcast_to(jnp.asarray(pos0, jnp.int32), (b,))
    anc_flat = jnp.asarray(anc, jnp.int32).reshape(-1)

    def page_map(s, m, tbl_ref, pos_ref, anc_ref):
        mi = jnp.minimum(m, n_pages - 1)
        pg = tbl_ref[s * n_pages + mi]
        pg = jnp.where((m < n_pages) & (pg >= 0), pg, scratch_page)
        return (pg, 0, 0, 0)

    def slot_map(s, m, tbl_ref, pos_ref, anc_ref):
        return (s, 0, 0, 0)

    kernel = functools.partial(
        _tree_kernel, kv=kv, groups=groups, t1=t1,
        page_tokens=page_tokens, n_pages=n_pages, scale=scale)
    rows = kv * t1 * groups
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, n_pages + 1),
        in_specs=[
            pl.BlockSpec((1, t1, h, dh), slot_map),
            pl.BlockSpec((1, page_tokens, kv, dh), page_map),
            pl.BlockSpec((1, page_tokens, kv, dh), page_map),
            pl.BlockSpec((1, t1, kv, dh), slot_map),
            pl.BlockSpec((1, t1, kv, dh), slot_map),
        ],
        out_specs=pl.BlockSpec((1, t1, h, dh), slot_map),
        scratch_shapes=[
            pltpu.VMEM((rows, dh), jnp.float32),
            pltpu.VMEM((rows, _STAT_LANES), jnp.float32),
            pltpu.VMEM((rows, _STAT_LANES), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, t1, h, dh), dtype),
        interpret=interpret,
    )(tbl, pos0, anc_flat, q, *pages, wk, wv)


# ----------------------------------------------------------- public op


def paged_attention(q, pages, table, pos, *, dtype, grouped: bool = False,
                    impl: str = "einsum", interpret: bool | None = None,
                    layer: int | None = None) -> jnp.ndarray:
    """Attention for already-projected queries over table-indirected
    K/V pages — the ONE paged-attention op behind the serve engine's
    gather-free step programs.

    ``q``: ``(b, cur, heads, dh)`` queries (RoPE already applied for
    LLaMA).  ``pages``: one LAYER's page buffers — ``(k, v)`` each
    ``(num_pages + 1, page_tokens, kv_heads, dh)`` (the last page is
    the write scratch), or ``(k, v, k_scale, v_scale)`` for int8
    payloads.  ``table``: ``(b, max_pages)`` int32 block table, ``-1``
    unmapped.  ``pos``: ``(b,)`` per-row depths (window position ``j``
    attends keys ``<= pos[b] + j``; the serve engine's vector-position
    contract) or a scalar (the prefill window's shared depth).
    ``grouped`` selects the GQA einsum family (LLaMA's dense-twin
    forms) over the MHA family (GPT-2's) so the fp path stays bitwise
    identical to whichever dense twin the caller mirrors.

    ``impl='einsum'`` is bit-exact vs the dense math on the gathered
    view; ``impl='kernel'`` routes the whole serving hot path through
    Pallas: single-token vector-position calls hit the paged-decode
    kernel, multi-token windows (the k+1 verify window) and scalar-
    position prefill chunks hit the flash-window kernel.  Both are
    tolerance-bounded like flash (online softmax rounds differently
    from the XLA chain); the einsum path stays the bit-exact fallback
    the engine selects per-program when a feature lacks kernel
    support.

    ``layer`` (kernel impl only) is whole-pool mode: ``pages`` carry
    the FULL stacked pool and the kernels' BlockSpecs pick the stratum
    — the per-layer slice never exists as an XLA value."""
    if impl not in ("einsum", "kernel"):
        raise ValueError(
            f"unknown paged-attention impl {impl!r}; choose from "
            f"'einsum' (bit-exact blockwise) or 'kernel' (Pallas decode)")
    if layer is not None and impl != "kernel":
        raise ValueError("whole-pool layer indexing is kernel-impl only")
    pos = jnp.asarray(pos)
    if impl == "kernel":
        if interpret is None:
            interpret = _interpret_default()
        if pos.ndim and q.shape[1] == 1:
            return _kernel_paged(q, pages, table, pos, dtype=dtype,
                                 interpret=interpret, layer=layer)
        return _window_paged(q, pages, table, pos, dtype=dtype,
                             interpret=interpret, layer=layer)
    return _einsum_paged(q, pages, table, pos, dtype=dtype,
                         grouped=grouped)


def tree_paged_attention(q, pages, table, pos0, wk, wv, anc, *, dtype,
                         interpret: bool | None = None) -> jnp.ndarray:
    """Tree-structured attention over table-indirected cache pages plus
    an in-flight node window — the kernel half of ``tree_verify_paged``.

    ``q``: ``(b, T+1, heads, dh)`` node queries; ``wk``/``wv``:
    ``(b, T+1, kv, dh)`` window K/V (computed this forward, NEVER
    written to pages — rejected branches must leave zero pool bytes);
    ``anc``: the static ``(T+1, T+1)`` ancestor-or-self mask (row j
    sees column c iff c is an ancestor of j or j itself), entering the
    kernel as a scalar-prefetched per-shape constant.  Cache visibility
    is strict ``k_pos < pos0`` — the committed prefix only.  fp pools
    only; the engine keeps int8 tree traffic on the einsum fallback."""
    if interpret is None:
        interpret = _interpret_default()
    return _tree_paged(q, pages, table, pos0, wk, wv, anc, dtype=dtype,
                       interpret=interpret)

"""Pallas TPU flash attention — the framework's owned hot-op kernel.

The reference delegates every op to ATen's C++ kernels (SURVEY.md §2.3);
here the attention hot op is a first-party Pallas kernel instead of an XLA
einsum chain:

  * Blocked online-softmax forward (flash-attention recurrence): the
    ``(t, t)`` score matrix is never materialized — and K/V are BLOCKED
    THROUGH THE GRID, not staged whole into VMEM: the grid is
    ``(batch·head, q_blocks, k_blocks)`` with the online-softmax state
    (running max / denominator / output accumulator) carried across the
    innermost K dimension in VMEM scratch.  Per-invocation VMEM is
    O((block_q + block_k)·dh) regardless of sequence length, so the kernel
    keeps scaling at t = 8k/16k+ where a whole-sequence K/V stage would
    overflow VMEM (round-1 weakness; Pallas double-buffers the K/V block
    fetches so HBM reads overlap the MXU matmuls).
  * Custom VJP with the standard two-kernel backward (a dq kernel gridded
    over (q_blocks, k_blocks) and a dk/dv kernel gridded over
    (k_blocks, q_blocks)), recomputing probabilities from the saved
    log-sum-exp rather than storing them — same grid-blocked structure.
  * Causal masking skips the compute of fully-masked blocks via
    ``pl.when`` (their tiles still stream, the MXU work is elided), and
    masks the diagonal tile elementwise.
  * Runs in interpret mode off-TPU, so the same code is unit-testable on the
    CPU simulator mesh (tests/test_flash_attention.py checks fwd and grads
    against a dense oracle).

Layouts: public API takes ``(batch, time, heads, head_dim)`` (the layout the
models use); the kernels run per ``(batch·head)`` with ``(time, head_dim)``
blocks. Compute is fp32 regardless of input dtype (MXU accumulate).
The running max/denominator scratch rows are stored broadcast across a
128-lane tile (Mosaic-friendly layout); reads reduce over lanes.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128  # scalar-per-row scratch is stored broadcast over one lane tile


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _read_rows(ref) -> jnp.ndarray:
    """(rows, LANES) scratch -> (rows, 1); every lane holds the same value."""
    return jnp.max(ref[...], axis=-1, keepdims=True)


def _write_rows(ref, val) -> None:
    ref[...] = jnp.broadcast_to(val, ref.shape)


# ---------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, causal: bool, scale: float, nk: int):
    bq, dh = q_ref.shape[1], q_ref.shape[2]
    bk = k_ref.shape[1]
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        _write_rows(m_ref, jnp.full((bq, 1), _NEG_INF, jnp.float32))
        _write_rows(l_ref, jnp.zeros((bq, 1), jnp.float32))

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, _NEG_INF)
        m_prev = _read_rows(m_ref)
        l_prev = _read_rows(l_ref)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        _write_rows(l_ref, l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True))
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)
        _write_rows(m_ref, m_new)

    if causal:
        # K blocks strictly above the diagonal contribute nothing: elide
        # their compute (the tile stream is pipelined regardless).
        @pl.when(ki * bk < (qi + 1) * bq)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        l_safe = jnp.maximum(_read_rows(l_ref), 1e-30)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = (_read_rows(m_ref) + jnp.log(l_safe)).reshape(1, bq)


def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    """q,k,v: (bh, t, dh) fp32/bf16 -> (o (bh,t,dh), lse (bh,t) f32)."""
    bh, t, dh = q.shape
    scale = 1.0 / math.sqrt(dh)
    nk = t // block_k
    grid = (bh, t // block_q, nk)
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale, nk=nk)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, dh), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, dh), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse.reshape(bh, t)


# --------------------------------------------------------------- backward


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, causal: bool, scale: float, nk: int):
    bq, dh = q_ref.shape[1], q_ref.shape[2]
    bk = k_ref.shape[1]
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0].reshape(bq, 1)
        delta = delta_ref[0].reshape(bq, 1)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        p = jnp.exp(s - lse)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_acc[...] += jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ki * bk < (qi + 1) * bq)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, causal: bool,
                scale: float, nq: int):
    bk, dh = k_ref.shape[1], k_ref.shape[2]
    bq = q_ref.shape[1]
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0].reshape(bq, 1)
        delta = delta_ref[0].reshape(bq, 1)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        p = jnp.exp(s - lse)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dv_acc[...] += jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        # scale is already folded into q, so dk = dsᵀ·(q·scale) is complete
        dk_acc[...] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)

    if causal:
        # Q blocks strictly above this K block see none of it.
        @pl.when((qi + 1) * bq > ki * bk)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, o, lse, do, causal, block_q, block_k, interpret):
    bh, t, dh = q.shape
    scale = 1.0 / math.sqrt(dh)
    # delta_i = rowsum(do_i * o_i) — the softmax-jacobian correction term.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).reshape(bh, 1, t)
    lse3 = lse.reshape(bh, 1, t)
    nq, nk = t // block_q, t // block_k

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale, nk=nk),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, dh), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, dh), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse3, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale, nq=nq),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, j)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, dh), k.dtype),
            jax.ShapeDtypeStruct((bh, t, dh), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, dh), jnp.float32),
            pltpu.VMEM((block_k, dh), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse3, delta)
    return dq, dk, dv


# ------------------------------------------------------------- public API


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    return _flash_bwd_impl(q, k, v, o, lse, do, causal, block_q, block_k,
                           interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Blocked flash attention. ``q, k, v``: ``(batch, time, heads, head_dim)``.

    ``time`` must be divisible by the block sizes (blocks are clamped to
    ``time`` when shorter). Differentiable (custom VJP); off-TPU the kernels
    run in Pallas interpret mode so tests work on the CPU simulator.

    Compiled (TPU) mode requires lane-aligned blocks: ``block_q``/``block_k``
    must be multiples of 128 (Mosaic tiling: the log-sum-exp blocks put
    ``block_q`` in the lane dimension). Interpret mode has no such limit.
    """
    if interpret is None:
        interpret = _interpret_default()
    b, t, h, dh = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"time {t} not divisible by blocks ({block_q},{block_k})")
    if not interpret and (block_q % 128 or block_k % 128):
        raise ValueError(
            f"compiled TPU mode needs block sizes that are multiples of 128 "
            f"(got block_q={block_q}, block_k={block_k}; time={t} — for "
            f"shorter sequences use dense attention or interpret=True)")

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, dh)

    o = _flash(to_bh(q), to_bh(k), to_bh(v), causal, block_q, block_k,
               interpret)
    return o.reshape(b, h, t, dh).transpose(0, 2, 1, 3)

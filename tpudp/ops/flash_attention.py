"""Pallas TPU flash attention — the framework's owned hot-op kernel.

The reference delegates every op to ATen's C++ kernels (SURVEY.md §2.3);
here the attention hot op is a first-party Pallas kernel instead of an XLA
einsum chain:

  * Blocked online-softmax forward (flash-attention recurrence): the
    ``(t, t)`` score matrix is never materialized — each grid step holds one
    ``(block_q, block_k)`` tile in VMEM, so memory is O(t · d) not O(t²) and
    the tiles feed the MXU back-to-back.
  * Custom VJP with the standard two-kernel backward (a dq kernel gridded
    over Q blocks and a dk/dv kernel gridded over K blocks), recomputing
    probabilities from the saved log-sum-exp rather than storing them.
  * Causal masking skips fully-masked K blocks via the loop bound (the tail
    tile is masked elementwise), so causal costs ~half the FLOPs.
  * Runs in interpret mode off-TPU, so the same code is unit-testable on the
    CPU simulator mesh (tests/test_flash_attention.py checks fwd and grads
    against a dense oracle).

Layouts: public API takes ``(batch, time, heads, head_dim)`` (the layout the
models use); the kernels run per ``(batch·head)`` with ``(time, head_dim)``
blocks. Compute is fp32 regardless of input dtype (MXU accumulate).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                causal: bool, scale: float):
    bq, dh = q_ref.shape[1], q_ref.shape[2]
    qi = pl.program_id(1)
    t = k_ref.shape[1]
    nk = t // block_k

    q = q_ref[0].astype(jnp.float32) * scale

    m = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, dh), jnp.float32)

    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, _NEG_INF)
        blk_max = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, blk_max)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l, acc

    # Causal: K blocks strictly above the diagonal contribute nothing — stop
    # the loop at the diagonal block instead of masking them.  upper <= nk
    # because t % block_k == 0 (checked in flash_attention()).
    upper = ((qi + 1) * bq + block_k - 1) // block_k if causal else nk
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m, l, acc))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l_safe)).reshape(1, bq)


def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    """q,k,v: (bh, t, dh) fp32/bf16 -> (o (bh,t,dh), lse (bh,t) f32)."""
    bh, t, dh = q.shape
    scale = 1.0 / math.sqrt(dh)
    grid = (bh, t // block_q)
    kernel = functools.partial(_fwd_kernel, block_k=block_k, causal=causal,
                               scale=scale)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, dh), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t, dh), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dh), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, dh), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse.reshape(bh, t)


# --------------------------------------------------------------- backward


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               block_k: int, causal: bool, scale: float):
    bq, dh = q_ref.shape[1], q_ref.shape[2]
    qi = pl.program_id(1)
    t = k_ref.shape[1]
    nk = t // block_k

    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0].reshape(bq, 1)
    delta = delta_ref[0].reshape(bq, 1)
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        p = jnp.exp(s - lse)
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    upper = ((qi + 1) * bq + block_k - 1) // block_k if causal else nk
    dq = jax.lax.fori_loop(0, upper, body,
                           jnp.zeros((bq, dh), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, block_q: int, causal: bool, scale: float):
    bk, dh = k_ref.shape[1], k_ref.shape[2]
    ki = pl.program_id(1)
    t = q_ref.shape[1]
    nq = t // block_q

    k_blk = k_ref[0].astype(jnp.float32)
    v_blk = v_ref[0].astype(jnp.float32)
    k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q)].reshape(block_q, 1)
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q)].reshape(block_q, 1)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        p = jnp.exp(s - lse)
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dv = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    # Causal: Q blocks strictly above this K block see none of it.
    lower = (ki * bk) // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(
        lower, nq, body,
        (jnp.zeros((bk, dh), jnp.float32), jnp.zeros((bk, dh), jnp.float32)))
    # scale is already folded into q above, so dk = dsᵀ·(q·scale) is complete
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, o, lse, do, causal, block_q, block_k, interpret):
    bh, t, dh = q.shape
    scale = 1.0 / math.sqrt(dh)
    # delta_i = rowsum(do_i * o_i) — the softmax-jacobian correction term.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).reshape(bh, 1, t)
    lse3 = lse.reshape(bh, 1, t)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block_k, causal=causal,
                          scale=scale),
        grid=(bh, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, dh), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t, dh), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, dh), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dh), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, dh), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse3, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, causal=causal,
                          scale=scale),
        grid=(bh, t // block_k),
        in_specs=[
            pl.BlockSpec((1, t, dh), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, dh), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, dh), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, dh), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, dh), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, dh), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, dh), k.dtype),
            jax.ShapeDtypeStruct((bh, t, dh), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse3, delta)
    return dq, dk, dv


# ------------------------------------------------------------- public API


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    return _flash_bwd_impl(q, k, v, o, lse, do, causal, block_q, block_k,
                           interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Blocked flash attention. ``q, k, v``: ``(batch, time, heads, head_dim)``.

    ``time`` must be divisible by the block sizes (blocks are clamped to
    ``time`` when shorter). Differentiable (custom VJP); off-TPU the kernels
    run in Pallas interpret mode so tests work on the CPU simulator.

    Compiled (TPU) mode requires lane-aligned blocks: ``block_q``/``block_k``
    must be multiples of 128 (Mosaic tiling: the log-sum-exp blocks put
    ``block_q`` in the lane dimension). Interpret mode has no such limit.
    """
    if interpret is None:
        interpret = _interpret_default()
    b, t, h, dh = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"time {t} not divisible by blocks ({block_q},{block_k})")
    if not interpret and (block_q % 128 or block_k % 128):
        raise ValueError(
            f"compiled TPU mode needs block sizes that are multiples of 128 "
            f"(got block_q={block_q}, block_k={block_k}; time={t} — for "
            f"shorter sequences use dense attention or interpret=True)")

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, dh)

    o = _flash(to_bh(q), to_bh(k), to_bh(v), causal, block_q, block_k,
               interpret)
    return o.reshape(b, h, t, dh).transpose(0, 2, 1, 3)

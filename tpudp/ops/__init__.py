from tpudp.ops.flash_attention import flash_attention
from tpudp.ops.sampling import sample_tokens, split_keys

__all__ = ["flash_attention", "sample_tokens", "split_keys"]

"""Shared multi-head attention dispatch for the transformer models.

One home for the impl-selection rule (dense XLA einsums vs the owned Pallas
flash kernel vs sequence-parallel ring attention) and the mixed-precision
softmax policy, so GPT-2 and ViT can never drift apart on kernel
constraints (the 128-lane block alignment) or numerics.  The reference has
no attention at all (SURVEY.md §5 long-context entry); this layer is where
tpudp's sequence models meet the hot-op kernel.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def multihead_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool,
    impl: str = "dense",
    dtype=jnp.float32,
    seq_axis: str | None = None,
) -> jnp.ndarray:
    """``(B, T, H, Dh)`` q/k/v -> ``(B, T, H, Dh)`` attention output.

    ``impl``:
      * ``'dense'`` — XLA einsum chain, fp32 softmax, ``dtype`` matmuls.
      * ``'flash'`` — the Pallas kernel (tpudp.ops.flash_attention) when the
        token count meets its 128-lane block alignment; silently the dense
        path otherwise (identical math, same param-free contract).
      * ``'ring'`` — exact sequence-parallel ring attention over the bound
        mesh axis ``seq_axis`` (causal only); requires the caller to run
        under ``shard_map`` with that axis, and falls back to dense when the
        axis is unbound (e.g. the single-device init trace).
    """
    t = q.shape[1]
    if impl == "ring" and seq_axis is not None:
        from tpudp.mesh import axis_is_bound

        if axis_is_bound(seq_axis):
            from tpudp.parallel.ring_attention import ring_attention

            return ring_attention(q, k, v, axis_name=seq_axis, causal=causal)
    if impl == "flash" and t % 128 == 0:
        from tpudp.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)

    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    probs = nn.softmax(logits.astype(jnp.float32), axis=-1).astype(dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

"""Memory-efficient losses for large-vocabulary LM training.

The standard LM path materializes the full ``(batch*time, vocab)`` logits
tensor — for GPT-2-small at batch 8, seq 1024 that is ``8*1024*50257``
fp32 ≈ 1.6 GB live through the softmax backward, usually THE activation
peak of the whole model.  :func:`chunked_softmax_xent` computes the same
tied-head cross entropy over token chunks under ``lax.scan`` with a
rematerialized body, so peak logits memory is ``chunk_size * vocab``
regardless of batch/sequence — the standard chunked-vocab-loss technique,
enabling batch sizes the dense path OOMs on.

No reference analogue (the reference is CNN-only, SURVEY.md §5); this is
TPU-first machinery for the GPT-2 family's hot loss.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax


def chunked_softmax_xent(
    hidden: jnp.ndarray,
    embedding: jnp.ndarray,
    targets: jnp.ndarray,
    chunk_size: int = 1024,
) -> jnp.ndarray:
    """Sum of softmax cross entropies of the tied-embedding head, chunked.

    Args:
      hidden: ``(..., d_model)`` final hidden states (post final-LayerNorm).
      embedding: ``(vocab, d_model)`` tied embedding table (the LM head is
        ``h @ embedding.T``, matching ``tpudp.models.gpt2.lm_head``).
      targets: ``(...)`` integer labels, same leading shape as ``hidden``.
      chunk_size: tokens per chunk; peak logits memory is
        ``chunk_size * vocab`` (the last ragged chunk is padded and the pad
        positions masked out).

    Returns the SUM of per-token CE losses as fp32 (divide by the token
    count for the mean).  Differentiable wrt ``hidden`` and ``embedding``;
    each chunk's logits are rematerialized in the backward
    (``jax.checkpoint``), so the backward peak matches the forward's.
    """
    if chunk_size < 1:
        raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
    d = hidden.shape[-1]
    h = hidden.reshape(-1, d)
    t = targets.reshape(-1)
    n = h.shape[0]
    chunk_size = min(chunk_size, n)
    pad = (-n) % chunk_size
    if pad:
        h = jnp.concatenate([h, jnp.zeros((pad, d), h.dtype)])
        t = jnp.concatenate([t, jnp.zeros((pad,), t.dtype)])
    valid = jnp.arange(n + pad) < n
    k = (n + pad) // chunk_size

    @jax.checkpoint
    def one_chunk(emb, hc, tc, vc):
        logits = (hc @ emb.T).astype(jnp.float32)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, tc)
        return jnp.where(vc, ce, 0.0).sum()

    def body(total, xs):
        hc, tc, vc = xs
        return total + one_chunk(embedding, hc, tc, vc), None

    total, _ = lax.scan(
        body, jnp.zeros((), jnp.float32),
        (h.reshape(k, chunk_size, d), t.reshape(k, chunk_size),
         valid.reshape(k, chunk_size)))
    return total


def chunked_lm_metrics(
    hidden: jnp.ndarray,
    embedding: jnp.ndarray,
    targets: jnp.ndarray,
    weights: jnp.ndarray,
    chunk_size: int = 1024,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Chunked eval twin: weighted ``(loss_sum, correct, count)`` with the
    framework eval contract (tpudp.train.eval_metrics), never materializing
    the full logits.  ``weights`` is per-sample ``(batch,)``, broadcast over
    each sample's tokens exactly as the dense eval does."""
    if chunk_size < 1:
        raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
    b = hidden.shape[0]
    d = hidden.shape[-1]
    per_token_w = jnp.broadcast_to(
        weights.reshape((b,) + (1,) * (targets.ndim - 1)), targets.shape)
    h = hidden.reshape(-1, d)
    t = targets.reshape(-1)
    w = per_token_w.reshape(-1).astype(jnp.float32)
    n = h.shape[0]
    chunk_size = min(chunk_size, n)
    pad = (-n) % chunk_size
    if pad:
        h = jnp.concatenate([h, jnp.zeros((pad, d), h.dtype)])
        t = jnp.concatenate([t, jnp.zeros((pad,), t.dtype)])
        w = jnp.concatenate([w, jnp.zeros((pad,), w.dtype)])
    k = h.shape[0] // chunk_size

    def body(carry, xs):
        loss_sum, correct = carry
        hc, tc, wc = xs
        logits = (hc @ embedding.T).astype(jnp.float32)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, tc)
        hit = (jnp.argmax(logits, -1) == tc).astype(jnp.float32)
        return (loss_sum + (ce * wc).sum(), correct + (hit * wc).sum()), None

    (loss_sum, correct), _ = lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (h.reshape(k, chunk_size, d), t.reshape(k, chunk_size),
         w.reshape(k, chunk_size)))
    return loss_sum, correct, w.sum()

"""CIFAR-10 dataset access.

The reference pulls CIFAR-10 through ``torchvision.datasets.CIFAR10`` with
``download=True`` into ``./data`` (``src/Part 2a/main.py:36-37,48-49``).  This
module reads the same on-disk format (``cifar-10-batches-py`` pickle batches)
directly — no torchvision dependency — and, when the dataset is absent and the
environment has no egress, falls back to a deterministic *learnable* synthetic
stand-in with identical shapes/dtypes so every code path stays exercisable.

Synthetic data is class-conditional (each class has a fixed random template
plus noise), so models genuinely learn on it — loss decreases and accuracy
rises above chance — which is what the convergence-as-test strategy of the
reference needs (SURVEY.md §4).
"""

from __future__ import annotations

import os
import pickle
import tarfile
from typing import NamedTuple

import numpy as np

# Channel statistics used by the reference's Normalize transform:
# mean=[125.3, 123.0, 113.9]/255, std=[63.0, 62.1, 66.7]/255
# (src/Part 2a/main.py:24-25).
CIFAR10_MEAN = np.array([125.3, 123.0, 113.9], dtype=np.float32) / 255.0
CIFAR10_STD = np.array([63.0, 62.1, 66.7], dtype=np.float32) / 255.0

_TRAIN_BATCHES = [f"data_batch_{i}" for i in range(1, 6)]
_TEST_BATCHES = ["test_batch"]


class Dataset(NamedTuple):
    images: np.ndarray  # (N, 32, 32, 3) uint8, NHWC
    labels: np.ndarray  # (N,) int32


def _read_pickle_batches(batch_dir: str, names: list[str]) -> Dataset:
    images, labels = [], []
    for name in names:
        with open(os.path.join(batch_dir, name), "rb") as f:
            entry = pickle.load(f, encoding="latin1")
        data = np.asarray(entry["data"], dtype=np.uint8)
        images.append(data.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))  # -> NHWC
        labels.append(np.asarray(entry.get("labels", entry.get("fine_labels")),
                                 dtype=np.int32))
    return Dataset(np.concatenate(images), np.concatenate(labels))


CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
CIFAR10_TGZ_MD5 = "c58f30108f718f92721af3b95e74349a"

# One failed attempt per process: zero-egress hosts (this build image) must
# not pay the connect timeout on every load_cifar10 call.
_DOWNLOAD_FAILED = False


def _download(root: str, timeout: float = 30.0) -> str | None:
    """Fetch the CIFAR-10 tarball into ``root`` — the ``download=True``
    analogue of the reference (``src/Part 2a/main.py:36-37``).  Verifies the
    torchvision-published md5 before accepting; returns the tarball path or
    None on any network failure (zero-egress environments fall through to
    the synthetic stand-in silently)."""
    import hashlib
    import urllib.error
    import urllib.request

    global _DOWNLOAD_FAILED
    if _DOWNLOAD_FAILED or os.environ.get("TPUDP_NO_DOWNLOAD"):
        return None
    os.makedirs(root, exist_ok=True)
    tgz = os.path.join(root, "cifar-10-python.tar.gz")
    tmp = tgz + ".part"
    try:
        with urllib.request.urlopen(CIFAR10_URL, timeout=timeout) as resp, \
                open(tmp, "wb") as out:
            md5 = hashlib.md5()
            while chunk := resp.read(1 << 20):
                out.write(chunk)
                md5.update(chunk)
    except (urllib.error.URLError, OSError, TimeoutError):
        _DOWNLOAD_FAILED = True
        if os.path.isfile(tmp):
            os.remove(tmp)
        return None
    # Verify OUTSIDE the network-failure catch: a corrupted tarball must be
    # loud, not silently replaced by synthetic data.
    if md5.hexdigest() != CIFAR10_TGZ_MD5:
        os.remove(tmp)
        _DOWNLOAD_FAILED = True
        import warnings

        warnings.warn(
            "CIFAR-10 download failed md5 verification (corrupted or "
            "proxy-mangled tarball); falling back as if offline",
            stacklevel=2)
        return None
    os.replace(tmp, tgz)
    return tgz


def _maybe_extract(root: str, download: bool = False) -> str | None:
    batch_dir = os.path.join(root, "cifar-10-batches-py")
    if os.path.isdir(batch_dir):
        return batch_dir
    tgz = os.path.join(root, "cifar-10-python.tar.gz")
    if not os.path.isfile(tgz) and download:
        _download(root)
    if os.path.isfile(tgz):
        with tarfile.open(tgz, "r:gz") as tar:
            tar.extractall(root)
        if os.path.isdir(batch_dir):
            return batch_dir
    return None


def _synthetic(n: int, seed: int, num_classes: int = 10) -> Dataset:
    """Deterministic class-conditional images: template[label] + noise."""
    rng = np.random.default_rng(seed)
    templates = rng.integers(0, 256, size=(num_classes, 32, 32, 3))
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    noise = rng.normal(0.0, 48.0, size=(n, 32, 32, 3))
    images = np.clip(templates[labels] + noise, 0, 255).astype(np.uint8)
    return Dataset(images, labels)


def load_cifar10(
    root: str = "./data",
    *,
    download: bool = True,
    synthetic_fallback: bool = True,
    synthetic_train_size: int = 50_000,
    synthetic_test_size: int = 10_000,
) -> tuple[Dataset, Dataset, bool]:
    """Return ``(train, test, is_synthetic)``.

    Real data is used when ``root/cifar-10-batches-py`` (or the tarball)
    exists; with ``download=True`` (the reference's default behavior) a
    missing dataset is fetched + md5-verified first.  Network failure is
    silent — offline hosts fall back to a deterministic synthetic stand-in
    of the same shape (or raise if ``synthetic_fallback=False``).
    """
    batch_dir = _maybe_extract(root, download=download)
    if batch_dir is not None:
        return (
            _read_pickle_batches(batch_dir, _TRAIN_BATCHES),
            _read_pickle_batches(batch_dir, _TEST_BATCHES),
            False,
        )
    if not synthetic_fallback:
        raise FileNotFoundError(
            f"CIFAR-10 not found under {root!r} and synthetic_fallback=False"
        )
    # Train/test are disjoint noise draws over identical class templates
    # (same template stream, different label/noise stream).
    train = _synthetic(synthetic_train_size, seed=1234)
    rng = np.random.default_rng(1234)
    templates = rng.integers(0, 256, size=(10, 32, 32, 3))
    trng = np.random.default_rng(5678)
    labels = trng.integers(0, 10, size=synthetic_test_size).astype(np.int32)
    noise = trng.normal(0.0, 48.0, size=(synthetic_test_size, 32, 32, 3))
    test = Dataset(
        np.clip(templates[labels] + noise, 0, 255).astype(np.uint8), labels
    )
    return train, test, True

"""Host-side data pipeline: CIFAR-10 (real or synthetic), reference-exact
augmentation, and per-shard sampling (DistributedSampler equivalent)."""

from tpudp.data.cifar10 import load_cifar10, CIFAR10_MEAN, CIFAR10_STD  # noqa: F401
from tpudp.data.sampler import ShardedSampler  # noqa: F401
from tpudp.data.loader import DataLoader, augment_batch, normalize_batch  # noqa: F401
from tpudp.data.prefetch import Prefetcher  # noqa: F401

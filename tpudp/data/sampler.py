"""Deterministic shard-aware sampling — the reference's DistributedSampler.

The reference shards the train set with
``DistributedSampler(dataset, num_replicas=size, rank=rank)``
(``src/Part 2a/main.py:38``): shuffle all indices with a seeded generator,
pad to a multiple of world size, then take the strided slice
``indices[rank::num_replicas]``.  This module reproduces those semantics in
numpy for *host*-level sharding (each host loads only its slice; device-level
splitting happens via the batch sharding in ``tpudp.mesh``).

Quirk fixed (SURVEY.md §7 quirks catalog): the reference never calls
``set_epoch`` so every epoch reuses the same shuffle
(``src/Part 2a/main.py:38,64-68``); here the epoch is mixed into the shuffle
seed by default.  Pass ``reshuffle_each_epoch=False`` for bug-compatible
behavior.
"""

from __future__ import annotations

import numpy as np


class ShardedSampler:
    """``batch_contiguous`` (a GLOBAL batch size, or None) switches the
    shard layout from DistributedSampler's strided slice
    (``indices[rank::num_shards]``) to per-batch CONTIGUOUS slices: shard
    ``k`` of ``H`` takes rows ``[k*B/H, (k+1)*B/H)`` of every global
    batch drawn from the canonical order.  The strided layout PERMUTES
    rows within each assembled global batch as the host count changes
    (host 0 of 2 holds rows 0,2,4,... — at 1 host they are 0,1,2,...),
    so a trajectory is only reproducible at the exact save-time host
    geometry; the contiguous layout makes the assembled global batch a
    pure function of ``(seed, epoch)``, independent of how many hosts
    contribute — the property elastic restore (a 2-host run resumed at
    1 host, docs/RESILIENCE.md) needs for bit-exact replay.  Requires
    the padded total size to divide into whole global batches and the
    batch to split evenly across shards."""

    def __init__(
        self,
        dataset_size: int,
        num_shards: int = 1,
        shard_index: int = 0,
        *,
        shuffle: bool = True,
        seed: int = 0,
        reshuffle_each_epoch: bool = True,
        batch_contiguous: int | None = None,
    ):
        if not 0 <= shard_index < num_shards:
            raise ValueError(f"shard_index {shard_index} out of range [0, {num_shards})")
        self.dataset_size = dataset_size
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.shuffle = shuffle
        self.seed = seed
        self.reshuffle_each_epoch = reshuffle_each_epoch
        # Padded length: every shard sees the same number of samples
        # (DistributedSampler pads by wrapping around).
        self.num_samples = -(-dataset_size // num_shards)  # ceil
        self.total_size = self.num_samples * num_shards
        self.batch_contiguous = batch_contiguous
        if batch_contiguous is not None:
            if batch_contiguous % num_shards:
                raise ValueError(
                    f"batch_contiguous={batch_contiguous} must split evenly "
                    f"across {num_shards} shards")
            if self.total_size % batch_contiguous:
                raise ValueError(
                    f"padded dataset size {self.total_size} is not a whole "
                    f"number of global batches of {batch_contiguous} — the "
                    "contiguous layout has no canonical final batch")

    def indices(self, epoch: int = 0) -> np.ndarray:
        return self.indices_and_mask(epoch)[0]

    def indices_and_mask(self, epoch: int = 0) -> tuple[np.ndarray, np.ndarray]:
        """Returns (indices, valid): ``valid`` is False for the wrap-around
        padding entries.  Training follows DistributedSampler and treats
        padded duplicates as real samples; *evaluation* must weight them 0,
        or samples wrapped onto a second shard get counted twice in the
        psum-ed metrics."""
        if self.shuffle:
            shuffle_seed = self.seed + (epoch if self.reshuffle_each_epoch else 0)
            order = np.random.default_rng(shuffle_seed).permutation(self.dataset_size)
        else:
            order = np.arange(self.dataset_size)
        valid = np.ones(self.total_size, dtype=bool)
        if self.total_size > self.dataset_size:  # wrap-around padding
            pad = self.total_size - self.dataset_size
            order = np.concatenate([order, order[:pad]])
            valid[self.dataset_size :] = False
        if self.batch_contiguous is not None:
            # Geometry-invariant layout: rows [k*B/H, (k+1)*B/H) of every
            # global batch in canonical order (see class docstring).
            per = self.batch_contiguous // self.num_shards
            lo = self.shard_index * per
            order = order.reshape(-1, self.batch_contiguous)
            valid = valid.reshape(-1, self.batch_contiguous)
            return (order[:, lo:lo + per].reshape(-1),
                    valid[:, lo:lo + per].reshape(-1))
        sel = slice(self.shard_index, None, self.num_shards)
        return order[sel], valid[sel]

    def __len__(self) -> int:
        return self.num_samples

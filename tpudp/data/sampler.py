"""Deterministic shard-aware sampling — the reference's DistributedSampler.

The reference shards the train set with
``DistributedSampler(dataset, num_replicas=size, rank=rank)``
(``src/Part 2a/main.py:38``): shuffle all indices with a seeded generator,
pad to a multiple of world size, then take the strided slice
``indices[rank::num_replicas]``.  This module reproduces those semantics in
numpy for *host*-level sharding (each host loads only its slice; device-level
splitting happens via the batch sharding in ``tpudp.mesh``).

Quirk fixed (SURVEY.md §7 quirks catalog): the reference never calls
``set_epoch`` so every epoch reuses the same shuffle
(``src/Part 2a/main.py:38,64-68``); here the epoch is mixed into the shuffle
seed by default.  Pass ``reshuffle_each_epoch=False`` for bug-compatible
behavior.
"""

from __future__ import annotations

import numpy as np


class ShardedSampler:
    def __init__(
        self,
        dataset_size: int,
        num_shards: int = 1,
        shard_index: int = 0,
        *,
        shuffle: bool = True,
        seed: int = 0,
        reshuffle_each_epoch: bool = True,
    ):
        if not 0 <= shard_index < num_shards:
            raise ValueError(f"shard_index {shard_index} out of range [0, {num_shards})")
        self.dataset_size = dataset_size
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.shuffle = shuffle
        self.seed = seed
        self.reshuffle_each_epoch = reshuffle_each_epoch
        # Padded length: every shard sees the same number of samples
        # (DistributedSampler pads by wrapping around).
        self.num_samples = -(-dataset_size // num_shards)  # ceil
        self.total_size = self.num_samples * num_shards

    def indices(self, epoch: int = 0) -> np.ndarray:
        return self.indices_and_mask(epoch)[0]

    def indices_and_mask(self, epoch: int = 0) -> tuple[np.ndarray, np.ndarray]:
        """Returns (indices, valid): ``valid`` is False for the wrap-around
        padding entries.  Training follows DistributedSampler and treats
        padded duplicates as real samples; *evaluation* must weight them 0,
        or samples wrapped onto a second shard get counted twice in the
        psum-ed metrics."""
        if self.shuffle:
            shuffle_seed = self.seed + (epoch if self.reshuffle_each_epoch else 0)
            order = np.random.default_rng(shuffle_seed).permutation(self.dataset_size)
        else:
            order = np.arange(self.dataset_size)
        valid = np.ones(self.total_size, dtype=bool)
        if self.total_size > self.dataset_size:  # wrap-around padding
            pad = self.total_size - self.dataset_size
            order = np.concatenate([order, order[:pad]])
            valid[self.dataset_size :] = False
        sel = slice(self.shard_index, None, self.num_shards)
        return order[sel], valid[sel]

    def __len__(self) -> int:
        return self.num_samples

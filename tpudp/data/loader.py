"""Batched host loader with reference-exact augmentation, vectorized in numpy.

Replaces the reference's torchvision transform stack + DataLoader
(``src/Part 2a/main.py:24-44``):

  train: RandomCrop(32, padding=4) -> RandomHorizontalFlip -> ToTensor ->
         Normalize(CIFAR10_MEAN, CIFAR10_STD)          (src/Part 2a/main.py:26-31)
  test:  ToTensor -> Normalize                          (src/Part 2a/main.py:33-35)

Differences by design (TPU-first):
  * NHWC float32 output (XLA:TPU conv layout) instead of NCHW tensors.
  * Whole-batch vectorized ops instead of per-sample Python transforms and
    worker processes: the fused native C++/OpenMP kernel (tpudp/native/)
    when available, else bit-identical vectorized numpy.  Random crop/flip
    decisions are drawn here in Python from one RNG stream, so backend
    choice never changes the data.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from tpudp import native
from tpudp.data.cifar10 import CIFAR10_MEAN, CIFAR10_STD, Dataset
from tpudp.data.sampler import ShardedSampler


def normalize_batch(images_u8: np.ndarray, mean: np.ndarray = CIFAR10_MEAN,
                    std: np.ndarray = CIFAR10_STD) -> np.ndarray:
    """uint8 (B,H,W,3) -> normalized float32, the ToTensor+Normalize pair."""
    x = images_u8.astype(np.float32) / 255.0
    return (x - mean) / std


def draw_augment_params(
    b: int, rng: np.random.Generator, *, crop_range: int = 9
) -> tuple[np.ndarray, np.ndarray]:
    """Draw (offsets (B,2) int32, flips (B,) bool) — the per-sample random
    decisions of RandomCrop + RandomHorizontalFlip, shared by both backends.
    ``crop_range`` = H_in + 2*pad - H_out + 1 (9 for CIFAR's 32+8-32+1)."""
    offsets = rng.integers(0, crop_range, size=(b, 2)).astype(np.int32)
    flips = rng.random(b) < 0.5
    return offsets, flips


def apply_crop_flip(
    images_u8: np.ndarray, offsets: np.ndarray, flips: np.ndarray, *, pad: int = 4
) -> np.ndarray:
    """numpy backend: zero-pad + crop(H,W) at ``offsets`` + flip where set."""
    b, h, w, _ = images_u8.shape
    padded = np.pad(images_u8, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    rows = offsets[:, 0, None] + np.arange(h)  # (B, H)
    cols = offsets[:, 1, None] + np.arange(w)
    out = padded[np.arange(b)[:, None, None], rows[:, :, None], cols[:, None, :]]
    out[flips] = out[flips, :, ::-1]
    return out


def augment_batch(images_u8: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    """RandomCrop(32, padding=4, zero fill) + RandomHorizontalFlip, batched."""
    offsets, flips = draw_augment_params(images_u8.shape[0], rng)
    return apply_crop_flip(images_u8, offsets, flips)


class DataLoader:
    """Iterates normalized (images, labels) numpy batches over a shard.

    ``batch_size`` here is the *host-local* batch (the reference computes
    per-rank batch = global / world_size at ``src/Part 2a/main.py:22``).
    ``drop_last=True`` mirrors the torch DataLoader default used with fixed
    batch shapes — jit-compiled steps want static shapes, so ragged final
    batches are dropped in training and padded (with a weight mask) in eval.
    """

    def __init__(
        self,
        dataset: Dataset,
        batch_size: int,
        *,
        sampler: ShardedSampler | None = None,
        train: bool = True,
        seed: int = 0,
        drop_last: bool | None = None,
        backend: str = "auto",
        mean: np.ndarray | None = None,
        std: np.ndarray | None = None,
        pad: int = 4,
    ):
        """``mean``/``std``/``pad`` default to the reference's CIFAR-10
        constants (``src/Part 2a/main.py:24-31``); pass ImageNet values for
        224-geometry datasets — the augmentation pipeline is size-agnostic."""
        self.dataset = dataset
        self.batch_size = batch_size
        self.mean = np.asarray(CIFAR10_MEAN if mean is None else mean,
                               np.float32)
        self.std = np.asarray(CIFAR10_STD if std is None else std, np.float32)
        self.pad = pad
        self.sampler = sampler or ShardedSampler(
            len(dataset.images), shuffle=train, seed=seed
        )
        self.train = train
        self.seed = seed
        self.drop_last = train if drop_last is None else drop_last
        self.epoch = 0
        if backend == "auto":
            backend = "native" if native.available() else "numpy"
        elif backend == "native" and not native.available():
            raise RuntimeError("native backend requested but the C++ library "
                               "failed to build/load (see tpudp/native)")
        elif backend not in ("native", "numpy"):
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Yields (images f32 NHWC, labels i32, weights f32).

        ``weights`` is 1 for real samples, 0 for padding in a ragged final
        eval batch — metrics are weight-summed so padding never counts.
        """
        idx, valid = self.sampler.indices_and_mask(self.epoch)
        aug_rng = np.random.default_rng((self.seed, self.epoch, self.sampler.shard_index))
        use_native = self.backend == "native"
        n_batches = len(self)
        for b in range(n_batches):
            sel = idx[b * self.batch_size : (b + 1) * self.batch_size]
            if use_native:
                images = native.gather(self.dataset.images, sel)
            else:
                images = self.dataset.images[sel]
            labels = self.dataset.labels[sel]
            if self.train:  # DistributedSampler semantics: duplicates count
                weights = np.ones(len(sel), dtype=np.float32)
            else:  # eval: wrap-padded duplicates must not be double-counted
                weights = valid[b * self.batch_size : (b + 1) * self.batch_size
                                ].astype(np.float32)
            if len(sel) < self.batch_size:  # pad ragged eval batch
                pad = self.batch_size - len(sel)
                images = np.concatenate([images, np.zeros((pad, *images.shape[1:]), images.dtype)])
                labels = np.concatenate([labels, np.zeros(pad, labels.dtype)])
                weights = np.concatenate([weights, np.zeros(pad, np.float32)])
            if self.train:
                offsets, flips = draw_augment_params(
                    len(images), aug_rng, crop_range=2 * self.pad + 1)
                if use_native:
                    images = native.augment_normalize(
                        images, offsets, flips, self.mean, self.std,
                        pad=self.pad)
                else:
                    images = normalize_batch(
                        apply_crop_flip(images, offsets, flips, pad=self.pad),
                        self.mean, self.std)
            elif use_native:
                images = native.normalize(images, self.mean, self.std)
            else:
                images = normalize_batch(images, self.mean, self.std)
            yield images, labels.astype(np.int32), weights

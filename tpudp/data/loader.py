"""Batched host loader with reference-exact augmentation, vectorized in numpy.

Replaces the reference's torchvision transform stack + DataLoader
(``src/Part 2a/main.py:24-44``):

  train: RandomCrop(32, padding=4) -> RandomHorizontalFlip -> ToTensor ->
         Normalize(CIFAR10_MEAN, CIFAR10_STD)          (src/Part 2a/main.py:26-31)
  test:  ToTensor -> Normalize                          (src/Part 2a/main.py:33-35)

Differences by design (TPU-first):
  * NHWC float32 output (XLA:TPU conv layout) instead of NCHW tensors.
  * Whole-batch vectorized numpy ops instead of per-sample Python transforms
    and worker processes — the 32x32 pipeline is far from being the
    bottleneck at TPU step times, so no separate loader processes are needed
    (a native C++ loader is still available for the large-image path).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from tpudp.data.cifar10 import CIFAR10_MEAN, CIFAR10_STD, Dataset
from tpudp.data.sampler import ShardedSampler


def normalize_batch(images_u8: np.ndarray) -> np.ndarray:
    """uint8 (B,32,32,3) -> normalized float32, the ToTensor+Normalize pair."""
    x = images_u8.astype(np.float32) / 255.0
    return (x - CIFAR10_MEAN) / CIFAR10_STD


def augment_batch(images_u8: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    """RandomCrop(32, padding=4, zero fill) + RandomHorizontalFlip, batched."""
    b = images_u8.shape[0]
    padded = np.pad(images_u8, ((0, 0), (4, 4), (4, 4), (0, 0)))
    offs = rng.integers(0, 9, size=(b, 2))
    rows = offs[:, 0, None] + np.arange(32)  # (B, 32)
    cols = offs[:, 1, None] + np.arange(32)
    out = padded[np.arange(b)[:, None, None], rows[:, :, None], cols[:, None, :]]
    flip = rng.random(b) < 0.5
    out[flip] = out[flip, :, ::-1]
    return out


class DataLoader:
    """Iterates normalized (images, labels) numpy batches over a shard.

    ``batch_size`` here is the *host-local* batch (the reference computes
    per-rank batch = global / world_size at ``src/Part 2a/main.py:22``).
    ``drop_last=True`` mirrors the torch DataLoader default used with fixed
    batch shapes — jit-compiled steps want static shapes, so ragged final
    batches are dropped in training and padded (with a weight mask) in eval.
    """

    def __init__(
        self,
        dataset: Dataset,
        batch_size: int,
        *,
        sampler: ShardedSampler | None = None,
        train: bool = True,
        seed: int = 0,
        drop_last: bool | None = None,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = sampler or ShardedSampler(
            len(dataset.images), shuffle=train, seed=seed
        )
        self.train = train
        self.seed = seed
        self.drop_last = train if drop_last is None else drop_last
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Yields (images f32 NHWC, labels i32, weights f32).

        ``weights`` is 1 for real samples, 0 for padding in a ragged final
        eval batch — metrics are weight-summed so padding never counts.
        """
        idx, valid = self.sampler.indices_and_mask(self.epoch)
        aug_rng = np.random.default_rng((self.seed, self.epoch, self.sampler.shard_index))
        n_batches = len(self)
        for b in range(n_batches):
            sel = idx[b * self.batch_size : (b + 1) * self.batch_size]
            images = self.dataset.images[sel]
            labels = self.dataset.labels[sel]
            if self.train:  # DistributedSampler semantics: duplicates count
                weights = np.ones(len(sel), dtype=np.float32)
            else:  # eval: wrap-padded duplicates must not be double-counted
                weights = valid[b * self.batch_size : (b + 1) * self.batch_size
                                ].astype(np.float32)
            if len(sel) < self.batch_size:  # pad ragged eval batch
                pad = self.batch_size - len(sel)
                images = np.concatenate([images, np.zeros((pad, *images.shape[1:]), images.dtype)])
                labels = np.concatenate([labels, np.zeros(pad, labels.dtype)])
                weights = np.concatenate([weights, np.zeros(pad, np.float32)])
            if self.train:
                images = augment_batch(images, aug_rng)
            yield normalize_batch(images), labels.astype(np.int32), weights

"""Background-thread batch prefetching.

The reference overlaps host-side data work with compute via torch DataLoader
worker processes (``num_workers=2``, ``src/Part 2a/main.py:39-44``).  Under
JAX async dispatch the device is already busy while Python prepares the next
batch, but the *host* augmentation (gather + crop/flip + normalize) still
runs serially with step dispatch; a single daemon thread with a small queue
hides it entirely.  Threads suffice (no worker processes): the heavy lifting
is numpy/native C++ code that releases the GIL.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator


class Prefetcher:
    """Wraps any loader (iterable of batches, with ``set_epoch``/``__len__``)
    and prepares up to ``depth`` batches ahead on a daemon thread.  Batch
    order and content are identical to the wrapped loader's.

    ``place`` (or :meth:`set_place`, which the Trainer calls with its
    input-sharding device_put) additionally runs on the worker thread, so
    host→device transfers START ``depth`` batches ahead of consumption
    instead of at step-dispatch time — device-side prefetch.  Matters most
    when the H2D link is slow relative to the step (the axon relay: ~3 MB
    of CIFAR batch per step over a tunnel); JAX dispatch is thread-safe and
    transfers overlap compute."""

    _DONE = object()

    def __init__(self, loader, depth: int = 2, place=None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.loader = loader
        self.depth = depth
        self.place = place
        self._lock = threading.Lock()
        self._live: list[tuple[threading.Event, threading.Thread]] = []

    def close(self, timeout: float = 5.0) -> None:
        """Stop every live worker thread and wait for it to exit.

        Abandoning iteration mid-epoch normally stops the worker via the
        generator's ``finally`` (GC-driven), but a consumer that merely
        drops the iterator without closing it — a supervisor restarting
        the pipeline, a relaunched soak worker — must be able to
        GUARANTEE no ``tpudp-prefetch`` thread survives and no ``put`` is
        left blocked.  Idempotent; the Prefetcher remains iterable after
        close (a new ``__iter__`` spawns a fresh worker)."""
        with self._lock:
            live = list(self._live)
        for stop, _t in live:
            stop.set()
        for _stop, t in live:
            t.join(timeout)
        with self._lock:
            self._live = [e for e in self._live if e[1].is_alive()]

    def set_place(self, fn) -> None:
        """Install/replace the batch-placement hook (applies to batches
        queued after this call; the Trainer installs it before iterating)."""
        self.place = fn

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.loader)

    def __iter__(self) -> Iterator:
        q: queue.Queue = queue.Queue(maxsize=self.depth)
        stop = threading.Event()

        def put(item) -> bool:
            """Bounded put that aborts when the consumer is gone."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker() -> None:
            try:
                for batch in self.loader:
                    if self.place is not None:
                        batch = self.place(batch)
                    if not put(batch):
                        return
                put(self._DONE)
            except BaseException as e:  # re-raise on the consumer side
                put(e)

        t = threading.Thread(target=worker, daemon=True, name="tpudp-prefetch")
        with self._lock:
            self._live.append((stop, t))
        t.start()
        try:
            while True:
                item = q.get()
                if item is self._DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            with self._lock:
                self._live = [e for e in self._live if e[0] is not stop]

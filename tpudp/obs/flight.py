"""Fault-triggered flight recorder: the last N spans/events/counters,
persisted the moment something goes wrong.

The runtime's failure paths all share one shape: a detector fires
(watchdog timeout, step-failure containment, NaN/spike rollback, vote
timeout) and the process either recovers or dies — and before this
module, either way the timeline that LED there was gone.  A
:class:`FlightRecorder` wraps a :class:`tpudp.obs.Recorder` and, on
demand, dumps its ring plus context to a per-host
``flightrec-<host>-<seq>-<reason>.json`` under a configured directory —
the black box the resilience soak and serve watchdog kills can be
debugged from.

Activation is by DIRECTORY: ``directory=None`` resolves through the
``TPUDP_FLIGHT_DIR`` environment variable, and when neither is set
every ``dump()`` is a no-op — so the recorder can be wired
unconditionally through the engine/trainer/watchdog without any
default-path behavior change.

Multi-host: each host dumps LOCALLY (a dump must never require a dead
peer), and :func:`coordinated_merge` — called only from points every
live host reaches together, e.g. after a coordinated recovery — has
rank 0 merge the per-host files into one ``flightrec-merged.json``
after a ``gather_host_values`` round confirms how many dumps each host
banked.  The gather rides the existing checkpoint-protocol seam and
sits outside every hot path.
"""

from __future__ import annotations

import json
import os
import time

from tpudp.obs.record import Recorder

#: Environment default for the dump directory (CLI flags/constructor
#: arguments override).  Unset + no explicit directory = dumps disabled.
FLIGHT_DIR_ENV = "TPUDP_FLIGHT_DIR"


def resolve_flight_dir(directory: str | None) -> str | None:
    """Explicit directory, else the ``TPUDP_FLIGHT_DIR`` env default,
    else None (dumping disabled)."""
    if directory:
        return directory
    return os.environ.get(FLIGHT_DIR_ENV) or None


def _host_index() -> int:
    """This process's host index without forcing a jax backend: jax is
    consulted only if it is already imported and initialized (the dump
    path may run while the device is wedged — it must never trigger
    distributed init itself)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:
            pass
    return 0


class FlightRecorder:
    """Dumps a :class:`Recorder`'s ring to per-host JSON files.

    One instance per engine/trainer; ``dump()`` is safe from any thread
    (the watchdog's monitor thread calls it right before killing the
    process) and never raises — a broken disk must not mask the fault
    being recorded.
    """

    def __init__(self, recorder: Recorder, directory: str | None = None,
                 component: str = ""):
        self.recorder = recorder
        self.directory = resolve_flight_dir(directory)
        self.component = component or recorder.name or "tpudp"
        self._dumped = 0

    @property
    def enabled(self) -> bool:
        return self.directory is not None

    @property
    def dumps(self) -> int:
        """Dumps successfully written by THIS instance."""
        return self._dumped

    def dump(self, reason: str, extra: dict | None = None) -> str | None:
        """Persist the black box: ring snapshot + counters + context.
        Returns the written path, or None when disabled or the write
        failed (best-effort by contract)."""
        if self.directory is None:
            return None
        try:
            host = _host_index()
            rec = self.recorder
            payload = {
                "kind": "tpudp_flight_record",
                "component": self.component,
                "reason": reason,
                "host": host,
                "seq": self._dumped,
                "wall_time": time.time(),
                "anchor_wall": rec.anchor_wall,
                "counters": dict(rec.counters),
                "last_span": rec.last_span(),
                "spans": rec.snapshot(),
            }
            if extra:
                payload["extra"] = extra
            os.makedirs(self.directory, exist_ok=True)
            safe = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in reason)[:48]
            path = os.path.join(
                self.directory,
                f"flightrec-{self.component}-h{host}-"
                f"{self._dumped:03d}-{safe}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=1, sort_keys=True,
                          default=str)
                f.write("\n")
            os.replace(tmp, path)
            self._dumped += 1
            return path
        except Exception:
            return None  # best-effort: never mask the fault being recorded


def list_dumps(directory: str) -> list[str]:
    """Sorted flight-record files under ``directory`` (sorted so every
    host walks the same order — the merge below is a coordination-
    adjacent path)."""
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return []
    return [os.path.join(directory, n) for n in names
            if n.startswith("flightrec-") and n.endswith(".json")
            and "merged" not in n]


def merge_dumps(directory: str) -> str | None:
    """Merge every per-host flight record under ``directory`` into
    ``flightrec-merged.json`` (records sorted by host then sequence).
    Pure file I/O — callable post-mortem on a dead pod's shared dir."""
    paths = list_dumps(directory)
    if not paths:
        return None
    records = []
    for p in paths:
        try:
            with open(p, encoding="utf-8") as f:
                records.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            records.append({"kind": "tpudp_flight_record",
                            "error": f"unreadable dump {p}"})
    records.sort(key=lambda r: (r.get("host", 0), r.get("seq", 0)))
    out = os.path.join(directory, "flightrec-merged.json")
    tmp = out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"kind": "tpudp_flight_record_merged",
                   "merged": len(records), "records": records}, f,
                  indent=1, sort_keys=True, default=str)
        f.write("\n")
    os.replace(tmp, out)
    return out


def coordinated_merge(directory: str | None) -> str | None:
    """Rank 0 merges the per-host dumps, after a ``gather_host_values``
    round confirms every live host's dump count (the existing
    cross-host seam from the checkpoint protocol — every host must call
    this together, from a point all of them reach, e.g. after a
    coordinated recovery; NEVER from a path where a peer may be dead).
    Single-process: plain local merge.  Returns rank 0's merged path
    (None elsewhere / when disabled)."""
    directory = resolve_flight_dir(directory)
    if directory is None:
        return None
    import jax

    if jax.process_count() > 1:
        from tpudp.utils.checkpoint import gather_host_values

        gather_host_values(len(list_dumps(directory)))
    if jax.process_index() == 0:
        return merge_dumps(directory)
    return None

"""Exporters: Chrome/Perfetto ``trace_event`` JSON and plain snapshots.

A recorder ring is only useful if something can read it.  Two formats:

  * :func:`to_chrome_trace` — the Chrome ``trace_event`` JSON format
    (the ``traceEvents`` array), loadable by Perfetto
    (https://ui.perfetto.dev) and ``chrome://tracing``.  Spans become
    complete ("X") events with microsecond timestamps, point events
    become instant ("i") events, counters become one counter ("C")
    sample.  ``pid`` is the host index, ``tid`` the component name —
    multi-host merges lay out one track per host.
  * :func:`spans_from_chrome_trace` — the inverse mapping back to
    recorder-snapshot dicts; :func:`to_chrome_trace` ∘
    :func:`spans_from_chrome_trace` is the identity on (name, kind,
    t0, dur, fields), which the schema round-trip test pins so the
    export can never drift from what Perfetto parses.
  * :func:`snapshot_json` — the raw ring + counters as one JSON
    document (the flight recorder's payload shape, reusable for ad-hoc
    ``Engine.metrics()``-style dumps).
"""

from __future__ import annotations

import json

from tpudp.obs.record import Recorder

_US = 1e6


def to_chrome_trace(recorder: Recorder, *, pid: int = 0,
                    tid: str | None = None) -> dict:
    """Recorder ring → Chrome ``trace_event`` JSON object."""
    tid = tid if tid is not None else (recorder.name or "tpudp")
    events = []
    for rec in recorder.snapshot():
        ts = rec["t0"] * _US
        base = {"name": rec["name"], "pid": pid, "tid": tid,
                "cat": "tpudp"}
        if rec.get("fields"):
            base["args"] = rec["fields"]
        if rec["kind"] == "span":
            dur = rec.get("dur")
            events.append({**base, "ph": "X", "ts": ts,
                           "dur": (dur if dur is not None else 0.0) * _US,
                           **({"args": {**base.get("args", {}),
                                        "open": True}}
                              if dur is None else {})})
        else:
            events.append({**base, "ph": "i", "ts": ts, "s": "t"})
    for name, value in sorted(recorder.counters.items()):
        events.append({"name": name, "ph": "C", "pid": pid, "tid": tid,
                       "cat": "tpudp", "ts": 0.0,
                       "args": {"value": value}})
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "component": recorder.name,
            "anchor_wall": recorder.anchor_wall,
        },
    }


def spans_from_chrome_trace(trace: dict) -> list[dict]:
    """Chrome trace object → recorder-snapshot-shaped dicts (the
    round-trip inverse; counter samples are skipped — they come back
    through the counters dict, not the ring)."""
    out = []
    for ev in trace.get("traceEvents", []):
        ph = ev.get("ph")
        if ph == "X":
            args = dict(ev.get("args") or {})
            open_span = bool(args.pop("open", False))
            rec = {"kind": "span", "name": ev["name"],
                   "t0": ev["ts"] / _US,
                   "dur": None if open_span else ev.get("dur", 0.0) / _US}
            if args:
                rec["fields"] = args
            out.append(rec)
        elif ph == "i":
            rec = {"kind": "event", "name": ev["name"],
                   "t0": ev["ts"] / _US}
            if ev.get("args"):
                rec["fields"] = dict(ev["args"])
            out.append(rec)
    return out


def counters_from_chrome_trace(trace: dict) -> dict:
    """Counter ("C") samples of a :func:`to_chrome_trace` export."""
    out = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") == "C":
            out[ev["name"]] = ev.get("args", {}).get("value")
    return out


def snapshot_json(recorder: Recorder, **extra) -> str:
    """The ring + counters as one pretty-printed JSON document."""
    return json.dumps(
        {"component": recorder.name, "anchor_wall": recorder.anchor_wall,
         "counters": dict(recorder.counters),
         "spans": recorder.snapshot(), **extra},
        indent=1, sort_keys=True, default=str)

"""Dispatch-honest timing helpers (the ``tpudp.obs`` home of the old
``tpudp/utils/timing.py`` — that module now re-exports from here so
existing imports keep working).

The reference brackets ``time.time()`` around eager torch calls
(``src/Part 2a/main.py:87-98``).  Under JAX async dispatch a naive
bracket measures dispatch, not compute — every timer here FETCHES a
leaf of the measured value before reading the clock (SURVEY.md §7
"timing honesty" hard part; BASELINE.md: under relay transports even
``block_until_ready`` can return before device compute completes, so
the shared :func:`tpudp.utils.profiler.fetch_fence` is the only
reliable edge).
"""

from __future__ import annotations

import time


class StepTimer:
    """Accumulates wall time across steps with fetch-fenced edges."""

    def __init__(self):
        self.total = 0.0
        self.count = 0
        self._t0 = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, *block_on) -> float:
        from tpudp.utils.profiler import fetch_fence

        for x in block_on:
            fetch_fence(x)
        dt = time.perf_counter() - self._t0
        self.total += dt
        self.count += 1
        return dt

    @property
    def mean(self) -> float:
        return self.total / max(self.count, 1)

    def reset(self) -> None:
        self.total, self.count = 0.0, 0

"""The span/event recorder at the bottom of ``tpudp.obs``.

Telemetry in this repo has to survive its own static analysis: the PR 8
linter forbids host syncs on the scheduler hot paths, and the same
discipline applies to the instrumentation itself — a recorder that
allocates, locks, or syncs per token would be the regression it exists
to observe.  So the core is a **preallocated monotonic-clock ring**:

  * :meth:`Recorder.begin` / :meth:`Recorder.end` — the allocation-free
    hot-path API.  ``begin`` writes (name, t0) into the next
    preallocated ring record and returns an integer token; ``end``
    stamps t1 into that record iff the ring has not lapped it.  Two
    ``time.monotonic()`` reads and a few attribute stores per span; no
    container growth, no device touch.  The ``obs-in-hot-path`` lint
    rule pins exactly this API as the only one allowed inside the
    designated hot paths.
  * :meth:`Recorder.event` / :meth:`Recorder.span` — the convenient
    (allocating) API for everything OFF the hot path: request
    admission/retirement, recovery decisions, checkpoint writes.
    Events carry a ``**fields`` dict; ``span`` is a context manager.
  * :meth:`Recorder.count` — host-side named counters (a plain
    ``Counter``); the device-side zero-sync counters live in the step
    programs (``tpudp/serve/engine.py``) and are only *fetched* here by
    ``metrics()`` snapshots, never on a hot path.

The ring holds the last ``capacity`` records per recorder — old
telemetry is dropped, never compacted; that bounded-loss contract is
what makes the recorder safe to leave on in production and is exactly
what the flight recorder (``tpudp/obs/flight.py``) wants: the last N
spans before a fault ARE the black box.

Timestamps are ``time.monotonic()`` (immune to wall-clock steps); each
recorder stamps a ``(monotonic, wall)`` anchor pair at construction so
exports can place the timeline in wall time.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import time

#: Disabled-recorder token: ``end()`` treats it as a no-op.
NO_SPAN = -1

_RECORDER_IDS = itertools.count()


class _Rec:
    """One preallocated ring slot, reused in place (never reallocated —
    the hot path only stores into existing attributes)."""

    __slots__ = ("seq", "kind", "name", "t0", "t1", "fields")

    def __init__(self):
        self.seq = -1       # ring generation; -1 = never written
        self.kind = ""      # "span" | "event"
        self.name = ""
        self.t0 = 0.0
        self.t1 = -1.0      # -1.0 = span still open
        self.fields = None  # dict for events / tagged spans, else None


class Recorder:
    """Bounded span/event/counter recorder — one per engine/trainer.

    ``enabled=False`` turns every method into an O(1) no-op (the
    overhead-guard test pins the enabled path's cost too).  ``capacity``
    bounds the ring; the newest ``capacity`` records win.
    """

    __slots__ = ("name", "enabled", "capacity", "counters",
                 "anchor_monotonic", "anchor_wall",
                 "_ring", "_seq", "_last_done", "_id")

    def __init__(self, name: str = "", capacity: int = 4096,
                 enabled: bool = True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.name = name
        self.enabled = enabled
        self.capacity = capacity
        self.counters: collections.Counter = collections.Counter()
        self.anchor_monotonic = time.monotonic()
        self.anchor_wall = time.time()
        self._ring = [_Rec() for _ in range(capacity)]
        self._seq = 0
        self._last_done = NO_SPAN
        self._id = next(_RECORDER_IDS)

    # -- hot-path API (allocation-free; sanctioned by obs-in-hot-path) --

    def begin(self, name: str) -> int:
        """Open a span; returns the token :meth:`end` closes.  Safe on
        the designated scheduler/step hot paths: two attribute stores
        and one clock read, no allocation beyond the returned int."""
        if not self.enabled:
            return NO_SPAN
        seq = self._seq
        rec = self._ring[seq % self.capacity]
        rec.seq = seq
        rec.kind = "span"
        rec.name = name
        rec.fields = None
        rec.t1 = -1.0
        rec.t0 = time.monotonic()
        self._seq = seq + 1
        return seq

    def end(self, token: int) -> None:
        """Close the span ``begin`` opened.  A token the ring has since
        lapped (or :data:`NO_SPAN`) is silently dropped — bounded loss,
        never an error, never a stall."""
        if token < 0 or not self.enabled:
            return
        rec = self._ring[token % self.capacity]
        if rec.seq == token:
            rec.t1 = time.monotonic()
            self._last_done = token

    def count(self, name: str, n: int = 1) -> None:
        """Bump a host-side named counter (Counter add — hot-path safe)."""
        if self.enabled:
            self.counters[name] += n

    # -- off-hot-path API ----------------------------------------------

    def event(self, name: str, **fields) -> None:
        """Record a point event with arbitrary JSON-able fields.  The
        convenient/allocating API: request lifecycle, recovery
        decisions, checkpoint writes — anything not on a designated hot
        path (the obs-in-hot-path rule rejects it there)."""
        if not self.enabled:
            return
        seq = self._seq
        rec = self._ring[seq % self.capacity]
        rec.seq = seq
        rec.kind = "event"
        rec.name = name
        rec.fields = fields or None
        rec.t0 = time.monotonic()
        rec.t1 = rec.t0
        self._seq = seq + 1
        self._last_done = seq

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        """Context-manager span with tags — the allocating twin of
        ``begin``/``end`` for off-hot-path regions."""
        token = self.begin(name)
        if token >= 0 and fields:
            self._ring[token % self.capacity].fields = fields
        try:
            yield token
        finally:
            self.end(token)

    # -- reads ----------------------------------------------------------

    def __len__(self) -> int:
        return min(self._seq, self.capacity)

    def _record_dict(self, rec: _Rec) -> dict:
        out = {"seq": rec.seq, "kind": rec.kind, "name": rec.name,
               "t0": rec.t0 - self.anchor_monotonic}
        if rec.kind == "span":
            out["dur"] = (rec.t1 - rec.t0) if rec.t1 >= 0.0 else None
        if rec.fields:
            out["fields"] = dict(rec.fields)
        return out

    def snapshot(self) -> list[dict]:
        """The ring's surviving records, oldest first, as plain dicts
        (relative-seconds timestamps).  Tolerates concurrent writers
        (the watchdog's monitor thread snapshots while the scheduler
        records): a record overwritten mid-read is skipped, never a
        crash — the flight recorder prefers a dropped span to a hang."""
        out = []
        top = self._seq
        for seq in range(max(0, top - self.capacity), top):
            rec = self._ring[seq % self.capacity]
            try:
                if rec.seq != seq:
                    continue  # lapped by a concurrent writer
                out.append(self._record_dict(rec))
            except Exception:
                continue
        return out

    def last_span(self) -> dict | None:
        """The most recently COMPLETED record (the watchdog's "last
        thing that finished before the hang")."""
        token = self._last_done
        if token < 0:
            return None
        rec = self._ring[token % self.capacity]
        if rec.seq != token:
            return None
        return self._record_dict(rec)

    def summary(self) -> dict:
        """Per-span-name aggregates over the surviving ring:
        ``{name: {"count": n, "total_s": s}}`` — the cheap rollup
        ``metrics()`` snapshots embed."""
        agg: dict[str, dict] = {}
        for rec in self.snapshot():
            if rec["kind"] != "span" or rec.get("dur") is None:
                continue
            slot = agg.setdefault(rec["name"], {"count": 0, "total_s": 0.0})
            slot["count"] += 1
            slot["total_s"] += rec["dur"]
        for slot in agg.values():
            slot["total_s"] = round(slot["total_s"], 6)
        return agg

    def clear(self) -> None:
        self._seq = 0
        self._last_done = NO_SPAN
        for rec in self._ring:
            rec.seq = -1
        self.counters.clear()

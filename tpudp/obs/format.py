"""Span-backed reference-format log lines.

The Trainer's window prints are PARITY OUTPUT — they reproduce the
reference's exact strings (``src/Part 2a/main.py:100-112``) and tests/
humans diff them against reference runs — so folding the print path
into ``tpudp.obs`` must be a refactor, not a reformat.  This module is
the single formatter both the Trainer and any span consumer use: given
a completed train window's numbers (exactly what the window span
carries), it returns the reference's lines byte-for-byte.
"""

from __future__ import annotations


def reference_window_lines(it: int, loss: float, window_time: float,
                           log_every: int, *, fwd_t: float | None = None,
                           bwd_t: float | None = None,
                           first_window: bool = False) -> list[str]:
    """The reference's per-window lines for one completed log window.

    ``first_window`` reproduces the reference's warmup exclusion (the
    compile-bearing first window prints loss only);
    ``fwd_t``/``bwd_t`` add the split-timing lines when the driver
    measured them (``timing_mode='split'``).  Strings are pinned
    byte-for-byte by tests/test_obs.py."""
    lines = [
        "Training loss after {} iterations is {}".format(it, loss),
    ]
    if not first_window:
        if fwd_t is not None:
            lines.append("Forward Pass time in iter {} is {}".format(
                it, fwd_t / log_every))
        if bwd_t is not None:
            lines.append("Backward Pass time in iter {} is {}".format(
                it, bwd_t / log_every))
        lines.append("Average Pass time in iter {} is {}".format(
            it, window_time / log_every))
    return lines

"""Prometheus-style text exposition for ``metrics()`` snapshots.

``Engine.metrics()`` / ``Trainer.metrics()`` return nested dicts;
:func:`prometheus_text` flattens the numeric leaves into the standard
``# TYPE`` + ``name value`` text format (one series per leaf, path
segments joined by ``_``, non-metric characters sanitized), and
:func:`serve_metrics` exposes that text over HTTP on a daemon thread —
``tpudp.cli --metrics-port N`` serves the live trainer, so a pod run's
progress is one ``curl localhost:N/metrics`` away.

This is deliberately the TEXT format only (no client library, no
registry): the repo's rule against new dependencies holds for
observability too, and the format is three lines of string building.
"""

from __future__ import annotations

import re
import threading

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _flatten(prefix: str, value, out: list[tuple[str, float]]) -> None:
    if isinstance(value, dict):
        for key in sorted(value, key=str):
            name = f"{prefix}_{key}" if prefix else str(key)
            _flatten(name, value[key], out)
        return
    if isinstance(value, bool):
        out.append((prefix, 1.0 if value else 0.0))
    elif isinstance(value, (int, float)):
        out.append((prefix, float(value)))
    # non-numeric leaves (strings, None, lists) are not series — skipped


def prometheus_text(metrics: dict, prefix: str = "tpudp") -> str:
    """Flatten a ``metrics()`` snapshot into Prometheus text format."""
    series: list[tuple[str, float]] = []
    _flatten(prefix, metrics, series)
    lines = []
    for name, value in series:
        name = _NAME_RE.sub("_", name)
        lines.append(f"# TYPE {name} gauge")
        # full precision, never %g: a token counter past ~1e6 must not
        # round to 6 significant digits on the wire
        text = "%d" % value if value.is_integer() else repr(value)
        lines.append(f"{name} {text}")
    return "\n".join(lines) + ("\n" if lines else "")


class MetricsServer:
    """Tiny ``/metrics`` HTTP endpoint on a daemon thread.

    ``supplier`` is called per request and must return the metrics
    dict; a supplier failure serves a 500 with the error text instead
    of killing the serving thread.  Binds localhost only — this is an
    operator peephole, not an ingress."""

    def __init__(self, port: int, supplier, prefix: str = "tpudp",
                 host: str = "127.0.0.1"):
        import http.server

        server_self = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                try:
                    body = prometheus_text(server_self.supplier(),
                                           server_self.prefix)
                    code = 200
                except Exception as exc:  # supplier is user code
                    body, code = f"# metrics supplier failed: {exc!r}\n", 500
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *args):  # quiet by default
                pass

        self.supplier = supplier
        self.prefix = prefix
        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      _Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="tpudp-metrics")
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

"""``tpudp.obs`` — structured telemetry for both runtimes.

One subsystem, four layers (docs/OBSERVABILITY.md):

  * **Spans & events** (:mod:`tpudp.obs.record`): a preallocated
    monotonic-clock ring per engine/trainer.  ``begin``/``end`` is the
    allocation-free hot-path API (the only one the ``obs-in-hot-path``
    lint rule allows on the designated scheduler/step hot paths);
    ``span``/``event`` are the convenient off-hot-path forms.
  * **Zero-sync device counters**: per-step scalars accumulated INSIDE
    the existing step programs (``tpudp/serve/engine.py``
    ``OBS_DEVICE_COUNTERS``) and carried in the arrays the engine
    already shuttles — fetched only by ``Engine.metrics()`` snapshots,
    never on a hot path, so ``tpudp.analysis lint`` stays at zero
    host-sync findings.
  * **Flight recorder** (:mod:`tpudp.obs.flight`): the ring persists as
    a per-host ``flightrec-*.json`` on watchdog timeouts, step-failure
    containment, and resilience rollbacks — enable by directory
    (``TPUDP_FLIGHT_DIR`` or the ``flight_dir`` knobs).
  * **Exposition** (:mod:`tpudp.obs.export` / :mod:`tpudp.obs.metrics`):
    Chrome/Perfetto ``trace_event`` JSON, plain JSON snapshots, and a
    Prometheus-style text endpoint (``tpudp.cli --metrics-port``).

This package also absorbed the repo's older one-off timing APIs so
there is ONE timing surface: :class:`StepTimer` (ex
``tpudp/utils/timing.py``), the XLA :func:`trace` capture wrapper (ex
``tpudp.utils.profiler.trace``), and the reference-parity window-line
formatter (:func:`reference_window_lines`) the Trainer prints through.
The old import paths re-export from here.  Importing ``tpudp.obs``
never imports jax.
"""

from tpudp.obs.export import (counters_from_chrome_trace, snapshot_json,
                              spans_from_chrome_trace, to_chrome_trace)
from tpudp.obs.flight import (FLIGHT_DIR_ENV, FlightRecorder,
                              coordinated_merge, list_dumps, merge_dumps,
                              resolve_flight_dir)
from tpudp.obs.format import reference_window_lines
from tpudp.obs.metrics import MetricsServer, prometheus_text
from tpudp.obs.record import NO_SPAN, Recorder
from tpudp.obs.timing import StepTimer
from tpudp.obs.tracing import step_annotation, trace

__all__ = [
    "FLIGHT_DIR_ENV", "FlightRecorder", "MetricsServer", "NO_SPAN",
    "Recorder", "StepTimer", "coordinated_merge",
    "counters_from_chrome_trace", "list_dumps", "merge_dumps",
    "prometheus_text", "reference_window_lines", "resolve_flight_dir",
    "snapshot_json", "spans_from_chrome_trace", "step_annotation",
    "to_chrome_trace", "trace",
]

"""XLA profiler capture — the ``tpudp.obs`` home of the old
``tpudp.utils.profiler.trace`` wrapper (that module re-exports from
here, so existing imports keep working).

The host-side recorder (``tpudp/obs/record.py``) answers "what was the
scheduler doing"; THIS layer answers "what was the chip doing": a real
XLA/TPU profile (TensorBoard trace-viewer format) around any region,
with per-step boundaries marked so the viewer groups work by training
step.  jax is imported lazily so ``tpudp.obs`` stays importable from
stdlib-only tooling (the same discipline as ``tpudp.analysis``).
"""

from __future__ import annotations

import contextlib
from typing import Iterator


@contextlib.contextmanager
def trace(log_dir: str | None) -> Iterator[None]:
    """XLA profiler capture into ``log_dir`` (no-op when None).  View
    with TensorBoard's profile plugin or xprof."""
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


def step_annotation(step: int):
    """Mark a training step in an active trace."""
    import jax

    return jax.profiler.StepTraceAnnotation("train_step", step_num=step)

"""Parallelism strategies as first-class Trainer capabilities.

Round-1 left TP/PP/EP/FSDP/SP as bare step-builders returning
``(state, step_fn)``; this module promotes them to full framework rungs:
:func:`build_strategy` packages the sharded state, the train step, a
matching eval step, and the input-sharding rule, so the ``Trainer`` drives
any rung with the same epoch loop, reference-format logging
(``src/Part 2a/main.py:102-112``), watchdog heartbeats, and orbax
checkpoint/resume the DP path always had.

Every strategy obeys the framework-wide contracts::

    train_step(state, inputs, labels)          -> (state, loss)
    eval_step(state, inputs, labels, weights)  -> (loss_sum, correct, count)
    shard_for(host_array)                      -> NamedSharding

Mesh axis requirements (build the mesh with tpudp.mesh.make_mesh_nd):

  ============  ===========================  ==========================
  strategy      mesh axes                    options
  ============  ===========================  ==========================
  ``tp``        ``data`` x ``model``         ``rules`` (partition rules)
  ``fsdp``      ``data``                     ``min_size``
  ``zero1``     ``data``                     ``min_size``
  ``pp``        [``data`` x] ``pipe``        ``n_microbatches``, ``remat``,
                                             ``schedule`` (``gpipe`` /
                                             ``1f1b`` / ``1f1b_mpmd``);
                                             under ``1f1b_mpmd`` also
                                             ``interleave``,
                                             ``shard_optimizer``
  ``ep``        ``data`` x ``expert``        ``aux_loss_coef``
  ``sp``        ``data`` x ``seq``           —
  ============  ===========================  ==========================
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudp.mesh import DATA_AXIS

STRATEGIES = ("dp", "tp", "fsdp", "zero1", "pp", "ep", "sp")


class BuiltStrategy(NamedTuple):
    state: Any
    train_step: Callable
    eval_step: Callable
    shard_for: Callable[[Any], NamedSharding]


def _leading_axis_sharder(mesh: Mesh, spec: P) -> Callable:
    sh = NamedSharding(mesh, spec)

    def shard_for(_arr) -> NamedSharding:
        return sh

    return shard_for


def build_strategy(
    name: str,
    model,
    tx,
    mesh: Mesh,
    state,
    *,
    donate: bool = True,
    **options,
) -> BuiltStrategy:
    """Build the full rung for ``name`` from a standard (single-device
    layout) TrainState.  See the module table for per-strategy options."""
    if name == "dp":
        raise ValueError(
            "'dp' is the Trainer's built-in rung (make_train_step / the sync "
            "ladder); build_strategy only packages the advanced rungs "
            f"{tuple(s for s in STRATEGIES if s != 'dp')}")
    if mesh is None:
        raise ValueError(f"strategy {name!r} needs a device mesh")
    if name == "tp":
        return _build_tp(model, tx, mesh, state, donate, options)
    if name == "fsdp":
        return _build_fsdp(model, tx, mesh, state, donate, options)
    if name == "zero1":
        return _build_zero1(model, tx, mesh, state, donate, options)
    if name == "pp":
        return _build_pp(model, tx, mesh, state, donate, options)
    if name == "ep":
        return _build_ep(model, tx, mesh, state, donate, options)
    if name == "sp":
        return _build_sp(model, tx, mesh, state, donate, options)
    raise ValueError(f"unknown strategy {name!r}; choose from {STRATEGIES}")


def _gspmd_eval(model, mesh, st_sh, data_axis):
    """Eval for GSPMD-sharded states (TP/FSDP): the global-batch metrics
    program under jit; XLA inserts the gathers the state sharding needs."""
    from tpudp.train import eval_metrics

    data = NamedSharding(mesh, P(data_axis))
    rep = NamedSharding(mesh, P())

    @partial(jax.jit, in_shardings=(st_sh, data, data, data),
             out_shardings=(rep, rep, rep))
    def eval_step(state, inputs, labels, weights):
        return eval_metrics(model, state, inputs, labels, weights)

    return eval_step


def _build_tp(model, tx, mesh, state, donate, options):
    from tpudp.train import make_tp_train_step, resolve_state_shardings

    rules = options.pop("rules")
    data_axis = options.pop("data_axis", DATA_AXIS)
    _no_extra(options, "tp")
    st, step = make_tp_train_step(model, tx, mesh, state, rules,
                                  data_axis=data_axis, donate=donate)
    st_sh = resolve_state_shardings(state, mesh, rules)
    return BuiltStrategy(st, step, _gspmd_eval(model, mesh, st_sh, data_axis),
                         _leading_axis_sharder(mesh, P(data_axis)))


def _build_data_sharded(name, make_step, shardings_fn,
                        model, tx, mesh, state, donate, options):
    """Shared builder for the 1-D data-axis GSPMD rungs (fsdp, zero1):
    identical option surface, step-maker + shardings function vary."""
    from tpudp.train import resolve_state_shardings

    data_axis = options.pop("data_axis", DATA_AXIS)
    min_size = options.pop("min_size", 1024)
    _no_extra(options, name)
    st, step = make_step(model, tx, mesh, state,
                         data_axis=data_axis, min_size=min_size,
                         donate=donate)
    st_sh = resolve_state_shardings(
        state, mesh, partial(shardings_fn, axis=data_axis,
                             min_size=min_size))
    return BuiltStrategy(st, step, _gspmd_eval(model, mesh, st_sh, data_axis),
                         _leading_axis_sharder(mesh, P(data_axis)))


def _build_fsdp(model, tx, mesh, state, donate, options):
    from tpudp.parallel.tensor import fsdp_shardings
    from tpudp.train import make_fsdp_train_step

    return _build_data_sharded("fsdp", make_fsdp_train_step, fsdp_shardings,
                               model, tx, mesh, state, donate, options)


def _build_zero1(model, tx, mesh, state, donate, options):
    from tpudp.parallel.tensor import zero1_shardings
    from tpudp.train import make_zero1_train_step

    return _build_data_sharded("zero1", make_zero1_train_step,
                               zero1_shardings, model, tx, mesh, state,
                               donate, options)


def _build_pp(model, tx, mesh, state, donate, options):
    from tpudp.parallel.pipeline import PIPE_AXIS

    n_microbatches = options.pop("n_microbatches")
    pipe_axis = options.pop("pipe_axis", PIPE_AXIS)
    data_axis = options.pop(
        "data_axis", DATA_AXIS if DATA_AXIS in mesh.shape else None)
    remat = options.pop("remat", False)
    schedule = options.pop("schedule", "gpipe")
    if schedule == "1f1b_mpmd":
        # The unrolled MPMD schedule (tpudp/parallel/schedule.py): per-tick
        # programs, interleaved virtual stages, in-step sharded optimizer.
        from tpudp.parallel.schedule import (make_pipeline_eval_step,
                                             make_pipeline_train_step)

        interleave = options.pop("interleave", 1)
        shard_optimizer = options.pop("shard_optimizer", True)
        _no_extra(options, "pp")
        st, step = make_pipeline_train_step(
            model, tx, mesh, state, n_microbatches=n_microbatches,
            interleave=interleave, data_axis=data_axis,
            pipe_axis=pipe_axis, donate=donate, remat=remat,
            shard_optimizer=shard_optimizer)
        eval_step = make_pipeline_eval_step(
            model, mesh, st, n_microbatches=n_microbatches,
            interleave=interleave, data_axis=data_axis,
            pipe_axis=pipe_axis)
    else:
        from tpudp.parallel.pipeline import (make_pp_eval_step,
                                             make_pp_train_step)

        _no_extra(options, "pp")
        st, step = make_pp_train_step(model, tx, mesh, state,
                                      n_microbatches=n_microbatches,
                                      data_axis=data_axis,
                                      pipe_axis=pipe_axis,
                                      donate=donate, remat=remat,
                                      schedule=schedule)
        eval_step = make_pp_eval_step(model, mesh, st,
                                      n_microbatches=n_microbatches,
                                      data_axis=data_axis,
                                      pipe_axis=pipe_axis)
    spec = P(data_axis) if data_axis is not None else P()
    return BuiltStrategy(st, step, eval_step,
                         _leading_axis_sharder(mesh, spec))


def _build_ep(model, tx, mesh, state, donate, options):
    from tpudp.parallel.expert import (EXPERT_AXIS, make_ep_eval_step,
                                       make_ep_train_step)

    data_axis = options.pop("data_axis", DATA_AXIS)
    expert_axis = options.pop("expert_axis", EXPERT_AXIS)
    aux_loss_coef = options.pop("aux_loss_coef", 0.01)
    _no_extra(options, "ep")
    st, step = make_ep_train_step(model, tx, mesh, state,
                                  data_axis=data_axis,
                                  expert_axis=expert_axis,
                                  aux_loss_coef=aux_loss_coef, donate=donate)
    eval_step = make_ep_eval_step(model, mesh, st, data_axis=data_axis,
                                  expert_axis=expert_axis)
    return BuiltStrategy(
        st, step, eval_step,
        _leading_axis_sharder(mesh, P((data_axis, expert_axis))))


def _build_sp(model, tx, mesh, state, donate, options):
    from tpudp.train import make_seq_parallel_train_step, make_sp_eval_step

    data_axis = options.pop("data_axis", DATA_AXIS)
    seq_axis = options.pop("seq_axis", "seq")
    _no_extra(options, "sp")
    step = make_seq_parallel_train_step(model, tx, mesh,
                                        data_axis=data_axis,
                                        seq_axis=seq_axis, donate=donate)
    eval_step = make_sp_eval_step(model, mesh, data_axis=data_axis,
                                  seq_axis=seq_axis)
    st = jax.device_put(state, NamedSharding(mesh, P()))
    two_d = NamedSharding(mesh, P(data_axis, seq_axis))
    one_d = NamedSharding(mesh, P(data_axis))

    def shard_for(arr) -> NamedSharding:
        # token/target matrices shard (batch, seq); per-sample vectors
        # (eval weights) shard batch only
        return two_d if getattr(arr, "ndim", 0) >= 2 else one_d

    return BuiltStrategy(st, step, eval_step, shard_for)


def _no_extra(options: dict, name: str) -> None:
    if options:
        raise TypeError(
            f"unknown option(s) for strategy {name!r}: {sorted(options)}")

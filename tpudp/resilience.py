"""In-process fault supervision for ``Trainer.fit`` — the training-stack
counterpart of the serve engine's containment layer (PR 3).

The trainer's detectors existed before this module — the heartbeat
``Watchdog`` sees hangs, ``check_finite`` sees non-finite loss windows,
emergency dumps make a killed process resumable — but every one of them
ended in a DEAD process that a human (or scheduler) had to relaunch.
Production TPU training treats preemptions, flaky steps, and loss spikes
as the steady state (arXiv:2204.06514); the contract a runtime must keep
through them is TRAJECTORY CONSISTENCY (arXiv:2509.07003): recovery may
cost wall time, never a different model.  This module converts each
detector into in-process recovery under exactly that oracle — every
recovery path restores a checkpoint and deterministically replays, so the
final parameters are bit-identical to an uninterrupted run (the
kill/resume soak in ``benchmarks/resilience_bench.py`` enforces this).

Recovery taxonomy (docs/RESILIENCE.md):

  * **Divergence rollback** — a non-finite loss window
    (``FloatingPointError`` from ``check_finite``) or a window loss beyond
    ``spike_factor`` x the trailing median (:class:`LossSpikeError`)
    restores the newest VERIFIED checkpoint and fast-forwards the data
    stream to the restore point (``train_epoch(skip_batches=)`` — host
    RNG replays, so the consumed-batch sequence is unchanged).  Bounded
    by ``max_rollbacks``, then the ORIGINAL error escalates.
  * **Step-fault / hang recovery** — an exception escaping the train step
    (or ``StepHangError`` from a ``kill=False`` watchdog) takes the
    emergency-dump path, restores it (or falls back to the newest
    verified checkpoint if the live state was invalidated by donation),
    re-arms the watchdog, and continues IN THE SAME PROCESS.  A second
    consecutive failure at the same step escalates.
  * **Checkpoint-integrity fallback** — every restore verifies the
    per-leaf checksum manifest; a torn/corrupt newest checkpoint falls
    back to the previous intact step dir (``stats["ckpt_fallbacks"]``).
  * **Loader containment** — an exception out of the loader/Prefetcher
    worker restarts the pipeline and replays to the exact batch offset
    (same host-RNG draws), bounded by ``max_loader_restarts`` per epoch.
  * **Silent-data-corruption defense** (``sdc_check_every=N`` +
    ``Trainer(track_sdc_fingerprint=True)``, tpudp.sdc) — every N
    optimizer steps, at the window-edge seam the host already pays
    for, the per-device shards of the in-step ``sdc_fp`` checksum are
    majority-voted (locally, and host-granular across hosts via one
    bounded gather of each host's fingerprint + local vote summary —
    every host derives the SAME verdict from the same rows, so every
    host raises in the same protocol round); raw param/optimizer
    shard bytes are fetched only AFTER a mismatch, to localize the
    corrupt device.  A detection rides the divergence rollback; the
    bit-exact replay is the oracle that GRADES it — a clean re-check
    is a transient flip (continue, params repaired bit-identically),
    the same LOCALIZED replica diverging again is a persistently bad
    chip: quarantine (marker +
    :data:`~tpudp.sdc.SDC_QUARANTINE_EXIT`) and reduced-geometry
    relaunch through the elastic verified restore.  An unlocalizable
    detection (2-replica tie) never quarantines — the rollback budget
    bounds it.

Every recovery is a typed event in ``trainer.stats["events"]`` with
counters (``rollbacks`` / ``step_retries`` / ``ckpt_fallbacks`` /
``loader_restarts``), so the soak can account one recovery per injected
fault.  ``Trainer.fit(..., resilience=None)`` — the default — is
byte-for-byte today's behavior: no supervisor, no extra host work, the
original crash semantics.

Multi-host (``jax.process_count() > 1``) supervision is COORDINATED
(docs/RESILIENCE.md "Multi-host recovery").  Per-process recovery
decisions could diverge replicas (one host resuming epoch 3 while its
peer resumes epoch 4 deadlocks the next collective), so at every
recovery decision point the hosts allgather a per-host outcome code
(:func:`reduce_outcomes`: worst severity wins) and execute ONE agreed
action — a NaN window on any host rolls every host back, and the
restore target is the newest checkpoint EVERY host verifies (the
coordinated walk in ``tpudp/utils/checkpoint.py`` votes per step dir,
so the restore step is effectively the min over hosts' newest
verified).  Step faults and hangs recover from the newest verified
checkpoint rather than an emergency dump: the dump path is a collective
save, and a host cannot unilaterally start a collective while its peer
is wedged.  The vote itself is BOUNDED: a host whose peers never join
(SIGKILLed worker, torn network) hard-exits with
:data:`VOTE_TIMEOUT_EXIT` so the scheduler relaunches the pod into the
coordinated resume path — mirroring the CLI watchdog's generation-
tracked hard-exit backstop, which keeps covering hosts wedged INSIDE a
device collective (those never reach a vote).  After any coordinated
restore, all hosts must agree on the state fingerprint
(``tpudp/utils/consistency.py``) before training resumes; divergence
raises :class:`~tpudp.utils.consistency.ReplicaDivergenceError`.
"""

from __future__ import annotations

import os
import statistics
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable

from tpudp.sdc import (SDC_QUARANTINE_EXIT, QUARANTINE_MARKER, SdcDetected,
                       SdcPersistentError)
from tpudp.utils.watchdog import StepHangError

# Per-host outcome codes for the multi-host recovery vote, ordered by
# severity: the allgathered codes reduce to their MAX (worst wins), so
# e.g. a divergence on one host outranks a peer's clean completion and
# every host executes the rollback.
OUTCOME_OK = 0
OUTCOME_STEP_FAULT = 1
OUTCOME_HANG = 2
OUTCOME_DIVERGENCE = 3

OUTCOME_NAMES = {OUTCOME_OK: "ok", OUTCOME_STEP_FAULT: "step_fault",
                 OUTCOME_HANG: "hang", OUTCOME_DIVERGENCE: "divergence"}

# Exit code when a recovery vote times out or its collective fails (a
# peer host is dead or wedged): the process exits for the scheduler,
# exactly like the CLI watchdog's hard-exit backstop (which uses 42) —
# distinct so the soak can attribute the exit to the vote path.
VOTE_TIMEOUT_EXIT = 43


def reduce_outcomes(codes) -> int:
    """Deterministically reduce per-host outcome codes to ONE action:
    worst severity wins.  Every host computes this over the same
    allgathered vector, so all hosts execute the same recovery."""
    return max(int(c) for c in codes)


class LossSpikeError(RuntimeError):
    """A finite but anomalous window loss: beyond ``spike_factor`` x the
    trailing-median window loss.  Finite spikes poison momentum and can
    take many windows to surface as NaN — rolling back at the spike is
    the cheap early exit (veScale's trajectory argument)."""

    def __init__(self, loss: float, median: float, step: int):
        super().__init__(
            f"training loss spike at step {step}: {loss:.6g} > "
            f"{median:.6g} trailing median")
        self.loss, self.median, self.step = loss, median, step


class ResilienceExhausted(RuntimeError):
    """Internal escalation signal: a recovery budget ran out.  Carries the
    ORIGINAL error, which the supervisor re-raises — escalation must look
    exactly like today's crash so schedulers/tests keyed on the original
    exception type keep working."""

    def __init__(self, message: str, original: BaseException):
        super().__init__(message)
        self.original = original


@dataclass
class ResiliencePolicy:
    """Knobs for the in-process fault supervisor (``Trainer.fit``'s
    ``resilience=`` argument).  ``checkpoint_dir`` is required: rollback
    and step recovery restore from the ``step_N`` series (and the
    emergency dump) under this root.

    ``spike_factor=None`` disables spike detection (NaN windows still roll
    back).  ``save_epoch_checkpoints=False`` is for drivers whose
    ``epoch_end_fn`` already saves into the same root (tpudp.cli) — the
    supervisor then never double-writes.  ``checkpoint_writer`` is the
    driver's AsyncCheckpointWriter if one is active: the supervisor calls
    ``wait()`` on it before any emergency dump so an overlapped epoch-end
    write can never interleave with the dump in the same root.

    ``vote_timeout_s`` (multi-host only) bounds the wait at each recovery
    vote: if no peer joins the allgather within it — the peer is dead,
    not merely recovering — the process hard-exits with
    :data:`VOTE_TIMEOUT_EXIT` so the scheduler relaunches the pod into
    the coordinated resume path instead of hanging forever.

    ``sdc_check_every=N`` arms the silent-data-corruption check
    (tpudp.sdc): every N optimizer steps the per-replica state
    fingerprints are majority-voted at the window-edge seam.  Requires
    the trainer to carry the in-step fingerprint
    (``Trainer(track_sdc_fingerprint=True)``) so detection inherits the
    zero-new-host-syncs contract; ``None`` — the default — adds no
    check and no work."""

    checkpoint_dir: str
    max_rollbacks: int = 3
    spike_factor: float | None = None
    spike_window: int = 8
    spike_min_history: int = 3
    max_step_retries: int = 1
    max_loader_restarts: int = 3
    save_epoch_checkpoints: bool = True
    checkpoint_writer: Any = None
    on_event: Callable[[dict], None] | None = None
    vote_timeout_s: float = 120.0
    sdc_check_every: int | None = None


def make_emergency_dump(checkpoint_dir: str, get_state,
                        per_epoch_batches: int,
                        async_writer=None, log=print) -> Callable[[], None]:
    """Build the dump closure shared by the CLI's watchdog ``on_hang`` and
    the supervisor's step recovery: invalidate the previous dump's
    sentinel FIRST, drain any in-flight async epoch-end write (two orbax
    writers interleaving in one root can tear both), save, then commit
    the sentinel only after orbax finalized."""
    from tpudp.utils.checkpoint import (clear_emergency_sentinel,
                                        save_checkpoint,
                                        write_emergency_sentinel)

    def dump() -> None:
        clear_emergency_sentinel(checkpoint_dir)
        if async_writer is not None:
            async_writer.wait()
        state = get_state()
        path = os.path.join(checkpoint_dir, "emergency")
        save_checkpoint(path, state)
        write_emergency_sentinel(checkpoint_dir, step=int(state.step),
                                 per_epoch_batches=per_epoch_batches)
        log(f"[tpudp] emergency checkpoint saved to {path}")

    return dump


def auto_resume(trainer, checkpoint_dir: str, per_epoch_batches: int,
                *, log=print, on_event=None) -> tuple[int, int]:
    """Restore ``trainer.state`` from ``checkpoint_dir`` the way the CLI
    does — emergency dump preferred (then consumed), else the newest
    VERIFIED ``step_N`` — and return ``(start_epoch, skip_batches)``.

    Distillation of tpudp.cli's resume block for supervised workers (the
    soak's relaunch loop, tests); position is derived from the restored
    optimizer-step counter, so any restore point continues the exact
    batch grid.  Multi-host resume is COORDINATED: the verified walk
    votes per step dir, and the emergency dump's accept/quarantine
    decision is unanimous (``restore_emergency_voted``) so every host resumes the
    same state — process 0 alone consumes/quarantines, behind a
    barrier."""
    import jax

    from tpudp.utils.checkpoint import (consume_emergency, coordinated_any,
                                        emergency_dir, latest_step_dir,
                                        restore_emergency_voted,
                                        restore_latest_verified)

    def _barrier(tag: str) -> None:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(tag)

    restored = False
    # Entry into each collective restore protocol is itself a collective
    # decision (coordinated_any): a per-host listing probe deciding entry
    # would leave the host that sees a checkpoint alone inside an
    # allgather its stale-listing peer never joins.
    if coordinated_any(latest_step_dir(checkpoint_dir) is not None):
        state, path, skipped = restore_latest_verified(
            checkpoint_dir, trainer.state, log=log)
        trainer.state = state
        restored = True
        if on_event is not None:
            for rejected, reason in skipped:
                on_event({"kind": "ckpt_fallback", "rejected": rejected,
                          "reason": reason})
        log(f"[tpudp] resumed from {path}"
            + (f" ({len(skipped)} newer checkpoint(s) skipped as corrupt)"
               if skipped else ""))
    emerg = emergency_dir(checkpoint_dir)
    if coordinated_any(emerg is not None):
        if emerg is None:
            # Stale listing on this host; the dump's location is fixed,
            # and the voted restore below decides its fate for all.
            emerg = os.path.join(checkpoint_dir, "emergency")
        dump_state = restore_emergency_voted(checkpoint_dir, emerg,
                                             trainer.state, log=log)
        if dump_state is not None:
            trainer.state = dump_state
            restored = True
            _barrier("tpudp_emergency_consume")  # all read before rank 0
            # renames the directory out from under them
            if jax.process_index() == 0:
                consume_emergency(checkpoint_dir)
            log(f"[tpudp] resumed mid-epoch state from emergency dump {emerg}")
    if not restored:
        return 0, 0
    step = int(trainer.state.step)
    return step // per_epoch_batches, step % per_epoch_batches


class Supervisor:
    """Runs ``Trainer._fit`` under the recovery loop.  One instance per
    ``fit`` call; installs itself as ``trainer._resilience`` so the epoch
    driver's (otherwise dormant) seams — window-loss observation, the
    guarded batch iterator — report here."""

    def __init__(self, trainer, policy: ResiliencePolicy):
        import jax

        if not policy.checkpoint_dir:
            raise ValueError(
                "ResiliencePolicy.checkpoint_dir is required: rollback and "
                "step recovery restore from the step_N series under it")
        self.trainer = trainer
        self.policy = policy
        # Multi-host supervision runs the agreement protocol: every
        # recovery decision is an allgathered vote reduced to one action
        # (worst severity wins), every restore is the coordinated
        # verified walk, and a vote nobody joins hard-exits for the
        # scheduler (VOTE_TIMEOUT_EXIT).
        self._multihost = jax.process_count() > 1
        self._vote_seq = 0
        trainer.stats.update(rollbacks=0, step_retries=0, ckpt_fallbacks=0,
                             loader_restarts=0, events=[], sdc_checks=0,
                             sdc_detections=0, sdc_transients=0,
                             sdc_quarantines=0)
        self._window_losses: deque[float] = deque(maxlen=policy.spike_window)
        self._last_failed_step: int | None = None
        self._consecutive_at_step = 0
        self._per_epoch: int | None = None
        if policy.sdc_check_every is not None:
            if policy.sdc_check_every < 1:
                raise ValueError(
                    f"sdc_check_every must be >= 1, got "
                    f"{policy.sdc_check_every}")
            if getattr(trainer.state, "sdc_fp", None) is None:
                # The fingerprint slot must exist BEFORE the step
                # programs are built (shard_map specs are a fixed
                # pytree), so it cannot be allocated lazily here.
                raise ValueError(
                    "sdc_check_every requires the in-step fingerprint: "
                    "construct the Trainer with track_sdc_fingerprint="
                    "True so the sdc_fp slot is allocated before the "
                    "step programs are built")
        # SDC grading state: the last checked optimizer step, and the
        # unresolved detection awaiting its post-replay verdict.
        self._sdc_last_check = 0
        self._sdc_pending: dict | None = None

    # -- event log ------------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        event = {"kind": kind, **fields}
        self.trainer.stats["events"].append(event)
        # Mirror every typed recovery event into the obs ring (vote
        # rounds included), so a flight-record dump carries the
        # recovery timeline next to the train-window spans.
        self.trainer.obs.event("resilience." + kind, **fields)
        if self.policy.on_event is not None:
            self.policy.on_event(event)

    # -- seams the Trainer calls ---------------------------------------
    def observe_window_loss(self, loss: float, *, epoch: int,
                            it: int) -> None:
        """Called at every completed log window (value already
        check_finite-verified).  Raises :class:`LossSpikeError` when the
        window mean exceeds ``spike_factor`` x the trailing median; a
        completed window is also the progress signal that clears the
        consecutive-same-step failure tracking."""
        self._last_failed_step = None
        self._consecutive_at_step = 0
        p = self.policy
        if (p.spike_factor is not None
                and len(self._window_losses) >= p.spike_min_history):
            med = statistics.median(self._window_losses)
            if med > 0 and loss > p.spike_factor * med:
                step = epoch * (self._per_epoch or 0) + it
                self.record("loss_spike", epoch=epoch, it=it, loss=loss,
                            median=med, step=step)
                raise LossSpikeError(loss, med, step)
        self._window_losses.append(loss)

    # -- silent-data-corruption check (tpudp.sdc) -----------------------
    def observe_window_state(self, state, *, epoch: int, it: int) -> None:
        """Called at every completed log window, right after
        :meth:`observe_window_loss` — the host is already synchronized
        there (it just fetched ``loss_sum``), so the fingerprint check
        adds no new hot-path sync.  Cadence-gated by
        ``policy.sdc_check_every`` (None: immediate no-op).

        Detection reads ONLY the in-step checksum: each device's
        ``sdc_fp`` shard is the fingerprint THAT device computed over
        its own params/optimizer bytes, so voting the (2,)-u32 shards
        (:func:`tpudp.sdc.vote_fp_shards`) convicts a divergent
        replica without fetching one raw parameter byte — the
        zero-new-host-syncs contract holds at any model size.  The
        raw-byte walk (:func:`tpudp.sdc.vote_shard_groups`) runs only
        AFTER a mismatch, to localize the corrupt device under layouts
        where the fp vote names a whole pipeline column.

        Multi-host, the verdict is made GLOBAL before anyone raises:
        every host contributes its device-0 fingerprint plus its local
        vote summary to ONE bounded gather, every host derives the
        same minority set (host-granular ``p<i>`` keys) from the same
        rows, and every host raises :class:`~tpudp.sdc.SdcDetected` in
        the same protocol round — a host raising alone on a local-only
        verdict would leave its peers wedged inside the next step
        collective, breaking the every-host-votes-each-round
        invariant.  The post-replay re-check grades a detection: clean
        means transient (continue), the same LOCALIZED culprit again
        means persistent (:meth:`_sdc_quarantine`).  An unlocalizable
        detection (2-replica disagreement, tie votes) NEVER
        quarantines, however often it recurs — it keeps riding the
        divergence rollback, whose ``max_rollbacks`` budget bounds it
        and escalates with the original :class:`SdcDetected`."""
        every = self.policy.sdc_check_every
        if every is None:
            return
        gstep = int(state.step)
        if gstep - self._sdc_last_check < every:
            return
        self._sdc_last_check = gstep
        self.trainer.stats["sdc_checks"] += 1
        import numpy as np

        from tpudp.sdc import (SdcDetected, localize_minority,
                               vote_fp_shards, vote_shard_groups)

        minority, majority = vote_fp_shards(state.sdc_fp)
        localized = bool(majority)
        if minority:
            # Localization only: the corrupt device's raw shard bytes
            # are fetched AFTER the checksum vote proved a mismatch,
            # never on the clean-path cadence.
            d_min, d_maj = vote_shard_groups(
                {"params": state.params, "opt_state": state.opt_state})
            if d_min and d_maj:
                minority, majority = sorted(d_min), sorted(d_maj)
                localized = True
        devices = list(minority)
        if self._multihost:
            rows = self._sdc_gather(np.concatenate([
                np.asarray(self._fetch_fp(state), np.uint64),
                np.array([len(minority),
                          int(localized or not minority)], np.uint64)]))
            host_fps = {f"p{i}": r[:2] for i, r in enumerate(rows)}
            h_min, h_maj = localize_minority(host_fps)
            flagged = {f"p{i}" for i, r in enumerate(rows) if int(r[2])}
            minority = sorted(set(h_min) | flagged)
            localized = bool(minority) and all(
                int(r[3]) for r in rows) and (not h_min or bool(h_maj))
            majority = (sorted({f"p{i}" for i in range(len(rows))}
                               - set(minority)) if localized else [])
        pending = self._sdc_pending
        if not minority:
            if pending is not None and gstep >= pending["step"]:
                # The bit-exact replay re-crossed the detection point
                # clean: the flip was TRANSIENT and the rollback
                # repaired it — params are bit-identical to a run that
                # never saw it (the trajectory-consistency oracle).
                self.trainer.stats["sdc_transients"] += 1
                self.record("sdc_transient", replicas=pending["minority"],
                            step=pending["step"], cleared_at=gstep)
                self.trainer.log(
                    f"[tpudp] resilience: SDC at step {pending['step']} "
                    f"(replica(s) {pending['minority']}) did not recur "
                    f"through step {gstep} — transient flip, repaired by "
                    "rollback; continuing")
                self._sdc_pending = None
            return
        self.trainer.stats["sdc_detections"] += 1
        self.record("sdc_detected", replicas=minority, step=gstep,
                    epoch=epoch, it=it, localized=localized,
                    devices=devices)
        if (pending is not None and localized and pending["localized"]
                and set(minority) & set(pending["minority"])):
            self._sdc_quarantine(minority, gstep, devices)  # raises/exits
        self._sdc_pending = {"minority": minority, "step": gstep,
                             "localized": localized}
        named = (f"minority replica(s) {minority}" if localized
                 else f"replicas disagree ({minority}) with no strict "
                      "majority — corruption proven, culprit unnamed")
        raise SdcDetected(
            f"silent data corruption at step {gstep}: {named}",
            step=gstep, replica=minority if localized else None)

    @staticmethod
    def _fetch_fp(state):
        """This host's in-step fingerprint value (device 0's buffer of
        the logically-replicated ``sdc_fp`` leaf)."""
        import numpy as np

        shards = getattr(state.sdc_fp, "addressable_shards", None)
        if shards:
            return np.asarray(shards[0].data)
        return np.asarray(state.sdc_fp)

    def _sdc_gather(self, fp):
        """Bounded cross-host exchange of this host's check record —
        ``[fp_checksum, fp_count, local_minority_count,
        local_localized]`` — with the same timeout discipline as
        :meth:`_vote`: every host reaches this gather at the same
        checked step (the check cadence is a pure function of the
        replicated ``state.step``), every host derives the verdict
        from the same gathered rows, and a host whose peers never
        join hard-exits for the scheduler instead of hanging the
        rendezvous."""
        import threading

        import numpy as np

        result: dict = {}

        def gather() -> None:
            try:
                import jax.numpy as jnp
                from jax.experimental import multihost_utils

                # tpudp: lint-ok(protocol-divergent-entry): the except
                # arm IS the bounded-gather mitigation — a locally
                # failing collective (torn TCP, dead peer) becomes a
                # timeout verdict and a hard exit (43), and any peer
                # still inside the gather times out the same way.
                out = np.asarray(multihost_utils.process_allgather(
                    jnp.asarray(fp, jnp.uint32)))
                result["fps"] = [np.asarray(row, np.uint64) for row in out]
            except BaseException as e:  # gloo/XLA surface various types
                result["error"] = e

        th = threading.Thread(target=gather, daemon=True,
                              name="tpudp-sdc-gather")
        th.start()
        th.join(self.policy.vote_timeout_s)
        if "fps" not in result:
            why = (f"fingerprint gather failed: {result['error']!r}"
                   if "error" in result else
                   f"no peer joined within {self.policy.vote_timeout_s}s")
            self.record("vote_timeout", outcome="sdc_check", reason=why)
            self.trainer.log(
                f"[tpudp] resilience: SDC fingerprint gather got no "
                f"answer ({why}); peer host dead or wedged — hard-exiting "
                f"for scheduler relaunch (exit {VOTE_TIMEOUT_EXIT})")
            self.trainer.flight.dump("vote_timeout", extra={
                "reason": why, "outcome": "sdc_check"})
            os._exit(VOTE_TIMEOUT_EXIT)
        return result["fps"]

    def _sdc_quarantine(self, minority, gstep: int, devices=None) -> None:
        """The persistent verdict: the same LOCALIZED replica diverged
        again after a bit-exact replay, so the chip — not a cosmic ray
        — is at fault (unlocalizable detections never reach here: with
        no named culprit there is nothing safe to quarantine, and the
        rollback budget bounds them instead).  Record + flight-dump,
        write the on-disk marker naming the replica(s) (the relaunch
        harness reads it to shrink the geometry; ``devices`` adds this
        host's device-level detail), then hard-exit the owning host
        with :data:`~tpudp.sdc.SDC_QUARANTINE_EXIT` (multi-host) or
        raise :class:`~tpudp.sdc.SdcPersistentError` (single-host /
        healthy hosts — whose crash sends them to the reduced-geometry
        relaunch alongside the quarantined peer).  The verdict is
        derived from identically-gathered check records, so every host
        grades the same round the same way."""
        import json

        import jax

        t = self.trainer
        t.stats["sdc_quarantines"] += 1
        self.record("sdc_quarantine", replicas=minority, step=gstep)
        t.flight.dump("sdc_quarantine",
                      extra={"replicas": minority, "step": gstep})
        proc = jax.process_index()
        mine = [k for k in minority if k.split("/")[0] == f"p{proc}"]
        if mine or not self._multihost:
            marker = os.path.join(self.policy.checkpoint_dir,
                                  QUARANTINE_MARKER)
            with open(marker, "w") as f:
                json.dump({"replicas": minority, "step": gstep,
                           "devices": sorted(devices or []),
                           "host": proc}, f)
        t.log(f"[tpudp] resilience: SDC on replica(s) {minority} recurred "
              f"after a bit-exact replay (step {gstep}) — persistent bad "
              "chip; quarantining for reduced-geometry relaunch")
        if self._multihost and mine:
            os._exit(SDC_QUARANTINE_EXIT)
        raise SdcPersistentError(
            f"replica(s) {minority} diverged again after a bit-exact "
            f"replay at step {gstep} — persistent silent corruption; "
            "host quarantined", replica=minority)

    def guard_batches(self, loader, epoch: int, base):
        """Wrap one epoch's batch iterator with loader containment: an
        exception out of ``next()`` (the Prefetcher re-raises its worker's
        exceptions there) restarts the pipeline and replays the already-
        consumed draws, so the batch sequence — and every host-side RNG
        draw behind it — is unchanged.  Bounded per epoch."""
        t = self.trainer
        beat = t.watchdog.beat if t.watchdog is not None else (lambda: None)
        it, consumed, replay, restarts = base, 0, 0, 0
        while True:
            try:
                item = next(it)
            except StopIteration:
                return
            except StepHangError:
                raise  # the watchdog's signal, not a loader fault
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                restarts += 1
                if restarts > self.policy.max_loader_restarts:
                    self.record("loader_escalation", epoch=epoch,
                                restarts=restarts - 1, error=repr(e))
                    raise ResilienceExhausted(
                        f"loader failed {restarts} times in epoch {epoch}",
                        e) from e
                self.trainer.stats["loader_restarts"] += 1
                self.record("loader_restart", epoch=epoch, offset=consumed,
                            error=repr(e))
                t.log(f"[tpudp] resilience: loader failed at batch "
                      f"{consumed} of epoch {epoch} ({e!r}); restarting "
                      "the pipeline and replaying to the exact offset")
                if hasattr(it, "close"):
                    it.close()  # generator close -> Prefetcher stop event
                if hasattr(loader, "set_epoch"):
                    loader.set_epoch(epoch)
                it = iter(loader)
                replay = consumed
                continue
            if replay:
                replay -= 1  # discarded re-draw: host RNG replays
                beat()
                continue
            consumed += 1
            yield item

    # -- recovery paths -------------------------------------------------
    def _resume_position(self) -> tuple[int, int]:
        step = int(self.trainer.state.step)
        return step // self._per_epoch, step % self._per_epoch

    def _restore_verified(self):
        from tpudp.utils.checkpoint import restore_latest_verified

        if self.policy.checkpoint_writer is not None:
            # Drain any in-flight async epoch-end save first (mirrors the
            # dump path): a half-materialized newest dir would otherwise
            # be misread as corrupt, spuriously falling back (and
            # replaying) one epoch further than necessary.
            self.policy.checkpoint_writer.wait()
        state, path, skipped = restore_latest_verified(
            self.policy.checkpoint_dir, self.trainer.state,
            log=self.trainer.log)
        self.trainer.stats["ckpt_fallbacks"] += len(skipped)
        for p, reason in skipped:
            self.record("ckpt_fallback", rejected=p, reason=reason)
        self.trainer.state = state
        return path

    def _rollback(self, e: BaseException) -> tuple[int, int]:
        # Black box FIRST: the ring's tail is the window timeline that
        # led to the divergence — dump before the restore overwrites
        # any live context (no-op without a flight dir).
        self.trainer.flight.dump("rollback", extra={"error": repr(e)[:500]})
        stats = self.trainer.stats
        if stats["rollbacks"] >= self.policy.max_rollbacks:
            self.record("rollback_escalation", error=repr(e),
                        rollbacks=stats["rollbacks"])
            self.trainer.log(
                f"[tpudp] resilience: rollback budget "
                f"({self.policy.max_rollbacks}) exhausted; escalating")
            raise e  # escalate with the ORIGINAL error
        stats["rollbacks"] += 1
        path = self._restore_verified()
        self._window_losses.clear()
        if self.trainer.watchdog is not None:
            self.trainer.watchdog.arm()
        epoch, skip = self._resume_position()
        self.record("rollback", error=repr(e), restored=path,
                    step=int(self.trainer.state.step), epoch=epoch,
                    skip=skip)
        self.trainer.log(
            f"[tpudp] resilience: {type(e).__name__} ({e}); rolled back to "
            f"{path} (epoch {epoch}, {skip} batches in) and replaying")
        return epoch, skip

    def _step_recover(self, e: BaseException) -> tuple[int, int]:
        from tpudp.utils.checkpoint import restore_checkpoint

        t, stats = self.trainer, self.trainer.stats
        t.flight.dump("step_fault"
                      if not isinstance(e, StepHangError) else "hang",
                      extra={"error": repr(e)[:500]})
        try:
            failed_step = int(t.state.step)
        except Exception:
            failed_step = None  # donated/invalid buffers
        if failed_step is not None and failed_step == self._last_failed_step:
            self._consecutive_at_step += 1
        else:
            self._consecutive_at_step = 1
        self._last_failed_step = failed_step
        if self._consecutive_at_step > self.policy.max_step_retries:
            self.record("step_escalation", error=repr(e), step=failed_step,
                        consecutive=self._consecutive_at_step)
            t.log(f"[tpudp] resilience: step {failed_step} failed "
                  f"{self._consecutive_at_step} consecutive times; "
                  "escalating")
            raise e  # escalate with the ORIGINAL error
        stats["step_retries"] += 1
        # The existing emergency-dump path, then restore IN-PROCESS (what
        # cli.py previously achieved only through a full relaunch).  The
        # dump doubles as validation that the live state is fetchable; a
        # donated/invalid state fails here and we fall back to the newest
        # verified checkpoint instead.
        dump = make_emergency_dump(
            self.policy.checkpoint_dir, lambda: t.state, self._per_epoch,
            async_writer=self.policy.checkpoint_writer, log=t.log)
        restored_from = None
        try:
            dump()
            emerg = os.path.join(self.policy.checkpoint_dir, "emergency")
            t.state = restore_checkpoint(emerg, t.state, verify=True)
            restored_from = emerg
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as dump_err:
            t.log(f"[tpudp] resilience: emergency dump/restore failed "
                  f"({dump_err!r}); falling back to the newest verified "
                  "checkpoint")
            # tpudp: lint-ok(protocol-order-divergence): single-host
            # path by construction — Supervisor.run routes every
            # multihost fault through _vote/_coordinated_recover, so
            # the dump-vs-fallback arms here never run on a pod and
            # their "collectives" degenerate to process_count()==1
            # identities.
            restored_from = self._restore_verified()
        else:
            # Consume the dump (mirrors cli resume): recovery succeeded
            # in-process, so a LATER relaunch must resume from the step
            # series (which only advances), not this now-stale snapshot.
            # Outside the try: a housekeeping failure here must never
            # discard the restore that just succeeded.
            try:
                from tpudp.utils.checkpoint import consume_emergency

                consume_emergency(self.policy.checkpoint_dir)
            except OSError as e:
                t.log(f"[tpudp] WARNING: could not consume emergency "
                      f"dump after recovery: {e}")
        if t.watchdog is not None:
            t.watchdog.arm()  # clears a recorded hang; re-arms monitoring
        epoch, skip = self._resume_position()
        self.record("step_retry", error=repr(e), step=failed_step,
                    hang=isinstance(e, StepHangError),
                    restored=restored_from, epoch=epoch, skip=skip)
        t.log(f"[tpudp] resilience: {type(e).__name__} ({e}); restored "
              f"{restored_from} and continuing in-process at epoch "
              f"{epoch}, {skip} batches in")
        return epoch, skip

    # -- multi-host agreement protocol ---------------------------------
    def _vote(self, code: int) -> int:
        """One round of the agreement protocol: allgather this host's
        outcome ``code`` (plus a protocol sequence number) and reduce to
        the worst severity — the ONE action every host executes.

        The wait is BOUNDED by ``policy.vote_timeout_s``: a peer that
        never joins (SIGKILLed worker) or a collective that errors out
        (torn TCP to a dead peer) means in-process recovery is
        impossible, and the host hard-exits with
        :data:`VOTE_TIMEOUT_EXIT` so the scheduler relaunches the pod
        into the coordinated resume path — the vote-layer mirror of the
        CLI watchdog's hard-exit backstop, which keeps covering hosts
        wedged inside a DEVICE collective (those never reach a vote)."""
        if not self._multihost:
            return code
        import threading

        self._vote_seq += 1
        seq, result = self._vote_seq, {}

        def gather() -> None:
            try:
                import jax.numpy as jnp
                import numpy as np
                from jax.experimental import multihost_utils

                # tpudp: lint-ok(protocol-divergent-entry): the except
                # arm IS the bounded-vote mitigation this verifier
                # demands elsewhere — a collective that fails locally
                # (torn TCP, dead peer) is converted to a vote-timeout
                # verdict and a hard exit (43), and any peer still
                # inside the gather times out the same way; nobody is
                # left waiting on this host's rendezvous.
                flags = np.asarray(multihost_utils.process_allgather(
                    jnp.asarray([code, seq], jnp.int32)))
                result["codes"] = [int(c) for c in flags[:, 0]]
                result["seqs"] = [int(s) for s in flags[:, 1]]
            except BaseException as e:  # gloo/XLA surface various types
                result["error"] = e

        th = threading.Thread(target=gather, daemon=True,
                              name="tpudp-recovery-vote")
        th.start()
        th.join(self.policy.vote_timeout_s)
        if "codes" not in result:
            why = (f"vote collective failed: {result['error']!r}"
                   if "error" in result else
                   f"no peer joined within {self.policy.vote_timeout_s}s")
            self.record("vote_timeout", outcome=OUTCOME_NAMES.get(code),
                        seq=seq, reason=why)
            self.trainer.log(
                f"[tpudp] resilience: recovery vote {seq} got no answer "
                f"({why}); peer host dead or wedged — hard-exiting for "
                f"scheduler relaunch (exit {VOTE_TIMEOUT_EXIT})")
            # The killed-host black box: this process is about to
            # disappear (exit 43) BECAUSE a peer died — the local dump
            # is the only surviving timeline of what this host saw, and
            # it must be strictly local (the dead peer can never be a
            # dependency of its own post-mortem).
            self.trainer.flight.dump("vote_timeout", extra={
                "seq": seq, "reason": why,
                "outcome": OUTCOME_NAMES.get(code)})
            os._exit(VOTE_TIMEOUT_EXIT)
        if any(s != seq for s in result["seqs"]):
            # Hosts disagree about WHICH decision this is — the protocol
            # itself desynced (e.g. one host recovered locally where
            # another voted).  Continuing would pair future votes with
            # the wrong decisions; relaunching resumes coordinated.
            self.record("vote_desync", seq=seq, seqs=result["seqs"])
            self.trainer.log(
                f"[tpudp] resilience: vote sequence desync (local {seq}, "
                f"peers {result['seqs']}); hard-exiting for scheduler "
                f"relaunch (exit {VOTE_TIMEOUT_EXIT})")
            self.trainer.flight.dump("vote_desync", extra={
                "seq": seq, "peer_seqs": result["seqs"]})
            os._exit(VOTE_TIMEOUT_EXIT)
        worst = reduce_outcomes(result["codes"])
        self.record("vote", seq=seq, outcome=OUTCOME_NAMES.get(code),
                    worst=OUTCOME_NAMES.get(worst), codes=result["codes"])
        return worst

    def _assert_replicas_agree(self) -> None:
        """Post-restore assertion (multi-host): every host must agree on
        the restored state's fingerprint BEFORE training resumes —
        replicas that restored different bytes would train a model that
        belongs to nobody and deadlock or silently desync the next
        collectives.  Raises ReplicaDivergenceError (typed, from
        tpudp/utils/consistency.py); single-host is a no-op."""
        if not self._multihost:
            return
        from tpudp.utils.consistency import verify_across_processes

        verify_across_processes({"state": self.trainer.state})

    def _coordinated_recover(self, worst: int,
                             e: BaseException | None) -> tuple[int, int]:
        """Execute the voted recovery action on EVERY host: restore the
        newest checkpoint all hosts verify (the coordinated walk) and
        replay.  ``e`` is this host's local error (None on a host that
        voted OK and merely learned of a peer's fault).  Same budgets and
        escalation semantics as the single-host paths — the counters
        advance in lockstep on all hosts (every host executes every
        coordinated recovery), so escalation fires on all hosts in the
        same round."""
        t, stats = self.trainer, self.trainer.stats
        original = e if e is not None else RuntimeError(
            "a peer host faulted; this host joined the coordinated "
            "recovery")
        # Every host banks its local black box for the voted recovery
        # (each host's timeline differs — only one actually faulted).
        t.flight.dump("coordinated_" + str(OUTCOME_NAMES.get(worst)),
                      extra={"error": repr(original)[:500],
                             "worst": OUTCOME_NAMES.get(worst)})
        if worst == OUTCOME_DIVERGENCE:
            if stats["rollbacks"] >= self.policy.max_rollbacks:
                self.record("rollback_escalation", error=repr(original),
                            rollbacks=stats["rollbacks"])
                t.log(f"[tpudp] resilience: rollback budget "
                      f"({self.policy.max_rollbacks}) exhausted; escalating")
                raise original
            stats["rollbacks"] += 1
        else:
            try:
                failed_step = int(t.state.step)
            except Exception:
                failed_step = None  # donated/invalid buffers
            if (failed_step is not None
                    and failed_step == self._last_failed_step):
                self._consecutive_at_step += 1
            else:
                self._consecutive_at_step = 1
            self._last_failed_step = failed_step
            if self._consecutive_at_step > self.policy.max_step_retries:
                self.record("step_escalation", error=repr(original),
                            step=failed_step,
                            consecutive=self._consecutive_at_step)
                t.log(f"[tpudp] resilience: step {failed_step} failed "
                      f"{self._consecutive_at_step} consecutive times; "
                      "escalating")
                raise original
            stats["step_retries"] += 1
        path = self._restore_verified()
        self._window_losses.clear()
        if t.watchdog is not None:
            t.watchdog.arm()
        self._assert_replicas_agree()
        if t.flight.enabled:
            # Every host that reaches here is live (it just voted and
            # restored), so the gather_host_values round inside
            # coordinated_merge is safe — rank 0 folds the per-host
            # dumps into one flightrec-merged.json.  Outside every hot
            # path by construction (we are mid-recovery).
            from tpudp.obs import coordinated_merge

            coordinated_merge(t.flight.directory)
        epoch, skip = self._resume_position()
        if worst == OUTCOME_DIVERGENCE:
            self.record("rollback", error=repr(original), restored=path,
                        step=int(t.state.step), epoch=epoch, skip=skip,
                        coordinated=True)
        else:
            self.record("step_retry", error=repr(original),
                        step=self._last_failed_step,
                        hang=worst == OUTCOME_HANG, restored=path,
                        epoch=epoch, skip=skip, coordinated=True)
        t.log(f"[tpudp] resilience: coordinated "
              f"{OUTCOME_NAMES.get(worst)} recovery "
              f"({type(original).__name__}: {original}); all hosts "
              f"restored {path} (epoch {epoch}, {skip} batches in) and "
              "replaying")
        return epoch, skip

    # -- the supervision loop ------------------------------------------
    def _ensure_initial_checkpoint(self, start_epoch: int,
                                   skip_first: int) -> None:
        """A rollback needs a restore target even before the first epoch
        checkpoint lands: save ``step_<start_epoch>`` of the initial state
        if the series is empty.  Skipped on a mid-epoch resume (the state
        would not be an epoch boundary, and the step_N series' name
        contract is 'state after epoch N').  The is-the-series-empty
        probe is COORDINATED: a multi-host save is collective, so one
        host deciding to save off a stale listing while its peer skips
        would park it alone in the commit barrier."""
        from tpudp.utils.checkpoint import (coordinated_any,
                                            latest_step_dir,
                                            save_checkpoint)

        if skip_first or coordinated_any(
                latest_step_dir(self.policy.checkpoint_dir) is not None):
            return
        path = os.path.join(self.policy.checkpoint_dir,
                            f"step_{start_epoch}")
        save_checkpoint(path, self.trainer.state)
        self.record("initial_checkpoint", path=path)

    def run(self, train_loader, test_loader, epochs: int, start_epoch: int,
            epoch_end_fn, skip_first: int) -> None:
        t = self.trainer
        self._per_epoch = len(train_loader)
        # Highest epoch whose epoch-end hook COMPLETED: a fault during
        # eval or the hook itself resumes at the NEXT epoch boundary
        # (state.step is already there), which would silently skip the
        # missed hook — and with it the epoch's checkpoint save.  The
        # loop below replays it before re-entering _fit.
        self._epoch_end_done = start_epoch - 1

        def epoch_end(epoch: int) -> None:
            if epoch_end_fn is not None:
                epoch_end_fn(epoch)
            if self.policy.save_epoch_checkpoints:
                from tpudp.utils.checkpoint import save_checkpoint

                save_checkpoint(
                    os.path.join(self.policy.checkpoint_dir,
                                 f"step_{epoch + 1}"), t.state)
            self._epoch_end_done = max(self._epoch_end_done, epoch)

        self._ensure_initial_checkpoint(start_epoch, skip_first)
        t._resilience = self
        if t.watchdog is not None:
            t.watchdog.arm()
        cur_start, cur_skip = start_epoch, skip_first
        try:
            while True:
                try:
                    missed = cur_start - 1
                    if (cur_skip == 0 and start_epoch <= missed
                            and missed > self._epoch_end_done):
                        # Recovery landed on an epoch boundary whose tail
                        # (eval + epoch-end hook) never completed: replay
                        # it, inside the try so a repeated failure goes
                        # through the same recovery/escalation machinery
                        # (state.step is unchanged through the tail, so a
                        # second failure there escalates as same-step).
                        if test_loader is not None:
                            t.evaluate(test_loader, epoch=missed)
                        epoch_end(missed)
                    t._fit(train_loader, test_loader, epochs, cur_start,
                           epoch_end, cur_skip)
                    if self._multihost:
                        # Completion vote: a host that finished cleanly
                        # parks here, so a peer faulting in the final
                        # stretch finds a vote partner instead of a
                        # departed process — and if the vote carries a
                        # fault, this host joins the coordinated
                        # recovery and replays alongside its peers.
                        worst = self._vote(OUTCOME_OK)
                        if worst != OUTCOME_OK:
                            cur_start, cur_skip = \
                                self._coordinated_recover(worst, None)
                            continue
                    return
                except ResilienceExhausted as e:
                    # tpudp: lint-ok(protocol-early-exit): escalation
                    # fires on EVERY host in the same protocol round —
                    # recovery budgets advance in lockstep (each host
                    # executes each coordinated recovery), so when one
                    # host escalates instead of re-entering the vote
                    # loop, all of them do.
                    raise e.original from e
                except (KeyboardInterrupt, SystemExit):
                    raise
                except SdcPersistentError:
                    # The quarantine verdict is computed from
                    # identically-gathered fingerprints, so every host
                    # leaves the vote loop in the same round (the named
                    # chip's host already hard-exited
                    # SDC_QUARANTINE_EXIT); the crash routes survivors
                    # to the reduced-geometry relaunch.
                    raise
                except (FloatingPointError, LossSpikeError,
                        SdcDetected) as e:
                    if self._multihost:
                        # tpudp: lint-ok(divergent-collective): this vote
                        # IS the mitigation the rule demands — every host
                        # reaches a vote each protocol round (clean
                        # finishers park at a completion vote, §_vote)
                        # and the gather is bounded (vote_timeout_s →
                        # VOTE_TIMEOUT_EXIT), so a lone voter exits
                        # instead of hanging the rendezvous.
                        # The multihost arms of this try all issue the
                        # same [vote, coordinated-recover] label
                        # sequence (worst-severity-wins re-unifies
                        # faulters and parked finishers), so the
                        # verifier compares them equal; what it still
                        # flags is the SINGLE-HOST sub-arm below, whose
                        # "collectives" degenerate to
                        # process_count()==1 identities.
                        cur_start, cur_skip = self._coordinated_recover(
                            self._vote(OUTCOME_DIVERGENCE), e)  # tpudp: lint-ok(divergent-collective): bounded vote (see above)
                    else:
                        # tpudp: lint-ok(protocol-order-divergence):
                        # single-host arm of the uniform
                        # `self._multihost` fork — no pod, no
                        # rendezvous; the restore-walk "collectives"
                        # inside _rollback are process_count()==1
                        # identities.
                        cur_start, cur_skip = self._rollback(e)
                except Exception as e:
                    if self._multihost:
                        code = (OUTCOME_HANG if isinstance(e, StepHangError)
                                else OUTCOME_STEP_FAULT)
                        # tpudp: lint-ok(divergent-collective): bounded
                        # vote — same protocol as the divergence arm.
                        cur_start, cur_skip = self._coordinated_recover(
                            self._vote(code), e)  # tpudp: lint-ok(divergent-collective): bounded vote (see the divergence arm)
                    else:
                        # tpudp: lint-ok(protocol-order-divergence):
                        # single-host arm, same as the divergence arm's.
                        cur_start, cur_skip = self._step_recover(e)
        finally:
            t._resilience = None
            if t.watchdog is not None:
                t.watchdog.disarm()

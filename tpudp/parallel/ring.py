"""Hand-rolled ring all-reduce built from ``lax.ppermute``.

North-star requirement (BASELINE.json configs[2]): the reference's "implement
the collective yourself" rung — Part 2a does gather→mean→scatter through rank 0
(``src/Part 2a/main.py:117-127``) — re-expressed as the bandwidth-optimal ring
algorithm on the TPU ICI torus: a reduce-scatter phase (N-1 steps, each device
ends owning one fully-reduced chunk) followed by an all-gather phase (N-1
steps circulating the reduced chunks).

TPU-first design notes:
  * One flat, padded buffer for the whole gradient pytree instead of the
    reference's per-parameter collectives (22 sequential collectives per step,
    SURVEY.md §3.2) — per-step latency is O(bytes/bandwidth + N·hop), not
    O(num_params · latency).  This is the "bucketing" that torch DDP does in
    C++, obtained here structurally.
  * Static Python loop over ring steps: N is known at trace time, so XLA sees
    a straight-line schedule of ppermutes it can pipeline; chunk indices are
    traced values derived from ``lax.axis_index``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _ring_perm(n: int) -> list[tuple[int, int]]:
    return [(i, (i + 1) % n) for i in range(n)]


def ring_all_reduce(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Sum ``x`` over ``axis_name`` with an explicit ppermute ring.

    Must be called inside ``shard_map``/``pmap``.  Works for any shape; the
    flat buffer is zero-padded to a multiple of the axis size (the
    "non-divisible tensor sizes" hard part from SURVEY.md §7).
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
    chunks = flat.reshape(n, -1)  # chunk c = chunks[c]
    i = lax.axis_index(axis_name)
    perm = _ring_perm(n)

    # Reduce-scatter: after step s, the chunk received from the left neighbor
    # has been partially reduced by s+1 devices.  After N-1 steps device i
    # owns the fully-reduced chunk (i+1) mod N.
    acc = chunks
    for s in range(n - 1):
        send_idx = (i - s) % n
        sent = jnp.take(acc, send_idx, axis=0)
        recv = lax.ppermute(sent, axis_name, perm)
        recv_idx = (i - s - 1) % n
        acc = acc.at[recv_idx].add(recv)
    own_idx = (i + 1) % n
    own = jnp.take(acc, own_idx, axis=0)

    # All-gather: circulate the reduced chunks around the ring.
    out = jnp.zeros_like(chunks)
    out = out.at[own_idx].set(own)
    cur = own
    for s in range(n - 1):
        cur = lax.ppermute(cur, axis_name, perm)
        arrived_idx = (i - s) % n  # left neighbor owned (i-1)+1 = i, then i-1, ...
        out = out.at[arrived_idx].set(cur)

    flat_out = out.reshape(-1)
    if pad:
        flat_out = flat_out[: flat.size - pad]
    return flat_out.reshape(shape)


def flatten_tree(tree, dtype=None):
    """Pack a pytree into ONE flat vector; returns ``(flat, unflatten)``.

    ``unflatten(vec)`` slices ``vec`` back into the original
    shapes/structure, casting each leaf to its original dtype.  The shared
    packing used by the ring collective, the int8 sync rung, and the
    error-feedback compressor — one place for the slice bookkeeping."""
    leaves, treedef = jax.tree.flatten(tree)
    sizes = [leaf.size for leaf in leaves]
    shapes = [leaf.shape for leaf in leaves]
    dtypes = [leaf.dtype for leaf in leaves]
    flat = jnp.concatenate([
        leaf.reshape(-1) if dtype is None else leaf.reshape(-1).astype(dtype)
        for leaf in leaves])

    def unflatten(vec, cast: bool = True):
        out, offset = [], 0
        for size, shape, dt in zip(sizes, shapes, dtypes):
            leaf = lax.dynamic_slice_in_dim(vec, offset, size).reshape(shape)
            out.append(leaf.astype(dt) if cast else leaf)
            offset += size
        return jax.tree.unflatten(treedef, out)

    return flat, unflatten


def int8_headroom_quantize(flat, axis_name: str):
    """Quantize a flat fp32 buffer onto an int8 grid safe to ring-sum.

    The single source of the wraparound invariant shared by the stateless
    int8 sync rung and the error-feedback compressor: quantized values are
    clipped to ``+/-(127 // N)``, so the worst-case ring partial sum — N
    devices all at the clip bound with the same sign — is
    ``N * (127 // N) <= 127``, strictly inside int8.  Clipping at the
    QUANTIZED level is what provides the guarantee: with plain round, N
    near-identical max-magnitude values each rounding 127/N up (e.g.
    round(63.5) = 64 at N=2) sum to 128 and wrap to -128, sign-flipping
    the largest element (round-2 advisor finding).

    Returns ``(q, unit)``: ``q`` int8 with ``|q| <= 127 // N``, and
    ``unit`` (one grid tick in ``flat``'s units, an fp32 scalar shared by
    every device via ``pmax``) such that ``q * unit ~= flat`` and a ring
    TOTAL dequantizes as ``total * unit``.  Effective precision is
    ``log2(127 // N)`` bits of the buffer's max-abs.
    """
    n = lax.axis_size(axis_name)
    qmax = 127 // n
    if qmax < 1:
        # 127 // n == 0 would make unit a divide-by-zero: every gradient
        # silently NaN.  Fail loudly at trace time (n is static).
        raise ValueError(
            f"int8 ring compression supports at most 127 devices along the "
            f"reduce axis (got {n}): the +/-(127 // N) headroom grid is "
            f"empty — use allreduce_bf16 or shard the axis")
    maxabs = lax.pmax(jnp.maximum(jnp.max(jnp.abs(flat)), 1e-30), axis_name)
    unit = maxabs / qmax
    q = jnp.clip(jnp.round(flat / unit), -qmax, qmax).astype(jnp.int8)
    return q, unit


def ring_all_reduce_mean(tree, axis_name: str):
    """Mean-reduce a gradient pytree over the ring as ONE flat buffer."""
    n = lax.axis_size(axis_name)
    flat, unflatten = flatten_tree(tree)
    mean = ring_all_reduce(flat, axis_name) / n
    return unflatten(mean, cast=False)

"""Hand-rolled ring all-reduce built from ``lax.ppermute``.

North-star requirement (BASELINE.json configs[2]): the reference's "implement
the collective yourself" rung — Part 2a does gather→mean→scatter through rank 0
(``src/Part 2a/main.py:117-127``) — re-expressed as the bandwidth-optimal ring
algorithm on the TPU ICI torus: a reduce-scatter phase (N-1 steps, each device
ends owning one fully-reduced chunk) followed by an all-gather phase (N-1
steps circulating the reduced chunks).

TPU-first design notes:
  * One flat, padded buffer for the whole gradient pytree instead of the
    reference's per-parameter collectives (22 sequential collectives per step,
    SURVEY.md §3.2) — per-step latency is O(bytes/bandwidth + N·hop), not
    O(num_params · latency).  This is the "bucketing" that torch DDP does in
    C++, obtained here structurally.
  * Static Python loop over ring steps: N is known at trace time, so XLA sees
    a straight-line schedule of ppermutes it can pipeline; chunk indices are
    traced values derived from ``lax.axis_index``.
  * Single-direction by default — a MEASURED decision (round-3 VERDICT #5):
    on every mesh this repo has timed (BASELINE.md "gradient-collective
    sweep": uni 698 ms vs bidirectional 1091 ms on the 8-device simulated
    mesh; psum 147 ms) the single ring wins, because the bidirectional
    schedule doubles the collective-permute dispatch count
    (tools/ring_hlo_evidence.py counts the compiled HLO ops) and on a
    non-torus transport the halved per-message payload buys nothing back.
  * ``bidirectional=True`` remains selectable (the ``ring_bidir`` sync
    rung): two counter-rotating half-buffers whose ppermutes are
    data-independent, so on a REAL TPU torus — where each ICI link carries
    traffic both directions at once — per-step payload halves.  That is a
    hypothesis this host cannot test (1 real chip; collectives compile to
    no-ops): benchmarks/collective_bench.py records the head-to-head the
    moment a multi-chip window exists, and the default should follow the
    data then too.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _ring_perm(n: int, sign: int = 1) -> list[tuple[int, int]]:
    """Neighbor map for the ring: ``sign=+1`` clockwise (i -> i+1),
    ``sign=-1`` counter-clockwise (i -> i-1)."""
    return [(i, (i + sign) % n) for i in range(n)]


def ring_all_reduce(x: jnp.ndarray, axis_name: str, *,
                    bidirectional: bool = False) -> jnp.ndarray:
    """Sum ``x`` over ``axis_name`` with an explicit ppermute ring.

    Must be called inside ``shard_map``/``pmap``.  Works for any shape; the
    flat buffer is zero-padded to a multiple of ``directions * axis size``
    (the "non-divisible tensor sizes" hard part from SURVEY.md §7).

    ``bidirectional=False`` (default) is the textbook single-direction
    schedule — the faster one on every mesh measured so far (see the
    module docstring).  ``True`` splits the buffer into two
    counter-rotating halves — still 2(N-1) ring steps, but each step moves
    two independent half-size messages the compiler can overlap (both ICI
    directions of a TPU torus); selectable pending real multi-chip data.
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    shape, dtype = x.shape, x.dtype
    ndir = 2 if bidirectional else 1
    flat = x.reshape(-1)
    pad = (-flat.size) % (ndir * n)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
    # parts[d] is direction d's (n, chunk) buffer; chunk c = parts[d][c].
    parts = list(flat.reshape(ndir, n, -1))
    i = lax.axis_index(axis_name)
    # Direction d sends to neighbor (i + sign_d); the reduce-scatter /
    # all-gather index walk mirrors with the sign.
    signs = (1, -1)[:ndir]
    perms = [_ring_perm(n, s) for s in signs]

    # Reduce-scatter: after step s, the chunk received from the upstream
    # neighbor has been partially reduced by s+1 devices.  After N-1 steps
    # device i owns direction d's fully-reduced chunk (i + sign_d) mod N.
    # The two directions' ppermutes are interleaved per step and share no
    # data — XLA is free to issue them concurrently.
    for s in range(n - 1):
        for d, (sign, perm) in enumerate(zip(signs, perms)):
            send_idx = (i - sign * s) % n
            sent = jnp.take(parts[d], send_idx, axis=0)
            recv = lax.ppermute(sent, axis_name, perm)
            recv_idx = (i - sign * (s + 1)) % n
            parts[d] = parts[d].at[recv_idx].add(recv)
    owns = [jnp.take(parts[d], (i + sign) % n, axis=0)
            for d, sign in enumerate(signs)]

    # All-gather: circulate the reduced chunks around each ring.
    outs = [jnp.zeros_like(parts[d]).at[(i + sign) % n].set(owns[d])
            for d, sign in enumerate(signs)]
    curs = list(owns)
    for s in range(n - 1):
        for d, (sign, perm) in enumerate(zip(signs, perms)):
            curs[d] = lax.ppermute(curs[d], axis_name, perm)
            arrived_idx = (i - sign * s) % n  # upstream owned (i-sign)+sign
            outs[d] = outs[d].at[arrived_idx].set(curs[d])

    flat_out = jnp.stack(outs).reshape(-1)
    if pad:
        flat_out = flat_out[: flat.size - pad]
    return flat_out.reshape(shape)


def hd_all_reduce(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Sum ``x`` over ``axis_name`` by recursive halving + doubling
    (Rabenseifner): reduce-scatter via log2(N) pairwise exchanges that
    halve the live payload each step, then all-gather by the mirror
    doubling walk.

    Same per-device wire bytes as the ring — ``2*(1-1/N)*payload``, the
    bandwidth-optimal bound — but ``2*log2(N)`` serial steps instead of
    ``2*(N-1)``: the schedule of choice when per-step latency/dispatch
    dominates (small payloads, or the simulated CPU mesh where every hop
    is a full cross-"device" barrier).  The trade: partners are at
    hypercube distances N/2, N/4, ... — neighbor hops on a hypercube but
    multi-hop routes on a TPU torus, where the bidirectional ring's
    neighbor-only traffic is the better fit for large payloads.

    Requires a power-of-two axis size (falls back to the default
    single-direction ring otherwise).
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    if n & (n - 1):
        import warnings

        warnings.warn(
            f"hd_all_reduce needs a power-of-two axis size (got {n}); "
            "falling back to the single-direction ring (the measured "
            "ring_all_reduce default) — timings labeled 'hd' on this "
            "mesh measure the ring schedule",
            stacklevel=2)
        return ring_all_reduce(x, axis_name)
    levels = n.bit_length() - 1
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
    i = lax.axis_index(axis_name)

    # Reduce-scatter, halving: at level k the live buffer (chunks whose
    # top-k index bits match i's) splits in two; keep the half whose next
    # bit matches i's, swap the other with the partner at distance
    # n >> (k+1), and add.  After all levels device i holds chunk i fully
    # reduced.  Chunk order is the natural binary order, so every half is
    # contiguous and no gather/scatter indexing is needed.
    live = flat
    for k in range(levels):
        d = n >> (k + 1)
        perm = [(j, j ^ d) for j in range(n)]
        halves = live.reshape(2, -1)
        mybit = (i >> (levels - 1 - k)) & 1
        keep = jnp.take(halves, mybit, axis=0)
        send = jnp.take(halves, 1 - mybit, axis=0)
        recv = lax.ppermute(send, axis_name, perm)
        live = keep + recv

    # All-gather, doubling: mirror walk; my half sits at position mybit,
    # the partner's at the other — both[h ^ mybit] is the half with top
    # bit h.
    for k in reversed(range(levels)):
        d = n >> (k + 1)
        perm = [(j, j ^ d) for j in range(n)]
        recv = lax.ppermute(live, axis_name, perm)
        mybit = (i >> (levels - 1 - k)) & 1
        both = jnp.stack([live, recv])
        live = jnp.take(both, jnp.array([0, 1]) ^ mybit,
                        axis=0).reshape(-1)

    if pad:
        live = live[: flat.size - pad]
    return live.reshape(shape)


def a2a_all_reduce(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Sum ``x`` over ``axis_name`` as reduce-scatter + all-gather, with
    the reduce-scatter built from ``all_to_all`` + a local sum.

    The third manual schedule: the REDUCTION is still hand-written (each
    device sums the N chunk-rows it receives), but the byte movement rides
    two of XLA's primitive collectives instead of 2(N-1) ppermute rounds —
    per-device wire bytes are the same bandwidth-optimal ``2*(1-1/N)*p``
    as the ring, in two dispatches.  Where the per-hop path is the
    bottleneck (the simulated CPU mesh; latency-bound small payloads) this
    is the fastest manual flavor; the ring keeps the advantage of
    neighbor-only traffic on a torus.
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
    chunks = flat.reshape(n, -1)
    # all_to_all: device i ends up with row j = device j's chunk i.
    rows = lax.all_to_all(chunks, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)
    own = jnp.sum(rows, axis=0)  # the manual reduction
    flat_out = lax.all_gather(own, axis_name, tiled=True)
    if pad:
        flat_out = flat_out[: flat.size - pad]
    return flat_out.reshape(shape)


def flatten_tree(tree, dtype=None):
    """Pack a pytree into ONE flat vector; returns ``(flat, unflatten)``.

    ``unflatten(vec)`` slices ``vec`` back into the original
    shapes/structure, casting each leaf to its original dtype.  The shared
    packing used by the ring collective, the int8 sync rung, and the
    error-feedback compressor — one place for the slice bookkeeping."""
    leaves, treedef = jax.tree.flatten(tree)
    sizes = [leaf.size for leaf in leaves]
    shapes = [leaf.shape for leaf in leaves]
    dtypes = [leaf.dtype for leaf in leaves]
    flat = jnp.concatenate([
        leaf.reshape(-1) if dtype is None else leaf.reshape(-1).astype(dtype)
        for leaf in leaves])

    def unflatten(vec, cast: bool = True):
        out, offset = [], 0
        for size, shape, dt in zip(sizes, shapes, dtypes):
            leaf = lax.dynamic_slice_in_dim(vec, offset, size).reshape(shape)
            out.append(leaf.astype(dt) if cast else leaf)
            offset += size
        return jax.tree.unflatten(treedef, out)

    return flat, unflatten


def int8_headroom_quantize(flat, axis_name: str):
    """Quantize a flat fp32 buffer onto an int8 grid safe to ring-sum.

    The single source of the wraparound invariant shared by the stateless
    int8 sync rung and the error-feedback compressor: quantized values are
    clipped to ``+/-(127 // N)``, so the worst-case ring partial sum — N
    devices all at the clip bound with the same sign — is
    ``N * (127 // N) <= 127``, strictly inside int8.  Clipping at the
    QUANTIZED level is what provides the guarantee: with plain round, N
    near-identical max-magnitude values each rounding 127/N up (e.g.
    round(63.5) = 64 at N=2) sum to 128 and wrap to -128, sign-flipping
    the largest element (round-2 advisor finding).

    Returns ``(q, unit)``: ``q`` int8 with ``|q| <= 127 // N``, and
    ``unit`` (one grid tick in ``flat``'s units, an fp32 scalar shared by
    every device via ``pmax``) such that ``q * unit ~= flat`` and a ring
    TOTAL dequantizes as ``total * unit``.  Effective precision is
    ``log2(127 // N)`` bits of the buffer's max-abs.
    """
    n = lax.axis_size(axis_name)
    qmax = 127 // n
    if qmax < 1:
        # 127 // n == 0 would make unit a divide-by-zero: every gradient
        # silently NaN.  Fail loudly at trace time (n is static).
        raise ValueError(
            f"int8 ring compression supports at most 127 devices along the "
            f"reduce axis (got {n}): the +/-(127 // N) headroom grid is "
            f"empty — use allreduce_bf16 or shard the axis")
    maxabs = lax.pmax(jnp.maximum(jnp.max(jnp.abs(flat)), 1e-30), axis_name)
    unit = maxabs / qmax
    q = jnp.clip(jnp.round(flat / unit), -qmax, qmax).astype(jnp.int8)
    return q, unit


def all_reduce_mean_tree(tree, axis_name: str, reduce_fn):
    """Mean-reduce a gradient pytree as ONE flat buffer through any of the
    manual sum-collectives above — the single flatten -> reduce -> /N ->
    unflatten path shared by every manual sync rung."""
    n = lax.axis_size(axis_name)
    flat, unflatten = flatten_tree(tree)
    return unflatten(reduce_fn(flat, axis_name) / n, cast=False)


def ring_all_reduce_mean(tree, axis_name: str, *,
                         bidirectional: bool = False):
    """Mean-reduce a gradient pytree over the ring as ONE flat buffer."""
    def reduce_fn(flat, ax):
        return ring_all_reduce(flat, ax, bidirectional=bidirectional)

    return all_reduce_mean_tree(tree, axis_name, reduce_fn)

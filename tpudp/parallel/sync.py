"""The gradient-synchronization strategy ladder — the reference's core.

Each strategy is a function ``(grad_tree, axis_name) -> grad_tree`` applied
between backward and optimizer step inside a ``shard_map``-ed train step,
mirroring where the reference calls its sync
(between ``loss.backward()`` and ``optimizer.step()``,
``src/Part 2a/main.py:94-96``).  All strategies produce the *mean* gradient
on every device — the observable contract of every rung of the ladder.

  none        Part 1  — no collective; single-device baseline
              (src/Part 1/main.py:32-58 has no sync call).
  coordinator Part 2a — semantics of gather-to-rank-0 → mean → scatter
              (src/Part 2a/main.py:117-127).  SPMD has no privileged rank, so
              every device all-gathers and means — numerically identical,
              without the rank-0 serialization bottleneck, but NOT the same
              traffic shape: all_gather lands N× the gradient payload on
              every device (vs 2 wire crossings per non-root rank in the
              hub pattern), and BASELINE.md measures it at ~10.5× psum's
              wall time on the 8-device mesh.  It exists for semantic
              parity with the reference's rung, not as a fast path.
  allreduce   Part 2b — built-in collective: psum then divide by world size
              (src/Part 2b/main.py:116-119: all_reduce(SUM); grad /= size).
  ring        north-star extra — hand-rolled ring all-reduce from ppermute,
              single-direction (the schedule that measures fastest on every
              mesh timed so far — BASELINE.md sweep; round-3 VERDICT #5
              reverted the faith-based bidirectional default).  ring_uni is
              a kept alias of the same schedule; ring_bidir selects the two
              counter-rotating half-buffers (both ICI directions of a real
              torus — a hypothesis benchmarks/collective_bench.py will
              test the moment a multi-chip window exists).
  allreduce_hd / allreduce_a2a  beyond-reference manual flavors —
              Rabenseifner halving-doubling (2*log2 N pairwise exchanges)
              and all_to_all+local-sum reduce-scatter (2 dispatches); same
              bandwidth-optimal wire bytes, different latency profiles
              (measured head-to-head in BASELINE.md).
  allreduce_bf16  beyond-reference extra — gradients cross the wire as
              bfloat16 (half the collective bytes), restored after the mean.
  allreduce_int8  beyond-reference extra — int8 on the wire via the
              ppermute ring (quarter the bytes; exact integer accumulation;
              effective precision log2(127 // N) bits; lossy, opt-in).
  auto        Part 3  — like DDP (src/Part 3/main.py:61), sync is *implicit*:
              the strategy is still psum/N, but the step is compiled as one
              XLA program so the compiler schedules/overlaps the collective
              with the backward pass — the TPU equivalent of DDP's bucketed
              overlap, obtained from the compiler rather than hand-written
              C++ hooks.  Also selectable as a GSPMD path (jit + sharding
              annotations, no explicit collectives) via Trainer(spmd_mode=
              'gspmd').
"""

from __future__ import annotations

from typing import Callable

import jax
from jax import lax

from tpudp.parallel.ring import (a2a_all_reduce, all_reduce_mean_tree,
                                 hd_all_reduce, ring_all_reduce_mean)

SyncFn = Callable[[object, str], object]


def sync_none(grads, axis_name: str):
    """Part 1: no synchronization."""
    del axis_name
    return grads


def sync_coordinator(grads, axis_name: str):
    """Part 2a semantics: every device ends with the mean gradient via
    all-gather + local mean (rank-0 asymmetry is a Gloo API artifact, not
    observable behavior — SURVEY.md §7 hard parts).  Traffic cost is N×
    the gradient payload per device — measured ~10.5× psum (BASELINE.md);
    see the module docstring."""
    def gather_mean(g):
        return lax.all_gather(g, axis_name).mean(axis=0)
    return jax.tree.map(gather_mean, grads)


def sync_allreduce(grads, axis_name):
    """Part 2b: all-reduce(SUM) then divide by world size.  ``axis_name``
    may be a tuple of mesh axes (DP x SP meshes reduce over both)."""
    n = lax.psum(1, axis_name)  # product of axis sizes, handles tuples
    return jax.tree.map(lambda g: lax.psum(g, axis_name) / n, grads)


def sync_ring(grads, axis_name: str):
    """North-star: hand-rolled ppermute ring all-reduce over one flat
    buffer — single-direction, the schedule that measures fastest on
    every mesh timed so far (BASELINE.md sweep; see the module
    docstring for why the bidirectional default was reverted)."""
    return ring_all_reduce_mean(grads, axis_name)


# Kept alias: round-2/3 CLIs, banked bench rows, and examples refer to the
# single-direction schedule by this name.
sync_ring_uni = sync_ring


def sync_ring_bidir(grads, axis_name: str):
    """Two counter-rotating half-buffers — both ICI directions of a TPU
    torus in flight at once.  Unmeasured on real multi-chip hardware (the
    torus-overlap win is a hypothesis; on the simulated mesh the doubled
    ppermute dispatch count makes it ~1.6x slower than the single ring,
    BASELINE.md) — selectable for benchmarks, not the default."""
    return ring_all_reduce_mean(grads, axis_name, bidirectional=True)


def sync_allreduce_hd(grads, axis_name):
    """Manual collective, latency-optimal flavor: recursive
    halving-doubling (Rabenseifner) — same bandwidth-optimal wire bytes
    as the ring in 2*log2(N) steps instead of 2*(N-1).  See
    tpudp.parallel.ring.hd_all_reduce for the schedule trade-offs."""
    return all_reduce_mean_tree(grads, axis_name, hd_all_reduce)


def sync_allreduce_a2a(grads, axis_name):
    """Manual collective, collective-fusion flavor: reduce-scatter from
    ``all_to_all`` + local sum, then all-gather — two dispatches moving
    the same bandwidth-optimal bytes as the ring.  See
    tpudp.parallel.ring.a2a_all_reduce."""
    return all_reduce_mean_tree(grads, axis_name, a2a_all_reduce)


def sync_allreduce_bf16(grads, axis_name):
    """Bandwidth-compressed all-reduce (beyond-reference): gradients cross
    the interconnect as bfloat16 — half the bytes of the fp32 ladder rungs —
    and are restored to their original dtype after the mean.

    bf16 keeps fp32's exponent range, so the cast cannot overflow the way
    fp16 compression does (no loss scaling needed); what it costs is
    mantissa precision (~8 bits) on the cast AND in the reduction — the
    psum's add runs on the bf16 operands, so rounding error grows with the
    axis size (O(sqrt(N) ulp for random signs).  Forward/backward math and
    the optimizer update stay in the model's compute dtype; on CIFAR-scale
    meshes the trajectory tracks fp32 closely (equivalence tested to loose
    tolerance in tests/test_sync.py).  For very large meshes where bf16
    tree accumulation is a concern, prefer the uncompressed ``allreduce``
    rung — this one trades precision for exactly the wire/reduce bytes.
    """
    import jax.numpy as jnp

    n = lax.psum(1, axis_name)

    def compress_reduce(g):
        total = lax.psum(g.astype(jnp.bfloat16), axis_name)
        return (total / n).astype(g.dtype)

    return jax.tree.map(compress_reduce, grads)


def sync_allreduce_int8(grads, axis_name):
    """8-bit **wire** compression (beyond-reference): the whole gradient
    pytree rides the ppermute ring as ONE flat int8 buffer — every hop of
    both ring phases moves 1 byte/element, a quarter of the fp32 rungs'
    wire traffic (a psum of upcast integers would move 4 bytes/element and
    save nothing; the ring is what makes the claim real).

    Scheme: one shared scale for the flat buffer (``pmax`` of the max-abs,
    one scalar collective), then each device quantizes onto a grid clipped
    to ``+/-(127 // N)`` — so the worst-case ring sum, N devices all at the
    clip bound with the same sign, is ``N * (127 // N) <= 127``: every
    partial sum along the reduce-scatter ring stays strictly within int8
    and accumulation is EXACT (integer adds; no bf16-style accumulation
    rounding).  Clipping at the *quantized* level is what provides the
    guarantee: with plain round, N near-identical max-magnitude gradients
    each rounding 127/N UP (e.g. round(63.5)=64 at N=2) would sum to 128
    and wrap to -128, sign-flipping the largest gradient element.  The
    cost is quantization resolution: effective precision is
    ``log2(127 // N)`` bits of the buffer's max-abs (~6 bits at N=2, ~4 at
    N=8).  Stateless, no error feedback — a lossy opt-in for
    bandwidth-bound meshes (the torch-DDP compress-hook idea pushed to 8
    bits); tested for mean-accuracy bounds, training closeness, and the
    no-wraparound guarantee in tests/test_sync.py.
    """
    import jax.numpy as jnp

    from tpudp.parallel.ring import (flatten_tree, int8_headroom_quantize,
                                     ring_all_reduce)

    n = lax.axis_size(axis_name)
    if n == 1:
        return grads
    flat, unflatten = flatten_tree(grads, dtype=jnp.float32)
    q, unit = int8_headroom_quantize(flat, axis_name)
    total = ring_all_reduce(q, axis_name)  # int8 on the wire, exact adds
    mean = total.astype(jnp.float32) * (unit / n)
    return unflatten(mean)


# 'auto' shares the allreduce math; the difference is scheduling, which XLA
# owns because the whole train step (fwd+bwd+sync+update) is one jitted
# program.  Kept as a distinct name so the CLI ladder maps 1:1 to the parts.
sync_auto = sync_allreduce

# Wire-schedule provenance for evidence rows (round-4 advisor): the label
# "ring" changed meaning in round 4 (bidirectional -> single-direction,
# per the measured sweep in parallel/ring.py), so bench/matrix rows stamp
# the direction the labeled rung actually ran, and banked-evidence
# matching (bench.py::_banked_good, tools/bench_gaps.py::matrix_missing)
# treats ring rows WITHOUT the stamp — pre-flip captures — as measuring a
# different schedule rather than re-emitting them under the new meaning.
RING_DIRECTION: dict[str, str] = {
    "ring": "uni",
    "ring_uni": "uni",
    "ring_bidir": "bidir",
}

SYNC_STRATEGIES: dict[str, SyncFn] = {
    "none": sync_none,
    "coordinator": sync_coordinator,
    "allreduce": sync_allreduce,
    "allreduce_bf16": sync_allreduce_bf16,
    "allreduce_int8": sync_allreduce_int8,
    "ring": sync_ring,
    "ring_uni": sync_ring_uni,
    "ring_bidir": sync_ring_bidir,
    "allreduce_hd": sync_allreduce_hd,
    "allreduce_a2a": sync_allreduce_a2a,
    "auto": sync_auto,
}


# What the example CLIs offer as --sync choices: the full ladder minus
# 'none', which under multi-device DP silently trains divergent replicas.
# One definition so every example stays in lockstep.
EXAMPLE_SYNC_CHOICES = tuple(sorted(set(SYNC_STRATEGIES) - {"none"}))


def get_sync(name: str) -> SyncFn:
    try:
        return SYNC_STRATEGIES[name]
    except KeyError:
        raise ValueError(
            f"unknown sync strategy {name!r}; choose from {sorted(SYNC_STRATEGIES)}"
        ) from None

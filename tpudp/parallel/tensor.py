"""Tensor parallelism: Megatron-style parameter sharding via GSPMD.

Beyond-parity capability (the reference is pure data-parallel — model
replicated whole per rank, ``src/Part 2a/main.py:59-60``; SURVEY.md §2.2
lists TP as an optional stretch).  This is the TPU-native way to do TP:
instead of hand-writing the column/row-parallel matmuls and their psums
(Megatron-LM's C++/NCCL approach), we *annotate* each parameter with a
:class:`~jax.sharding.PartitionSpec` over a ``model`` mesh axis and jit the
unchanged train step with those shardings — XLA's SPMD partitioner then
splits every matmul and inserts/schedules the reduce-scatter/all-reduce
collectives over ICI itself (the "pick a mesh, annotate shardings, let XLA
insert collectives" recipe).

The rules below reproduce Megatron's layout for a transformer block:

  * qkv projection      — column-parallel (output features split): each
    device computes a head-subset of Q/K/V locally, attention is then
    embarrassingly parallel over heads.
  * attention output    — row-parallel (input features split): the partial
    products are summed with one all-reduce, which XLA inserts.
  * MLP up-projection   — column-parallel; gelu applies elementwise to the
    local shard (no communication).
  * MLP down-projection — row-parallel (one all-reduce).
  * token embedding     — vocab-split; the tied LM head (``wte.attend``)
    becomes a vocab-split matmul whose output stays sharded into the
    softmax, and the embedding *lookup* becomes a masked-gather + psum.
  * LayerNorm / biases of row-parallel layers / positional embedding —
    replicated (tiny).

Rules are path-regex → spec pairs (t5x-style), resolved against
``jax.tree_util.keystr`` paths, so they apply uniformly to params, SGD
momentum (whose trace mirrors the param tree and therefore shards
identically), and any other param-shaped state.
"""

from __future__ import annotations

import re
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Sequence[tuple[str, P]]

MODEL_AXIS = "model"


def gpt2_tp_rules(model_axis: str = MODEL_AXIS) -> Rules:
    """Megatron-style partition rules for tpudp.models.gpt2.GPT2 params."""
    col = P(None, model_axis)  # split output features
    row = P(model_axis, None)  # split input features (psum'd by XLA)
    return (
        (r"attn/qkv/kernel", col),
        (r"attn/qkv/bias", P(model_axis)),
        (r"attn/proj/kernel", row),
        (r"attn/proj/bias", P()),
        (r"mlp_fc/kernel", col),
        (r"mlp_fc/bias", P(model_axis)),
        (r"mlp_proj/kernel", row),
        (r"mlp_proj/bias", P()),
        (r"wte/embedding", P(model_axis, None)),  # vocab-split
        (r"wpe/embedding", P()),
        (r"ln_\w+/(scale|bias)", P()),
    )


def llama_tp_rules(model_axis: str = MODEL_AXIS) -> Rules:
    """Megatron-style partition rules for tpudp.models.llama.Llama.

    q/gate/up are column-parallel, the wo/down projections row-parallel
    (their output psum inserted by XLA), the untied head column-parallel
    over vocab.  GQA note: wk/wv output dim is ``kv_heads * head_dim`` —
    it must divide by the model-axis size, so shard KV-light configs
    (e.g. kv_heads=1) on a correspondingly small TP degree or widen
    kv_heads.
    """
    col = P(None, model_axis)  # split output features
    row = P(model_axis, None)  # split input features (psum'd by XLA)
    return (
        (r"attn/w[qkv]/kernel", col),
        (r"attn/wo/kernel", row),
        (r"(gate|up)/kernel", col),
        (r"down/kernel", row),
        (r"wte/embedding", P(model_axis, None)),  # vocab-split
        (r"lm_head/kernel", col),
        (r"rms_\w+/scale", P()),
    )


def vgg_tp_rules(model_axis: str = MODEL_AXIS) -> Rules:
    """Channel-split rules for the conv models: conv kernels are HWIO, the
    output-channel axis (last) splits across ``model``; BatchNorm runs on
    the local channel shard.  The classifier head is column-parallel."""
    return (
        (r"Conv_\d+/kernel|stem_conv/kernel|conv\w*/kernel", P(None, None, None, model_axis)),
        (r"Conv_\d+/bias", P(model_axis)),
        (r"BatchNorm_\d+/(scale|bias)", P(model_axis)),
        (r"(classifier|Dense_\d+)/kernel", P(None, model_axis)),
        (r"(classifier|Dense_\d+)/bias", P(model_axis)),
    )


def _normalize_path(path) -> str:
    """``keystr`` gives e.g. ``['params']['h_0']['attn']['qkv']['kernel']`` —
    normalize to ``params/h_0/attn/qkv/kernel`` for readable regexes."""
    s = jax.tree_util.keystr(path)
    s = re.sub(r"[\[\]'\.]+", "/", s)
    return s.strip("/")


def spec_for_path(path_str: str, rules: Rules, leaf=None) -> P:
    """First matching rule wins; unmatched (and scalar) leaves replicate."""
    ndim = getattr(leaf, "ndim", None)
    for pattern, spec in rules:
        if spec is None:
            continue
        if re.search(pattern, path_str):
            if ndim is not None and len(spec) > ndim:
                return P()
            return spec
    return P()


def tree_shardings(tree: Any, mesh: Mesh, rules: Rules) -> Any:
    """Map every leaf of ``tree`` (arrays or ShapeDtypeStructs) to a
    NamedSharding chosen by the rules.  Leaves whose sharded dimension is
    not divisible by the axis size fall back to replicated — correctness
    never depends on the annotation, only layout does (GSPMD invariant)."""

    def one(path, leaf):
        spec = spec_for_path(_normalize_path(path), rules, leaf)
        shape = getattr(leaf, "shape", ())
        for dim, axes in enumerate(spec):
            if axes is None:
                continue
            names = axes if isinstance(axes, tuple) else (axes,)
            size = int(np.prod([mesh.shape[a] for a in names]))
            if dim >= len(shape) or shape[dim] % size != 0:
                return NamedSharding(mesh, P())
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, tree)


def state_shardings(state: Any, mesh: Mesh, rules: Rules) -> Any:
    """Shardings for a full TrainState: params and the momentum trace (whose
    tree paths embed the param paths, so the same regexes hit) shard by the
    rules; step/loss scalars and everything unmatched replicate."""
    return tree_shardings(state, mesh, rules)


def fsdp_shardings(
    tree: Any,
    mesh: Mesh,
    axis: str = "data",
    *,
    min_size: int = 1024,
) -> Any:
    """ZeRO-3/FSDP-style shardings: every large leaf shards its first
    ``axis``-divisible dimension over the DATA axis, so each device stores
    only ``1/N`` of the parameters and optimizer state.

    This is the TPU-native FSDP: no gather/scatter bookkeeping code — the
    sharding annotation alone makes XLA all-gather each parameter just
    before use in the forward/backward and reduce-scatter its gradient,
    overlapping both with compute.  Leaves smaller than ``min_size``
    elements stay replicated (the collective would cost more than the
    memory saved — FSDP implementations have the same threshold knob).
    Applies uniformly to params and momentum (same tree shapes).
    """
    n = mesh.shape[axis]

    def one(leaf):
        shape = getattr(leaf, "shape", ())
        size = int(np.prod(shape)) if shape else 0
        if size < min_size:
            return NamedSharding(mesh, P())
        for dim, d in enumerate(shape):
            if d % n == 0:
                spec = [None] * dim + [axis]  # trailing dims implicit
                return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree.map(one, tree)


def zero1_shardings(
    state: Any,
    mesh: Mesh,
    axis: str = "data",
    *,
    min_size: int = 1024,
) -> Any:
    """Weight-update sharding (ZeRO-1; the XLA cross-replica weight-update
    sharding of arXiv:2004.13336): parameters stay REPLICATED — forward and
    backward are plain DP, no weight gathers — but the optimizer state
    shards over the data axis, so each device stores 1/N of the momentum
    and applies the update only to its shard.

    Under GSPMD this layout alone makes XLA reduce-scatter the gradients
    into the sharded momentum update and all-gather the parameter delta —
    the paper's transformation, obtained from the partitioner.  Exactly the
    DP trajectory (tested), with optimizer memory ÷ N; the middle rung
    between plain DP (everything replicated) and FSDP/ZeRO-3
    (:func:`fsdp_shardings`, everything sharded).
    """
    rep = jax.tree.map(
        lambda _: NamedSharding(mesh, P()), state)
    return rep.replace(opt_state=fsdp_shardings(
        state.opt_state, mesh, axis, min_size=min_size))


def shard_state(state: Any, mesh: Mesh, rules: Rules) -> Any:
    """Device-put an (unsharded) TrainState onto its TP layout."""
    return jax.device_put(state, state_shardings(state, mesh, rules))

"""MPMD pipeline schedules: per-tick 1F1B (+ interleaved virtual stages)
over ``lax.ppermute`` on a PP x DP mesh, with the optimizer update sharded
across DP replicas in-step.

Where ``tpudp/parallel/pipeline.py`` expresses its schedules as a uniform
``lax.scan`` (every tick compiles to the same fixed program, so ramp and
drain ticks pay full forward+backward price), this module takes the MPMD
route of "Scaling Deep Learning Training with MPMD Pipeline Parallelism"
(PAPERS.md, arXiv:2412.14374): the tick table is computed in Python at
trace time and the schedule is emitted as an UNROLLED per-tick program —
each tick traces only the work some stage actually performs that tick.
Ramp ticks carry no backward, drain ticks no forward, and dead virtual-
stage slots are statically elided, which is exactly the non-uniformity
that makes interleaved virtual stages (``interleave > 1``) profitable on
TPU — the trade pipeline.py's module docstring declares out of scope for
its scan-based schedules.  The price is program size growing with
``M + 2(S*V - 1)`` ticks; geometry is part of the compile key (and of the
trace-lock identity), so the program still compiles exactly once per
geometry (``TRACE_COUNTS`` observes this).

Schedule mechanics (1F1B-with-recompute over C = S*V *virtual* stages,
chunk ``c`` living on physical stage ``c % S``):

  * Forward of microbatch ``m`` on virtual stage ``p`` runs at tick
    ``p + m``; activations ride the ICI ring via a forward ``ppermute``
    (consecutive virtual stages always sit on ring-adjacent devices, the
    stage-wrap handled by a chunk-axis shift on the last/first device).
  * Backward of ``m`` on ``p`` runs at tick ``2(C-1) - p + m``; cotangents
    ride the reverse ring.  Each stage input is stashed in a per-chunk
    ring buffer of ``min(M, 2C-1)`` slots and the stage forward is
    recomputed at backward time (1F1B-with-recompute: O(C) activation
    memory independent of M).
  * The loss head runs only on the last virtual stage (a ``lax.cond`` so
    the other stages never trace the vocab matmul); the embedding vjp
    only on virtual stage 0.  Embedding- and head-side shared-param
    gradients accumulate in SEPARATE buffers combined once after the
    loop, so the floating-point reduction order is IDENTICAL across
    PP degrees — see "bit-exactness" below.

In-step sharded optimizer (``shard_optimizer=True``, the default — the
cross-replica weight-update sharding of arXiv:2004.13336, upgrading the
PR 7 manifest-only ZeRO-1 story): after the pipe-axis gradient assembly,
each gradient leaf is flattened, zero-padded to a multiple of DP, and
``lax.psum_scatter``-ed over the data axis, so every DP replica reduces
AND keeps only its 1/DP gradient shard; the optimizer update (momentum,
weight decay — elementwise transforms only) runs on that shard against a
1/DP param slice and a 1/DP-resident optimizer state; ``lax.all_gather``
then reassembles the full parameters for the next forward.  Optimizer
state is physically sharded over ``data`` (and ``pipe`` for block
leaves) in the TrainState itself — per-stage checkpoint shards fall out
of the ordinary global-slice manifest format, and a stage fault takes
the supervisor's existing voted-rollback path (docs/PIPELINE.md).

Bit-exactness discipline (veScale, arXiv:2509.07003): at equal global
batch, equal microbatch count, and equal DP degree, the LOSS trajectory
is BIT-EXACT across PP degrees — the pipeline is pure transport.  This
holds because (a) each chunk applies its layers as an unrolled Python
loop, so the per-layer op sequence never depends on the partition;
(b) every cross-microbatch accumulator adds in microbatch order on every
geometry; (c) embed/head shared-gradient sums stay separate until one
final add; and (d) ``ppermute`` moves bits, not arithmetic.  Parameters
agree to within 1 ulp (XLA fuses a single-layer chunk's backward into a
different — equally valid — op schedule than a multi-layer chunk's, via
the residual edges between the recomputed forward and its vjp; an
``optimization_barrier`` fence on the activation chain does not reach
those edges, so the last ulp of dW is owned by the compiler, not the
schedule).  tests/test_schedule.py pins loss trajectories at PP in
{1,2,4} against the single-stage (PP=1) trainer, including through an
injected stage fault + voted rollback, and parameter trajectories at
1-ulp tolerance.
"""

from __future__ import annotations

import collections
import dataclasses
import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudp.mesh import DATA_AXIS
from tpudp.parallel.pipeline import (PIPE_AXIS, _map_params_subtrees,
                                     pipeline_spec_tree)

#: Trace-time compile counter, one bump per (geometry, schedule) trace —
#: the train-side analogue of tpudp.serve.TRACE_COUNTS: steady-state
#: steps at a fixed geometry must never re-trace (tests/test_schedule.py
#: observes the count across steps).
TRACE_COUNTS: collections.Counter = collections.Counter()


@dataclasses.dataclass(frozen=True)
class StagePartition:
    """Static placement of a model's block stack onto P pipeline stages.

    With ``interleave == 1`` each stage owns one contiguous run of
    ``num_layers / stages`` layers (the classic 1F1B partition).  With
    ``interleave == V > 1`` the stack is cut into ``C = stages * V``
    chunks of ``num_layers / C`` layers placed round-robin — chunk ``c``
    on stage ``c % stages`` — so each device hosts V *virtual* stages
    and the pipeline ramp shrinks from ``(S-1)`` full-stage slots to
    ``(C-1)`` chunk slots each ``1/V`` the work (Megatron's interleaved
    schedule, per-tick-programmable here because the MPMD schedule is
    unrolled, not scanned).
    """

    num_layers: int
    stages: int
    interleave: int = 1

    def __post_init__(self):
        if self.stages < 1 or self.interleave < 1:
            raise ValueError(
                f"stages ({self.stages}) and interleave ({self.interleave}) "
                "must be >= 1")
        if self.num_layers % (self.stages * self.interleave):
            raise ValueError(
                f"{self.num_layers} layers not divisible into "
                f"{self.stages} stages x {self.interleave} virtual chunks")

    @property
    def chunks(self) -> int:
        """Total virtual-stage count ``C = stages * interleave``."""
        return self.stages * self.interleave

    @property
    def layers_per_chunk(self) -> int:
        return self.num_layers // self.chunks

    def chunk_layers(self, chunk: int) -> tuple[int, ...]:
        lo = chunk * self.layers_per_chunk
        return tuple(range(lo, lo + self.layers_per_chunk))

    def chunk_stage(self, chunk: int) -> int:
        return chunk % self.stages

    def stage_chunks(self, stage: int) -> tuple[int, ...]:
        return tuple(stage + v * self.stages for v in range(self.interleave))

    def stage_layers(self, stage: int) -> tuple[int, ...]:
        """Layers hosted by ``stage``, in chunk-major execution order."""
        return sum((self.chunk_layers(c) for c in self.stage_chunks(stage)),
                   ())

    def layer_order(self) -> tuple[int, ...]:
        """Global stacking order, stage-major: sharding the stacked
        leading axis over ``pipe`` in ``stages`` equal slices hands each
        stage exactly :meth:`stage_layers`.  Identity for
        ``interleave == 1`` (checkpoint-manifest compatible with
        :func:`tpudp.parallel.pipeline.stack_block_params`)."""
        return sum((self.stage_layers(s) for s in range(self.stages)), ())

    def ticks(self, n_microbatches: int) -> int:
        """Schedule length: ``M + 2(C-1)`` (ramp + steady 1F1B + drain)."""
        return n_microbatches + 2 * (self.chunks - 1)

    def bubble_fraction(self, n_microbatches: int) -> float:
        from tpudp.utils.flops import pipeline_bubble_fraction

        return pipeline_bubble_fraction(self.stages, n_microbatches,
                                        interleave=self.interleave)


def stack_partitioned(params: dict, part: StagePartition,
                      prefix: str = "h_") -> dict:
    """Re-layout GPT-2 params into the partition's pipeline layout: one
    stacked ``blocks`` pytree whose leading axis follows
    :meth:`StagePartition.layer_order` (so a ``pipe``-axis shard is one
    stage's chunks, chunk-major), plus the shared params."""
    blocks = [params[f"{prefix}{i}"] for i in part.layer_order()]
    out = {k: v for k, v in params.items() if not k.startswith(prefix)}
    out["blocks"] = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    return out


def unstack_partitioned(params_pp: dict, part: StagePartition,
                        prefix: str = "h_") -> dict:
    """Inverse of :func:`stack_partitioned` (checkpoint interop)."""
    blocks = params_pp["blocks"]
    out = {k: v for k, v in params_pp.items() if k != "blocks"}
    for pos, layer in enumerate(part.layer_order()):
        out[f"{prefix}{layer}"] = jax.tree.map(lambda x, p=pos: x[p], blocks)
    return out


def _chunk_slice(blocks: Any, part: StagePartition, v: int) -> Any:
    """Virtual chunk ``v``'s ``(layers_per_chunk, ...)`` slice of this
    device's ``(interleave * layers_per_chunk, ...)`` local block stack."""
    lc = part.layers_per_chunk
    return jax.tree.map(lambda a: a[v * lc:(v + 1) * lc], blocks)


def _path_has_blocks(path) -> bool:
    return "blocks" in [getattr(p, "key", getattr(p, "name", None))
                        for p in path]


def onef1b_mpmd_loss_and_grads(
    cfg,
    params: dict,
    tokens: jnp.ndarray,
    targets: jnp.ndarray,
    part: StagePartition,
    n_microbatches: int,
    block_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    axis_name: str = PIPE_AXIS,
) -> tuple[jnp.ndarray, dict]:
    """The unrolled per-tick 1F1B MPMD program, inside ``shard_map``.

    Runs ``part.ticks(M)`` statically-specialized ticks.  Python decides
    per tick which virtual-stage slots can be live ANYWHERE on the ring
    (ramp ticks trace no backward, drain ticks no forward, dead chunk
    slots trace nothing); the per-device microbatch index within a live
    slot is the only dynamic quantity, resolved from
    ``lax.axis_index``.  Returns ``(mean_loss, grads)`` with grads
    structured like ``params`` — blocks stage-local, shared params as
    separate embed/head sums combined by ONE final add (the caller's
    structural psum over the pipe axis supplies the cross-stage terms).
    """
    from tpudp.models.gpt2 import embed_tokens, lm_head

    s_size = part.stages
    v_count = part.interleave
    c_count = part.chunks
    m_count = n_microbatches
    sidx = lax.axis_index(axis_name)
    b, t = tokens.shape
    if b % m_count:
        raise ValueError(f"per-data-shard batch {b} not divisible by "
                         f"{m_count} microbatches")
    mb = b // m_count
    slots = min(m_count, 2 * c_count - 1)
    blocks = params["blocks"]
    shared = {k: v for k, v in params.items() if k != "blocks"}

    tok_mb = tokens.reshape(m_count, mb, t)
    tgt_mb = targets.reshape(m_count, mb, t)
    fwd_perm = [(j, (j + 1) % s_size) for j in range(s_size)]
    bwd_perm = [(j, (j - 1) % s_size) for j in range(s_size)]

    def chunk_apply(p_chunk, x):
        # Unrolled per-layer loop (NOT lax.scan): the per-layer op
        # sequence is then partition-independent, which is what makes
        # the loss trajectory bit-exact across PP degrees.
        for i in range(part.layers_per_chunk):
            x = block_fn(jax.tree.map(lambda a, i=i: a[i], p_chunk), x)
        return x

    def head_loss(sh, h, tgts):
        """Sum (not mean) CE of one microbatch — normalized once at the
        end so the reduction order is microbatch-major everywhere."""
        logits = lm_head(cfg, sh, h)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgts).sum()

    act = jax.eval_shape(lambda sh: embed_tokens(cfg, sh, tok_mb[0]), shared)
    zeros_act = jnp.zeros(act.shape, act.dtype)

    # Static liveness windows per virtual-chunk slot v (any stage live).
    def fwd_live(v, tick):
        return v * s_size <= tick <= v * s_size + (s_size - 1) + (m_count - 1)

    def bwd_live(v, tick):
        lo = 2 * (c_count - 1) - (v * s_size + s_size - 1)
        hi = 2 * (c_count - 1) - v * s_size + (m_count - 1)
        return lo <= tick <= hi

    def head_live(tick):  # virtual stage C-1 backs up the tick it forwards
        return c_count - 1 <= tick <= c_count - 1 + (m_count - 1)

    fwd_in = [zeros_act for _ in range(v_count)]
    bwd_in = [zeros_act for _ in range(v_count)]
    stash = [jnp.zeros((slots,) + zeros_act.shape, zeros_act.dtype)
             for _ in range(v_count)]
    f32 = lambda tree: jax.tree.map(
        lambda p: jnp.zeros(p.shape, jnp.float32), tree)
    gchunk = [f32(_chunk_slice(blocks, part, v)) for v in range(v_count)]
    gembed = f32(shared)
    ghead = f32(shared)
    loss_sum = jnp.zeros((), jnp.float32)

    for tick in range(part.ticks(m_count)):
        # ---- forward slots -------------------------------------------
        ys = {}
        for v in range(v_count):
            if not fwd_live(v, tick):
                continue
            m_f = tick - v * s_size - sidx
            f_active = (m_f >= 0) & (m_f < m_count)
            m_f_c = jnp.clip(m_f, 0, m_count - 1)
            if v == 0:
                toks_f = lax.dynamic_index_in_dim(tok_mb, m_f_c, 0,
                                                  keepdims=False)
                x = jnp.where(sidx == 0, embed_tokens(cfg, shared, toks_f),
                              fwd_in[0])
            else:
                x = fwd_in[v]
            slot = m_f_c % slots
            prev = lax.dynamic_index_in_dim(stash[v], slot, 0, keepdims=False)
            stash[v] = lax.dynamic_update_index_in_dim(
                stash[v], jnp.where(f_active, x, prev), slot, 0)
            ys[v] = chunk_apply(_chunk_slice(blocks, part, v), x)

        # ---- backward slots ------------------------------------------
        dxs = {}
        for v in range(v_count):
            if not bwd_live(v, tick):
                continue
            m_b = tick - 2 * (c_count - 1) + v * s_size + sidx
            b_active = (m_b >= 0) & (m_b < m_count)
            m_b_c = jnp.clip(m_b, 0, m_count - 1)
            slot = m_b_c % slots
            x_b = lax.dynamic_index_in_dim(stash[v], slot, 0, keepdims=False)
            tgts_b = lax.dynamic_index_in_dim(tgt_mb, m_b_c, 0,
                                              keepdims=False)

            if v == v_count - 1 and head_live(tick):
                # Last virtual stage: loss + head cotangent from THIS
                # tick's forward output.  lax.cond so the other stages
                # never trace the (mb, t, vocab) head matmul + pullback.
                def _head(operands):
                    sh, h, tg = operands
                    loss_mb, head_vjp = jax.vjp(
                        lambda sh_, h_: head_loss(sh_, h_, tg), sh, h)
                    dsh, dy_h = head_vjp(jnp.ones((), loss_mb.dtype))
                    return loss_mb, dsh, dy_h

                def _head_zero(operands):
                    sh, h, _tg = operands
                    return (jnp.zeros((), jnp.float32),
                            jax.tree.map(jnp.zeros_like, sh),
                            jnp.zeros_like(h))

                loss_mb, dsh_head, dy_head = lax.cond(
                    (sidx == s_size - 1) & b_active, _head, _head_zero,
                    (shared, ys[v_count - 1], tgts_b))
                dy = jnp.where(sidx == s_size - 1, dy_head, bwd_in[v])
                ghead = jax.tree.map(lambda a, g: a + g, ghead, dsh_head)
                loss_sum = loss_sum + loss_mb
            else:
                dy = bwd_in[v]
            dy = jnp.where(b_active, dy, jnp.zeros_like(dy))

            # Recompute this chunk's forward from the stashed input.
            _, chunk_vjp = jax.vjp(chunk_apply,
                                   _chunk_slice(blocks, part, v), x_b)
            dchunk, dx = chunk_vjp(dy)
            gchunk[v] = jax.tree.map(lambda a, g: a + g, gchunk[v], dchunk)
            dxs[v] = dx

            if v == 0 and tick >= 2 * (c_count - 1):
                # Virtual stage 0: the input cotangent becomes embedding
                # grads (its own accumulator — see module docstring).
                toks_b = lax.dynamic_index_in_dim(tok_mb, m_b_c, 0,
                                                  keepdims=False)

                def _embed(operands):
                    sh, tk, d = operands
                    _, embed_vjp = jax.vjp(
                        lambda sh_: embed_tokens(cfg, sh_, tk), sh)
                    (dsh,) = embed_vjp(d)
                    return dsh

                def _embed_zero(operands):
                    sh, _tk, _d = operands
                    return jax.tree.map(jnp.zeros_like, sh)

                dsh_embed = lax.cond((sidx == 0) & b_active, _embed,
                                     _embed_zero, (shared, toks_b, dx))
                gembed = jax.tree.map(lambda a, g: a + g, gembed, dsh_embed)

        if s_size == 1:
            # Single stage: the ring is a self-loop and nothing is ever
            # read from the carries — elide the collectives entirely.
            continue

        # ---- ring transport to tick+1 --------------------------------
        if any(fwd_live(v, tick + 1) for v in range(v_count)):
            ystack = jnp.stack([ys.get(v, zeros_act)
                                for v in range(v_count)])
            # Chunk wrap: the last device's chunk v feeds the first
            # device's chunk v+1 (virtual stage vS+S-1 -> vS+S).
            shifted = jnp.concatenate(
                [jnp.zeros_like(ystack[:1]), ystack[:-1]], axis=0)
            moved = lax.ppermute(
                jnp.where(sidx == s_size - 1, shifted, ystack),
                axis_name, fwd_perm)
            fwd_in = [moved[v] for v in range(v_count)]
        if any(bwd_live(v, tick + 1) for v in range(v_count)):
            dstack = jnp.stack([dxs.get(v, zeros_act)
                                for v in range(v_count)])
            # Reverse wrap: the first device's chunk v+1 cotangent feeds
            # the last device's chunk v (virtual stage vS+S <- vS+S-1).
            shifted = jnp.concatenate(
                [dstack[1:], jnp.zeros_like(dstack[:1])], axis=0)
            moved = lax.ppermute(
                jnp.where(sidx == 0, shifted, dstack),
                axis_name, bwd_perm)
            bwd_in = [moved[v] for v in range(v_count)]

    lc_axis = jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *gchunk) \
        if v_count > 1 else gchunk[0]
    denom = jnp.asarray(b * t, jnp.float32)  # sum -> mean normalization
    gshared = jax.tree.map(lambda e, h: e + h, gembed, ghead)
    grads = {**{k: jax.tree.map(lambda g: g / denom, v)
                for k, v in gshared.items()},
             "blocks": jax.tree.map(lambda g: g / denom, lc_axis)}
    grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)
    return loss_sum / denom, grads


def pipeline_forward_mpmd(
    cfg,
    params: dict,
    tokens: jnp.ndarray,
    part: StagePartition,
    n_microbatches: int,
    block_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    axis_name: str = PIPE_AXIS,
) -> jnp.ndarray:
    """Forward-only MPMD ticks (``M + C - 1``): the eval twin of
    :func:`onef1b_mpmd_loss_and_grads`.  Returns the ``(M, mb, t, d)``
    last-virtual-stage hidden states — valid only on the last physical
    stage (zeros elsewhere); callers mask and psum like
    :func:`tpudp.parallel.pipeline.gpipe` consumers do."""
    from tpudp.models.gpt2 import embed_tokens

    s_size = part.stages
    v_count = part.interleave
    c_count = part.chunks
    m_count = n_microbatches
    sidx = lax.axis_index(axis_name)
    b, t = tokens.shape
    mb = b // m_count
    blocks = params["blocks"]
    shared = {k: v for k, v in params.items() if k != "blocks"}
    tok_mb = tokens.reshape(m_count, mb, t)
    fwd_perm = [(j, (j + 1) % s_size) for j in range(s_size)]

    def chunk_apply(p_chunk, x):
        for i in range(part.layers_per_chunk):
            x = block_fn(jax.tree.map(lambda a, i=i: a[i], p_chunk), x)
        return x

    act = jax.eval_shape(lambda sh: embed_tokens(cfg, sh, tok_mb[0]), shared)
    zeros_act = jnp.zeros(act.shape, act.dtype)

    def fwd_live(v, tick):
        return v * s_size <= tick <= v * s_size + (s_size - 1) + (m_count - 1)

    fwd_in = [zeros_act for _ in range(v_count)]
    outs = jnp.zeros((m_count,) + zeros_act.shape, zeros_act.dtype)

    for tick in range(m_count + c_count - 1):
        ys = {}
        for v in range(v_count):
            if not fwd_live(v, tick):
                continue
            m_f = tick - v * s_size - sidx
            f_active = (m_f >= 0) & (m_f < m_count)
            m_f_c = jnp.clip(m_f, 0, m_count - 1)
            if v == 0:
                toks_f = lax.dynamic_index_in_dim(tok_mb, m_f_c, 0,
                                                  keepdims=False)
                x = jnp.where(sidx == 0, embed_tokens(cfg, shared, toks_f),
                              fwd_in[0])
            else:
                x = fwd_in[v]
            ys[v] = chunk_apply(_chunk_slice(blocks, part, v), x)
            if v == v_count - 1 and c_count - 1 <= tick:
                # Last virtual stage emits microbatch m_f on the last
                # physical stage once the pipe has filled.
                write = (sidx == s_size - 1) & f_active
                prev = lax.dynamic_index_in_dim(outs, m_f_c, 0,
                                                keepdims=False)
                outs = lax.dynamic_update_index_in_dim(
                    outs, jnp.where(write, ys[v], prev), m_f_c, 0)

        if s_size == 1:
            continue
        if any(fwd_live(v, tick + 1) for v in range(v_count)):
            ystack = jnp.stack([ys.get(v, zeros_act)
                                for v in range(v_count)])
            shifted = jnp.concatenate(
                [jnp.zeros_like(ystack[:1]), ystack[:-1]], axis=0)
            moved = lax.ppermute(
                jnp.where(sidx == s_size - 1, shifted, ystack),
                axis_name, fwd_perm)
            fwd_in = [moved[v] for v in range(v_count)]

    return outs


def _pad_to(n: int, k: int) -> int:
    return k * math.ceil(n / k) if k > 1 else n


def _opt_shard_layout(subtree: dict, part: StagePartition, dp: int) -> dict:
    """Host-side re-layout of one params-shaped optimizer subtree (e.g.
    the SGD momentum trace) into the in-step-sharded layout: pipeline-
    stacked, then per leaf flattened and zero-padded to a multiple of
    ``dp`` — per STAGE for block leaves (so a ``(pipe, data)`` sharding
    of the flat axis hands each (stage, replica) device its own
    contiguous 1/DP slice), whole-leaf for shared leaves."""
    pp = stack_partitioned(subtree, part)

    def one(path, x):
        if _path_has_blocks(path):
            per_stage = x.reshape(part.stages, -1)
            n = per_stage.shape[1]
            pad = _pad_to(n, dp) - n
            if pad:
                per_stage = jnp.pad(per_stage, ((0, 0), (0, pad)))
            return per_stage.reshape(-1)
        flat = x.reshape(-1)
        pad = _pad_to(flat.size, dp) - flat.size
        return jnp.pad(flat, (0, pad)) if pad else flat

    return jax.tree_util.tree_map_with_path(one, pp)


def _opt_shard_specs(subtree: dict, part: StagePartition,
                     pipe_axis: str, data_axis: str | None) -> dict:
    """Spec twin of :func:`_opt_shard_layout` (structure only)."""
    pp = jax.eval_shape(lambda t: stack_partitioned(t, part), subtree)

    def one(path, _x):
        if _path_has_blocks(path):
            return (P((pipe_axis, data_axis)) if data_axis is not None
                    else P(pipe_axis))
        return P(data_axis) if data_axis is not None else P()

    return jax.tree_util.tree_map_with_path(one, pp)


def make_pipeline_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    state,
    *,
    n_microbatches: int,
    interleave: int = 1,
    data_axis: str | None = DATA_AXIS,
    pipe_axis: str = PIPE_AXIS,
    donate: bool = True,
    remat: bool = False,
    shard_optimizer: bool = True,
) -> tuple[Any, Callable]:
    """The 1F1B MPMD train step for tpudp.models.gpt2.GPT2: unrolled
    per-tick schedule (``interleave`` virtual stages per device) on a
    PP x DP mesh, with the optimizer update sharded across DP replicas
    in-step when ``shard_optimizer=True`` (reduce-scatter grads → shard
    update → allgather params; requires an elementwise ``tx`` — the
    make_optimizer SGD/AdamW chains qualify).

    Takes a standard (single-device-layout) TrainState and returns
    ``(pp_state, step_fn)`` with ``step_fn(state, tokens, targets) ->
    (state, loss)`` — the framework-wide contract, so the Trainer drives
    it unchanged.  ``pp_state`` holds params in the partition's stacked
    layout (blocks sharded over ``pipe``) and — under
    ``shard_optimizer`` — optimizer state as flat 1/DP shards over
    ``data`` (block leaves additionally over ``pipe``).
    """
    from tpudp.models.gpt2 import Block

    cfg = getattr(model, "config", None)
    if cfg is None or not hasattr(cfg, "num_layers"):
        raise TypeError(
            "make_pipeline_train_step drives tpudp.models.gpt2.GPT2 (a "
            f"model with a GPT2Config at .config); got "
            f"{type(model).__name__}")
    if cfg.attn_impl == "ring" or cfg.mlp_impl != "dense":
        raise ValueError(
            "pipeline parallelism supports dense/flash attention and dense "
            f"MLP blocks; got attn_impl={cfg.attn_impl!r} "
            f"mlp_impl={cfg.mlp_impl!r}")
    s = mesh.shape[pipe_axis]
    part = StagePartition(cfg.num_layers, s, interleave)
    missing = [f"h_{i}" for i in range(cfg.num_layers)
               if f"h_{i}" not in state.params]
    if missing:
        raise ValueError(
            f"params are missing block subtrees {missing[:3]}... — expected "
            f"the GPT-2 layout h_0..h_{cfg.num_layers - 1}")
    dp = mesh.shape[data_axis] if data_axis is not None else 1

    pp_params = stack_partitioned(state.params, part)
    params_struct = jax.tree.structure(state.params)
    if shard_optimizer:
        pp_opt = _map_params_subtrees(
            state.opt_state, params_struct,
            lambda sub: _opt_shard_layout(sub, part, dp))
        opt_specs = _map_params_subtrees(
            state.opt_state, params_struct,
            lambda sub: _opt_shard_specs(sub, part, pipe_axis, data_axis))
    else:
        pp_opt = _map_params_subtrees(
            state.opt_state, params_struct,
            lambda sub: stack_partitioned(sub, part))
        opt_specs = _map_params_subtrees(
            state.opt_state, params_struct,
            lambda sub: pipeline_spec_tree(
                jax.eval_shape(lambda t: stack_partitioned(t, part), sub),
                pipe_axis))
    # Non-params optimizer leaves (schedule counts etc.) stay replicated.
    opt_specs = jax.tree.map(
        lambda x: x if isinstance(x, P) else P(), opt_specs,
        is_leaf=lambda x: isinstance(x, P))
    pp_state = state.replace(params=pp_params, opt_state=pp_opt)
    pp_state_specs = pp_state.replace(
        step=P(),
        params=pipeline_spec_tree(pp_params, pipe_axis),
        batch_stats=jax.tree.map(lambda _: P(), pp_state.batch_stats),
        opt_state=opt_specs,
        loss_sum=P(),
        obs_norms=P() if pp_state.obs_norms is not None else None,
        sdc_fp=P() if pp_state.sdc_fp is not None else None,
    )

    block_fn = lambda p, x: Block(cfg).apply({"params": p}, x)
    if remat:
        block_fn = jax.checkpoint(block_fn)

    def body(st, tokens, targets):
        TRACE_COUNTS["pp_1f1b"] += 1
        loss, grads = onef1b_mpmd_loss_and_grads(
            cfg, st.params, tokens, targets, part, n_microbatches, block_fn,
            pipe_axis)
        # Shared-param grads live on the stages that produced them ->
        # structural psum over pipe; block grads are stage-local.
        grads = jax.tree_util.tree_map_with_path(
            lambda path, g: g if "blocks" in jax.tree_util.keystr(path)
            else lax.psum(g, pipe_axis),
            grads)
        loss = lax.psum(loss, pipe_axis)
        if data_axis is not None:
            loss = lax.psum(loss, data_axis) / dp

        if shard_optimizer:
            didx = (lax.axis_index(data_axis) if data_axis is not None
                    else jnp.zeros((), jnp.int32))

            def scatter_grad(g):
                flat = g.reshape(-1)
                pad = _pad_to(flat.size, dp) - flat.size
                if pad:
                    flat = jnp.pad(flat, (0, pad))
                if data_axis is None or dp == 1:
                    return flat
                return lax.psum_scatter(flat, data_axis,
                                        scatter_dimension=0,
                                        tiled=True) / dp

            def param_shard(x):
                flat = x.reshape(-1)
                pad = _pad_to(flat.size, dp) - flat.size
                if pad:
                    flat = jnp.pad(flat, (0, pad))
                if data_axis is None or dp == 1:
                    return flat
                n = flat.shape[0] // dp
                return lax.dynamic_slice(flat, (didx * n,), (n,))

            g_sh = jax.tree.map(scatter_grad, grads)
            p_sh = jax.tree.map(param_shard, st.params)
            updates, new_opt = tx.update(g_sh, st.opt_state, p_sh)
            new_p_sh = optax.apply_updates(p_sh, updates)

            def regather(ps, old):
                full = (lax.all_gather(ps, data_axis, axis=0, tiled=True)
                        if data_axis is not None and dp > 1 else ps)
                return full[:old.size].reshape(old.shape).astype(old.dtype)

            new_params = jax.tree.map(regather, new_p_sh, st.params)
        else:
            if data_axis is not None and dp > 1:
                grads = jax.tree.map(
                    lambda g: lax.psum(g, data_axis) / dp, grads)
            updates, new_opt = tx.update(grads, st.opt_state, st.params)
            new_params = optax.apply_updates(st.params, updates)

        # In-step SDC fingerprint (tpudp.sdc): stage-local u32 checksum
        # of the post-update params, summed over the pipe axis so every
        # device carries the FULL-model checksum — DP replicas (pipe
        # columns across `data`) hold bit-identical params after the
        # all-gather, so healthy fingerprints agree bit-for-bit.  The
        # 1/DP-sharded optimizer state is excluded (a different slice
        # per replica, the same exclusion rule as
        # consistency.fingerprint); the stage-stacked optimizer of the
        # unsharded path IS replicated over data and rides along.
        new_fp = st.sdc_fp
        if new_fp is not None:
            from tpudp.sdc import traced_fingerprint

            fp_tree = {"params": new_params}
            if not shard_optimizer:
                fp_tree["opt_state"] = new_opt
            new_fp = lax.psum(traced_fingerprint(fp_tree), pipe_axis)

        return st.replace(
            step=st.step + 1,
            params=new_params,
            opt_state=new_opt,
            loss_sum=st.loss_sum + loss,
            sdc_fp=new_fp,
        ), loss

    tok_spec = P(data_axis) if data_axis is not None else P()
    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pp_state_specs, tok_spec, tok_spec),
        out_specs=(pp_state_specs, P()),
        check_vma=False,
    )
    step = jax.jit(sharded, donate_argnums=(0,) if donate else ())

    placed = jax.device_put(
        pp_state,
        jax.tree.map(lambda sp: NamedSharding(mesh, sp), pp_state_specs,
                     is_leaf=lambda x: isinstance(x, P)),
    )
    return placed, step


def make_pipeline_eval_step(
    model,
    mesh: Mesh,
    state,
    *,
    n_microbatches: int,
    interleave: int = 1,
    data_axis: str | None = DATA_AXIS,
    pipe_axis: str = PIPE_AXIS,
):
    """Eval twin for the MPMD schedule: ``(state, tokens, targets,
    weights) -> (loss_sum, correct, count)`` per the Trainer eval
    contract.  ``state`` must already be in the partition layout (the
    output of :func:`make_pipeline_train_step`)."""
    from tpudp.models.gpt2 import Block, lm_head

    cfg = model.config
    s = mesh.shape[pipe_axis]
    part = StagePartition(cfg.num_layers, s, interleave)
    block_fn = lambda p, x: Block(cfg).apply({"params": p}, x)

    def body(st, tokens, targets, weights):
        b, t = tokens.shape
        h = pipeline_forward_mpmd(cfg, st.params, tokens, part,
                                  n_microbatches, block_fn, pipe_axis)
        h = h.reshape(b, t, cfg.d_model)
        logits = lm_head(cfg, st.params, h)
        per = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        w = jnp.broadcast_to(weights[:, None], per.shape)
        # Only the last stage saw real pipeline outputs; zero elsewhere
        # so the structural psum over pipe yields the true totals.
        mask = (lax.axis_index(pipe_axis) == s - 1).astype(per.dtype)
        loss_sum = mask * (per * w).sum()
        correct = mask * ((jnp.argmax(logits, -1) == targets) * w).sum()
        count = mask * w.sum()
        axes = (pipe_axis,) if data_axis is None else (pipe_axis, data_axis)
        return (lax.psum(loss_sum, axes), lax.psum(correct, axes),
                lax.psum(count, axes))

    # Eval reads params only; optimizer shards ride through untouched, so
    # the spec tree must mirror the train step's state layout exactly.
    state_specs = jax.tree.map(
        lambda x: x.sharding.spec, state,
        is_leaf=lambda x: hasattr(x, "sharding"))
    tok_spec = P(data_axis) if data_axis is not None else P()
    return jax.jit(jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(state_specs, tok_spec, tok_spec, tok_spec),
        out_specs=(P(), P(), P()),
        check_vma=False,
    ))

"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pipe``
mesh axis, built from ``lax.scan`` + ``lax.ppermute`` inside ``shard_map``.

Beyond-parity capability (the reference runs a single ``model(data)`` call
per step — no pipelining anywhere, SURVEY.md §2.2).  TPU-first design: the
transformer's blocks are *stacked* into one ``(L, ...)`` pytree and sharded
over the ``pipe`` axis, so each device owns ``L/S`` contiguous layers.
Activations travel stage-to-stage over the ICI ring via ``ppermute``; the
schedule is the classic GPipe fill-drain loop over ``M`` microbatches in
``M + S - 1`` ticks, expressed as a single ``lax.scan`` so the whole
pipeline (forward AND backward) is one compiled XLA program.

Autodiff gives the backward pipeline for free: the transpose of
``ppermute`` is the reverse-ring ``ppermute``, so cotangents flow from the
loss (computed on the last stage only, masked elsewhere) back through each
stage, depositing exactly that stage's block gradients on its own device.
Shared params (embedding / final LayerNorm / tied head) receive gradient
contributions only on the stages that actually use them (stage 0: lookup,
stage S-1: head), and one structural ``psum`` over the pipe axis assembles
the full gradient — no double counting, verified against the single-device
oracle in tests/test_pipeline.py.

Known non-goal (documented): this is GPipe (fill/drain bubble of
``(S-1)/(M+S-1)``), not interleaved/looping 1F1B — the schedule slot is a
clean extension point and the bubble shrinks with more microbatches.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudp.mesh import DATA_AXIS

PIPE_AXIS = "pipe"


def stack_block_params(params: dict, num_layers: int, prefix: str = "h_") -> dict:
    """Re-layout standard GPT-2 params (``h_0`` .. ``h_{L-1}`` subtrees)
    into a pipeline layout: one stacked ``blocks`` pytree with a leading
    ``(L, ...)`` layer axis (the axis the ``pipe`` mesh dimension shards),
    alongside the shared (non-block) params."""
    blocks = [params[f"{prefix}{i}"] for i in range(num_layers)]
    out = {k: v for k, v in params.items() if not k.startswith(prefix)}
    out["blocks"] = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    return out


def unstack_block_params(params_pp: dict, prefix: str = "h_") -> dict:
    """Inverse of :func:`stack_block_params` (checkpoint interop)."""
    blocks = params_pp["blocks"]
    num_layers = jax.tree.leaves(blocks)[0].shape[0]
    out = {k: v for k, v in params_pp.items() if k != "blocks"}
    for i in range(num_layers):
        out[f"{prefix}{i}"] = jax.tree.map(lambda x: x[i], blocks)
    return out


def _map_params_subtrees(node: Any, params_struct, fn: Callable) -> Any:
    """Apply ``fn`` to every subtree of ``node`` whose pytree structure
    equals the param tree's (e.g. the SGD momentum trace inside an optax
    state), rebuilding containers around everything else."""
    if jax.tree.structure(node) == params_struct:
        return fn(node)
    if isinstance(node, tuple) and hasattr(node, "_fields"):  # NamedTuple
        return type(node)(*(
            _map_params_subtrees(c, params_struct, fn) for c in node))
    if isinstance(node, (tuple, list)):
        return type(node)(
            _map_params_subtrees(c, params_struct, fn) for c in node)
    if isinstance(node, dict):
        return {k: _map_params_subtrees(v, params_struct, fn)
                for k, v in node.items()}
    return node


def pipeline_spec_tree(tree: Any, pipe_axis: str = PIPE_AXIS) -> Any:
    """Per-leaf shard_map specs for a pipeline-layout pytree: leaves under a
    ``blocks`` key shard their leading (layer) axis over ``pipe``; everything
    else is replicated."""

    def one(path, _leaf):
        keys = [getattr(p, "key", getattr(p, "name", None)) for p in path]
        return P(pipe_axis) if "blocks" in keys else P()

    return jax.tree_util.tree_map_with_path(one, tree)


def gpipe(
    stage_params: Any,
    x_microbatches: jnp.ndarray,
    block_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    axis_name: str = PIPE_AXIS,
) -> jnp.ndarray:
    """Run the GPipe schedule inside ``shard_map``.

    Args:
      stage_params: this device's ``(L/S, ...)`` stacked slice of block params.
      x_microbatches: ``(M, mb, ...)`` microbatched input, replicated over
        the pipe axis (only stage 0 reads it).
      block_fn: ``(one_layer_params, x) -> x`` — applied sequentially over
        this stage's layers.
      axis_name: the pipe mesh axis.

    Returns:
      ``(M, mb, ...)`` outputs of the final stage — VALID ONLY on the last
      stage (zeros elsewhere); callers mask their loss with
      ``lax.axis_index(axis_name) == S - 1`` and ``psum`` the result.
    """
    s = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    perm = [(j, (j + 1) % s) for j in range(s)]

    def stage_apply(x):
        return lax.scan(lambda h, p: (block_fn(p, h), None), x, stage_params)[0]

    def tick(carry, t):
        incoming, outputs = carry
        # Stage 0 ingests microbatch t while t < M (garbage afterwards is
        # never written); later stages consume what arrived on the ring.
        x0 = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(t, 0, m - 1), 0, keepdims=False)
        inp = jnp.where(idx == 0, x0, incoming)
        out = stage_apply(inp)
        # The last stage emits microbatch t-(S-1) once the pipe has filled.
        out_idx = jnp.clip(t - (s - 1), 0, m - 1)
        write = (idx == s - 1) & (t >= s - 1)
        prev = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, out, prev), out_idx, 0)
        return (lax.ppermute(out, axis_name, perm), outputs), None

    init = (
        jnp.zeros_like(x_microbatches[0]),
        jnp.zeros_like(x_microbatches),
    )
    (_, outputs), _ = lax.scan(tick, init, jnp.arange(m + s - 1))
    return outputs


def make_pp_eval_step(
    model,
    mesh: Mesh,
    state,
    *,
    n_microbatches: int,
    data_axis: str | None = DATA_AXIS,
    pipe_axis: str = PIPE_AXIS,
):
    """Pipelined eval: ``(state, tokens, targets, weights) -> (loss_sum,
    correct, count)`` with the Trainer's eval contract, so a PP run gets the
    reference's post-epoch test summary (``src/Part 2a/main.py:130-145``).
    ``state`` must already be in the pipeline layout (stacked ``blocks``)."""
    from tpudp.models.gpt2 import Block, embed_tokens, lm_head

    cfg = model.config
    s = mesh.shape[pipe_axis]
    block_fn = lambda p, x: Block(cfg).apply({"params": p}, x)

    def body(st, tokens, targets, weights):
        import optax

        b, t = tokens.shape
        mb = b // n_microbatches
        params = st.params
        x = embed_tokens(cfg, params, tokens)
        x_mb = x.reshape(n_microbatches, mb, t, cfg.d_model)
        h = gpipe(params["blocks"], x_mb, block_fn, pipe_axis)
        h = h.reshape(b, t, cfg.d_model)
        logits = lm_head(cfg, params, h)
        per = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        w = jnp.broadcast_to(weights[:, None], per.shape)
        # Only the last stage saw real pipeline outputs; zero elsewhere so
        # the structural psum over the pipe axis yields the true totals.
        mask = (lax.axis_index(pipe_axis) == s - 1).astype(per.dtype)
        loss_sum = mask * (per * w).sum()
        correct = mask * ((jnp.argmax(logits, -1) == targets) * w).sum()
        count = mask * w.sum()
        axes = (pipe_axis,) if data_axis is None else (pipe_axis, data_axis)
        return (lax.psum(loss_sum, axes), lax.psum(correct, axes),
                lax.psum(count, axes))

    state_specs = pipeline_spec_tree(state, pipe_axis)
    tok_spec = P(data_axis) if data_axis is not None else P()
    return jax.jit(jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(state_specs, tok_spec, tok_spec, tok_spec),
        out_specs=(P(), P(), P()),
        check_vma=False,
    ))


def make_pp_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    state,
    *,
    n_microbatches: int,
    data_axis: str | None = DATA_AXIS,
    pipe_axis: str = PIPE_AXIS,
    donate: bool = True,
    remat: bool = False,
):
    """DP x PP train step for tpudp.models.gpt2.GPT2.

    ``remat=True`` rematerializes each block during backward
    (``jax.checkpoint`` around the per-layer apply): the scan then stashes
    only the per-tick block inputs instead of every intermediate inside
    every block, which is the activation term that dominates PP memory at
    large microbatch counts.

    Takes a standard (single-device-layout) TrainState, re-lays params and
    momentum out into the stacked pipeline layout, shards blocks over the
    ``pipe`` mesh axis and the batch over ``data``, and returns
    ``(pp_state, step_fn)`` with ``step_fn(state, tokens, targets) ->
    (state, loss)`` — the same contract as every other rung, so the Trainer
    drives it unchanged.

    The optimizer update runs inside the shard_map on each device's local
    shard: SGD/weight-decay/momentum are elementwise, so sharded application
    is exact.
    """
    from tpudp.models.gpt2 import Block, embed_tokens, lm_head

    cfg = getattr(model, "config", None)
    if cfg is None or not hasattr(cfg, "num_layers"):
        raise TypeError(
            "make_pp_train_step drives tpudp.models.gpt2.GPT2 (a model with "
            f"a GPT2Config at .config); got {type(model).__name__}")
    if cfg.attn_impl == "ring" or cfg.mlp_impl != "dense":
        raise ValueError(
            "pipeline parallelism supports dense/flash attention and dense "
            f"MLP blocks; got attn_impl={cfg.attn_impl!r} "
            f"mlp_impl={cfg.mlp_impl!r} (compose PP with SP/EP on separate "
            "mesh axes instead)")
    num_layers = cfg.num_layers
    missing = [f"h_{i}" for i in range(num_layers) if f"h_{i}" not in state.params]
    if missing:
        raise ValueError(
            f"params are missing block subtrees {missing[:3]}... — expected "
            f"the GPT-2 layout h_0..h_{num_layers - 1}")
    s = mesh.shape[pipe_axis]
    if num_layers % s != 0:
        raise ValueError(f"{num_layers} layers not divisible by {s} stages")

    def relayout(tree):
        return stack_block_params(tree, num_layers)

    pp_params = relayout(state.params)
    # Momentum (and any other params-shaped optimizer leaves) re-lays out
    # with its params so a resumed mid-training state keeps its trajectory.
    params_struct = jax.tree.structure(state.params)
    pp_opt = _map_params_subtrees(state.opt_state, params_struct, relayout)
    pp_state = state.replace(params=pp_params, opt_state=pp_opt)

    block_fn = lambda p, x: Block(cfg).apply({"params": p}, x)
    if remat:
        block_fn = jax.checkpoint(block_fn)

    def body(st, tokens, targets):
        b, t = tokens.shape
        if b % n_microbatches:
            raise ValueError(
                f"per-data-shard batch {b} not divisible by "
                f"{n_microbatches} microbatches")
        mb = b // n_microbatches
        sidx = lax.axis_index(pipe_axis)
        last = s - 1

        def loss_fn(params):
            x = embed_tokens(cfg, params, tokens)
            x_mb = x.reshape(n_microbatches, mb, t, cfg.d_model)
            h = gpipe(params["blocks"], x_mb, block_fn, pipe_axis)
            h = h.reshape(b, t, cfg.d_model)
            logits = lm_head(cfg, params, h)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()
            # Only the last stage saw real outputs; mask so dead-stage
            # garbage carries zero loss and zero gradient.
            return jnp.where(sidx == last, ce, 0.0)

        loss, grads = jax.value_and_grad(loss_fn)(st.params)
        # Assemble: shared-param grads live on the stages that produced them
        # (stage 0: embedding lookup; last: head) -> structural psum over
        # pipe; block grads are already stage-local. Then mean over data.
        grads = jax.tree_util.tree_map_with_path(
            lambda path, g: g if "blocks" in jax.tree_util.keystr(path)
            else lax.psum(g, pipe_axis),
            grads)
        loss = lax.psum(loss, pipe_axis)
        if data_axis is not None:
            grads = jax.tree.map(lambda g: lax.pmean(g, data_axis), grads)
            loss = lax.pmean(loss, data_axis)
        updates, new_opt = tx.update(grads, st.opt_state, st.params)
        new_params = optax.apply_updates(st.params, updates)
        return st.replace(
            step=st.step + 1,
            params=new_params,
            opt_state=new_opt,
            loss_sum=st.loss_sum + loss,
        ), loss

    pp_state_specs = pipeline_spec_tree(pp_state, pipe_axis)
    tok_spec = P(data_axis) if data_axis is not None else P()

    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pp_state_specs, tok_spec, tok_spec),
        out_specs=(pp_state_specs, P()),
        check_vma=False,
    )
    step = jax.jit(sharded, donate_argnums=(0,) if donate else ())

    from jax.sharding import NamedSharding

    placed = jax.device_put(
        pp_state,
        jax.tree.map(lambda sp: NamedSharding(mesh, sp), pp_state_specs,
                     is_leaf=lambda x: isinstance(x, P)),
    )
    return placed, step

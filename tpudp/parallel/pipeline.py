"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pipe``
mesh axis, built from ``lax.scan`` + ``lax.ppermute`` inside ``shard_map``.

Beyond-parity capability (the reference runs a single ``model(data)`` call
per step — no pipelining anywhere, SURVEY.md §2.2).  TPU-first design: the
transformer's blocks are *stacked* into one ``(L, ...)`` pytree and sharded
over the ``pipe`` axis, so each device owns ``L/S`` contiguous layers.
Activations travel stage-to-stage over the ICI ring via ``ppermute``; the
schedule is the classic GPipe fill-drain loop over ``M`` microbatches in
``M + S - 1`` ticks, expressed as a single ``lax.scan`` so the whole
pipeline (forward AND backward) is one compiled XLA program.

Autodiff gives the backward pipeline for free: the transpose of
``ppermute`` is the reverse-ring ``ppermute``, so cotangents flow from the
loss (computed on the last stage only, masked elsewhere) back through each
stage, depositing exactly that stage's block gradients on its own device.
Shared params (embedding / final LayerNorm / tied head) receive gradient
contributions only on the stages that actually use them (stage 0: lookup,
stage S-1: head), and one structural ``psum`` over the pipe axis assembles
the full gradient — no double counting, verified against the single-device
oracle in tests/test_pipeline.py.

Two schedules are provided (``make_pp_train_step(schedule=...)``):
``'gpipe'`` — the fill/drain loop above, backward derived by autodiff
(activation residuals for all M microbatches live at the fwd/bwd
boundary); and ``'1f1b'`` — :func:`onef1b_loss_and_grads`, a manual
one-forward-one-backward interleave whose per-stage activation stash is
bounded by the STAGE count (``2S-1`` microbatch inputs) independent of M,
recomputing each stage's forward at backward time.  Both match the
single-device oracle exactly (tests/test_pipeline.py).

Deliberate non-goal FOR THE SCAN-BASED SCHEDULES HERE: Megatron-style
INTERLEAVED 1F1B (virtual stages, round-robin chunk placement).  Its
bubble win assumes ramp-phase time slots cost less than steady-state
ones; under ``lax.scan`` every tick compiles to the same fixed program,
so masked ramp ticks cost full price and the interleave would only
lengthen the scan (``M + 2(SV-1)`` ticks vs ``M + 2(S-1)``) without
reducing wall time.  That reasoning is specific to the uniform-tick
constraint, not to TPU: ``tpudp/parallel/schedule.py`` harvests the
interleaved bubble by emitting the schedule as an UNROLLED per-tick MPMD
program (ramp ticks trace no backward, dead chunk slots trace nothing)
and adds the in-step DP-sharded optimizer update — select it with
``make_pp_train_step``'s strategy-level twin,
``build_strategy('pp', ..., schedule='1f1b_mpmd')``.  The scan schedules
remain the right choice when program size (compile time) matters more
than the bubble.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudp.mesh import DATA_AXIS

PIPE_AXIS = "pipe"


def stack_block_params(params: dict, num_layers: int, prefix: str = "h_") -> dict:
    """Re-layout standard GPT-2 params (``h_0`` .. ``h_{L-1}`` subtrees)
    into a pipeline layout: one stacked ``blocks`` pytree with a leading
    ``(L, ...)`` layer axis (the axis the ``pipe`` mesh dimension shards),
    alongside the shared (non-block) params."""
    blocks = [params[f"{prefix}{i}"] for i in range(num_layers)]
    out = {k: v for k, v in params.items() if not k.startswith(prefix)}
    out["blocks"] = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    return out


def unstack_block_params(params_pp: dict, prefix: str = "h_") -> dict:
    """Inverse of :func:`stack_block_params` (checkpoint interop)."""
    blocks = params_pp["blocks"]
    num_layers = jax.tree.leaves(blocks)[0].shape[0]
    out = {k: v for k, v in params_pp.items() if k != "blocks"}
    for i in range(num_layers):
        out[f"{prefix}{i}"] = jax.tree.map(lambda x: x[i], blocks)
    return out


def _map_params_subtrees(node: Any, params_struct, fn: Callable) -> Any:
    """Apply ``fn`` to every subtree of ``node`` whose pytree structure
    equals the param tree's (e.g. the SGD momentum trace inside an optax
    state), rebuilding containers around everything else."""
    if jax.tree.structure(node) == params_struct:
        return fn(node)
    if isinstance(node, tuple) and hasattr(node, "_fields"):  # NamedTuple
        return type(node)(*(
            _map_params_subtrees(c, params_struct, fn) for c in node))
    if isinstance(node, (tuple, list)):
        return type(node)(
            _map_params_subtrees(c, params_struct, fn) for c in node)
    if isinstance(node, dict):
        return {k: _map_params_subtrees(v, params_struct, fn)
                for k, v in node.items()}
    return node


def pipeline_spec_tree(tree: Any, pipe_axis: str = PIPE_AXIS) -> Any:
    """Per-leaf shard_map specs for a pipeline-layout pytree: leaves under a
    ``blocks`` key shard their leading (layer) axis over ``pipe``; everything
    else is replicated."""

    def one(path, _leaf):
        keys = [getattr(p, "key", getattr(p, "name", None)) for p in path]
        return P(pipe_axis) if "blocks" in keys else P()

    return jax.tree_util.tree_map_with_path(one, tree)


def gpipe(
    stage_params: Any,
    x_microbatches: jnp.ndarray,
    block_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    axis_name: str = PIPE_AXIS,
) -> jnp.ndarray:
    """Run the GPipe schedule inside ``shard_map``.

    Args:
      stage_params: this device's ``(L/S, ...)`` stacked slice of block params.
      x_microbatches: ``(M, mb, ...)`` microbatched input, replicated over
        the pipe axis (only stage 0 reads it).
      block_fn: ``(one_layer_params, x) -> x`` — applied sequentially over
        this stage's layers.
      axis_name: the pipe mesh axis.

    Returns:
      ``(M, mb, ...)`` outputs of the final stage — VALID ONLY on the last
      stage (zeros elsewhere); callers mask their loss with
      ``lax.axis_index(axis_name) == S - 1`` and ``psum`` the result.
    """
    s = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    perm = [(j, (j + 1) % s) for j in range(s)]

    def stage_apply(x):
        return lax.scan(lambda h, p: (block_fn(p, h), None), x, stage_params)[0]

    def tick(carry, t):
        incoming, outputs = carry
        # Stage 0 ingests microbatch t while t < M (garbage afterwards is
        # never written); later stages consume what arrived on the ring.
        x0 = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(t, 0, m - 1), 0, keepdims=False)
        inp = jnp.where(idx == 0, x0, incoming)
        out = stage_apply(inp)
        # The last stage emits microbatch t-(S-1) once the pipe has filled.
        out_idx = jnp.clip(t - (s - 1), 0, m - 1)
        write = (idx == s - 1) & (t >= s - 1)
        prev = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, out, prev), out_idx, 0)
        return (lax.ppermute(out, axis_name, perm), outputs), None

    init = (
        jnp.zeros_like(x_microbatches[0]),
        jnp.zeros_like(x_microbatches),
    )
    (_, outputs), _ = lax.scan(tick, init, jnp.arange(m + s - 1))
    return outputs


def onef1b_loss_and_grads(
    cfg,
    params: dict,
    tokens: jnp.ndarray,
    targets: jnp.ndarray,
    n_microbatches: int,
    block_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    axis_name: str = PIPE_AXIS,
) -> tuple[jnp.ndarray, dict]:
    """One-forward-one-backward pipeline schedule with O(stages) activation
    memory, inside ``shard_map``.

    The GPipe path (:func:`gpipe` + autodiff) stashes residuals for ALL
    ``M`` microbatches before any backward runs — activation memory grows
    with M, which defeats the point of raising M to shrink the bubble.
    This schedule interleaves: a microbatch's backward starts as soon as
    its forward reaches the last stage, so stage ``s`` holds at most
    ``2(S-1-s)+1 <= 2S-1`` in-flight microbatch INPUTS — bounded by the
    stage count, independent of M.

    Mechanics (one ``lax.scan`` over ``M + 2(S-1)`` ticks; every tick does
    at most one stage-forward and one stage-backward):

      * Forward of microbatch ``m`` runs on stage ``s`` at tick ``s + m``;
        activations travel the ICI ring via forward ``ppermute``.  The
        stage INPUT is stashed in a ``2S-1``-slot ring buffer (slots are
        collision-free: a slot is always consumed before its reuse tick).
      * Backward of ``m`` runs on stage ``s`` at tick ``2(S-1) - s + m``
        (the last stage backs up the microbatch the same tick it forwards
        it); cotangents travel the reverse ring.
      * The backward recomputes the stage forward from the stashed input
        (``jax.vjp`` at backward time) instead of storing residuals —
        1F1B-with-recompute: one extra stage-forward of FLOPs per
        microbatch buys the O(S) memory bound.
      * Shared params: the embedding vjp accumulates on stage 0, the
        head/final-LN vjp on the last stage; the caller's structural psum
        over the pipe axis assembles them exactly as in the GPipe path.

    Returns ``(loss, grads)`` with the same contract as
    ``jax.value_and_grad(loss_fn)`` in :func:`make_pp_train_step`: the mean
    CE loss (nonzero only on the last stage, psum-assembled by the caller)
    and a gradient tree structured like ``params``.
    """
    from tpudp.models.gpt2 import embed_tokens, lm_head

    s_size = lax.axis_size(axis_name)
    sidx = lax.axis_index(axis_name)
    last = s_size - 1
    b, t = tokens.shape
    m_count = n_microbatches
    mb = b // m_count
    slots = 2 * s_size - 1
    blocks = params["blocks"]
    shared = {k: v for k, v in params.items() if k != "blocks"}

    tok_mb = tokens.reshape(m_count, mb, t)
    tgt_mb = targets.reshape(m_count, mb, t)
    fwd_perm = [(j, (j + 1) % s_size) for j in range(s_size)]
    bwd_perm = [((j + 1) % s_size, j) for j in range(s_size)]

    def stage_apply(p_stack, x):
        return lax.scan(lambda h, p: (block_fn(p, h), None), x, p_stack)[0]

    def head_loss(sh, h, tgts):
        """Sum (not mean) CE of one microbatch — normalized once at the end."""
        logits = lm_head(cfg, sh, h)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgts).sum()

    # Probe one embed to get the activation shape/dtype flowing the ring.
    act_proto = jax.eval_shape(lambda sh: embed_tokens(cfg, sh, tok_mb[0]),
                               shared)
    zeros_act = jnp.zeros(act_proto.shape, act_proto.dtype)

    def tick(carry, tt):
        stash, fwd_in, bwd_in, gblocks, gshared, loss_sum = carry

        # ---- forward slot: microbatch tt - sidx ------------------------
        m_f = tt - sidx
        f_active = (m_f >= 0) & (m_f < m_count)
        m_f_c = jnp.clip(m_f, 0, m_count - 1)
        toks_f = lax.dynamic_index_in_dim(tok_mb, m_f_c, 0, keepdims=False)
        x = jnp.where(sidx == 0, embed_tokens(cfg, shared, toks_f), fwd_in)
        slot_f = m_f_c % slots
        prev = lax.dynamic_index_in_dim(stash, slot_f, 0, keepdims=False)
        stash = lax.dynamic_update_index_in_dim(
            stash, jnp.where(f_active, x, prev), slot_f, 0)
        y = stage_apply(blocks, x)

        # ---- backward slot: microbatch tt - (2(S-1) - sidx) ------------
        m_b = tt - (2 * (s_size - 1) - sidx)
        b_active = (m_b >= 0) & (m_b < m_count)
        m_b_c = jnp.clip(m_b, 0, m_count - 1)
        slot_b = m_b_c % slots
        # For the last stage slot_b == slot_f this tick (written above), so
        # the read below sees the microbatch it just forwarded.
        x_b = lax.dynamic_index_in_dim(stash, slot_b, 0, keepdims=False)
        toks_b = lax.dynamic_index_in_dim(tok_mb, m_b_c, 0, keepdims=False)
        tgts_b = lax.dynamic_index_in_dim(tgt_mb, m_b_c, 0, keepdims=False)

        # Last stage only: loss + its cotangent from THIS tick's forward
        # output.  lax.cond (runtime per-device predicate, collective-free
        # branches) so the other S-1 stages never run the (mb, t, vocab)
        # head matmul + pullback — without it the head would execute
        # S*(M+2S-2) times per step instead of M.
        def _head(operands):
            sh, h, tg = operands
            loss_mb, head_vjp = jax.vjp(
                lambda sh_, h_: head_loss(sh_, h_, tg), sh, h)
            dsh, dy_h = head_vjp(jnp.ones((), loss_mb.dtype))
            return loss_mb, dsh, dy_h

        def _head_zero(operands):
            sh, h, _tg = operands
            return (jnp.zeros((), jnp.float32),
                    jax.tree.map(jnp.zeros_like, sh), jnp.zeros_like(h))

        loss_mb, dshared_head, dy_head = lax.cond(
            (sidx == last) & b_active, _head, _head_zero, (shared, y, tgts_b))
        dy = jnp.where(sidx == last, dy_head, bwd_in)
        dy = jnp.where(b_active, dy, jnp.zeros_like(dy))

        # Stage backward, recomputing the forward from the stashed input.
        _, stage_vjp = jax.vjp(stage_apply, blocks, x_b)
        dblocks, dx = stage_vjp(dy)
        gblocks = jax.tree.map(lambda a, g: a + g, gblocks, dblocks)

        # Stage 0 only: convert the input cotangent into embedding grads.
        def _embed(operands):
            sh, tk, d = operands
            _, embed_vjp = jax.vjp(lambda sh_: embed_tokens(cfg, sh_, tk), sh)
            (dsh,) = embed_vjp(d)
            return dsh

        def _embed_zero(operands):
            sh, _tk, _d = operands
            return jax.tree.map(jnp.zeros_like, sh)

        dshared_embed = lax.cond(
            (sidx == 0) & b_active, _embed, _embed_zero,
            (shared, toks_b, dx))
        gshared = jax.tree.map(
            lambda a, ge, gh: a + ge + gh,
            gshared, dshared_embed, dshared_head)
        loss_sum = loss_sum + loss_mb

        return (stash, lax.ppermute(y, axis_name, fwd_perm),
                lax.ppermute(dx, axis_name, bwd_perm),
                gblocks, gshared, loss_sum), None

    init = (
        jnp.zeros((slots,) + zeros_act.shape, zeros_act.dtype),
        zeros_act,
        zeros_act,
        jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), blocks),
        jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), shared),
        jnp.zeros((), jnp.float32),
    )
    (_, _, _, gblocks, gshared, loss_sum), _ = lax.scan(
        tick, init, jnp.arange(m_count + 2 * (s_size - 1)))

    denom = jnp.asarray(b * t, jnp.float32)  # sum -> mean normalization
    grads = {**{k: jax.tree.map(lambda g: g / denom, v)
                for k, v in gshared.items()},
             "blocks": jax.tree.map(lambda g: g / denom, gblocks)}
    grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)
    return loss_sum / denom, grads


def make_pp_eval_step(
    model,
    mesh: Mesh,
    state,
    *,
    n_microbatches: int,
    data_axis: str | None = DATA_AXIS,
    pipe_axis: str = PIPE_AXIS,
):
    """Pipelined eval: ``(state, tokens, targets, weights) -> (loss_sum,
    correct, count)`` with the Trainer's eval contract, so a PP run gets the
    reference's post-epoch test summary (``src/Part 2a/main.py:130-145``).
    ``state`` must already be in the pipeline layout (stacked ``blocks``)."""
    from tpudp.models.gpt2 import Block, embed_tokens, lm_head

    cfg = model.config
    s = mesh.shape[pipe_axis]
    block_fn = lambda p, x: Block(cfg).apply({"params": p}, x)

    def body(st, tokens, targets, weights):
        import optax

        b, t = tokens.shape
        mb = b // n_microbatches
        params = st.params
        x = embed_tokens(cfg, params, tokens)
        x_mb = x.reshape(n_microbatches, mb, t, cfg.d_model)
        h = gpipe(params["blocks"], x_mb, block_fn, pipe_axis)
        h = h.reshape(b, t, cfg.d_model)
        logits = lm_head(cfg, params, h)
        per = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        w = jnp.broadcast_to(weights[:, None], per.shape)
        # Only the last stage saw real pipeline outputs; zero elsewhere so
        # the structural psum over the pipe axis yields the true totals.
        mask = (lax.axis_index(pipe_axis) == s - 1).astype(per.dtype)
        loss_sum = mask * (per * w).sum()
        correct = mask * ((jnp.argmax(logits, -1) == targets) * w).sum()
        count = mask * w.sum()
        axes = (pipe_axis,) if data_axis is None else (pipe_axis, data_axis)
        return (lax.psum(loss_sum, axes), lax.psum(correct, axes),
                lax.psum(count, axes))

    state_specs = pipeline_spec_tree(state, pipe_axis)
    tok_spec = P(data_axis) if data_axis is not None else P()
    return jax.jit(jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(state_specs, tok_spec, tok_spec, tok_spec),
        out_specs=(P(), P(), P()),
        check_vma=False,
    ))


def make_pp_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    state,
    *,
    n_microbatches: int,
    data_axis: str | None = DATA_AXIS,
    pipe_axis: str = PIPE_AXIS,
    donate: bool = True,
    remat: bool = False,
    schedule: str = "gpipe",
):
    """DP x PP train step for tpudp.models.gpt2.GPT2.

    ``schedule`` selects the microbatch schedule:
      * ``'gpipe'`` — fill/drain via :func:`gpipe` + autodiff; activation
        residuals for all ``n_microbatches`` are live at the fwd/bwd
        boundary (memory grows with M).
      * ``'1f1b'`` — :func:`onef1b_loss_and_grads`; backward interleaves
        with forward so at most ``2S-1`` microbatch inputs are live per
        stage (memory bounded by the STAGE count), recomputing each
        stage's forward at backward time.  Same gradients to numerical
        tolerance (oracle-parity tested).

    ``remat=True`` rematerializes each block during backward
    (``jax.checkpoint`` around the per-layer apply): the scan then stashes
    only the per-tick block inputs instead of every intermediate inside
    every block, which is the activation term that dominates PP memory at
    large microbatch counts.

    Takes a standard (single-device-layout) TrainState, re-lays params and
    momentum out into the stacked pipeline layout, shards blocks over the
    ``pipe`` mesh axis and the batch over ``data``, and returns
    ``(pp_state, step_fn)`` with ``step_fn(state, tokens, targets) ->
    (state, loss)`` — the same contract as every other rung, so the Trainer
    drives it unchanged.

    The optimizer update runs inside the shard_map on each device's local
    shard: SGD/weight-decay/momentum are elementwise, so sharded application
    is exact.
    """
    from tpudp.models.gpt2 import Block, embed_tokens, lm_head

    cfg = getattr(model, "config", None)
    if cfg is None or not hasattr(cfg, "num_layers"):
        raise TypeError(
            "make_pp_train_step drives tpudp.models.gpt2.GPT2 (a model with "
            f"a GPT2Config at .config); got {type(model).__name__}")
    if cfg.attn_impl == "ring" or cfg.mlp_impl != "dense":
        raise ValueError(
            "pipeline parallelism supports dense/flash attention and dense "
            f"MLP blocks; got attn_impl={cfg.attn_impl!r} "
            f"mlp_impl={cfg.mlp_impl!r} (compose PP with SP/EP on separate "
            "mesh axes instead)")
    num_layers = cfg.num_layers
    missing = [f"h_{i}" for i in range(num_layers) if f"h_{i}" not in state.params]
    if missing:
        raise ValueError(
            f"params are missing block subtrees {missing[:3]}... — expected "
            f"the GPT-2 layout h_0..h_{num_layers - 1}")
    s = mesh.shape[pipe_axis]
    if num_layers % s != 0:
        raise ValueError(f"{num_layers} layers not divisible by {s} stages")
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(
            f"unknown schedule {schedule!r}; choose 'gpipe' or '1f1b'")

    def relayout(tree):
        return stack_block_params(tree, num_layers)

    pp_params = relayout(state.params)
    # Momentum (and any other params-shaped optimizer leaves) re-lays out
    # with its params so a resumed mid-training state keeps its trajectory.
    params_struct = jax.tree.structure(state.params)
    pp_opt = _map_params_subtrees(state.opt_state, params_struct, relayout)
    pp_state = state.replace(params=pp_params, opt_state=pp_opt)

    block_fn = lambda p, x: Block(cfg).apply({"params": p}, x)
    if remat:
        block_fn = jax.checkpoint(block_fn)

    def body(st, tokens, targets):
        b, t = tokens.shape
        if b % n_microbatches:
            raise ValueError(
                f"per-data-shard batch {b} not divisible by "
                f"{n_microbatches} microbatches")
        mb = b // n_microbatches
        sidx = lax.axis_index(pipe_axis)
        last = s - 1

        def loss_fn(params):
            x = embed_tokens(cfg, params, tokens)
            x_mb = x.reshape(n_microbatches, mb, t, cfg.d_model)
            h = gpipe(params["blocks"], x_mb, block_fn, pipe_axis)
            h = h.reshape(b, t, cfg.d_model)
            logits = lm_head(cfg, params, h)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()
            # Only the last stage saw real outputs; mask so dead-stage
            # garbage carries zero loss and zero gradient.
            return jnp.where(sidx == last, ce, 0.0)

        if schedule == "1f1b":
            loss, grads = onef1b_loss_and_grads(
                cfg, st.params, tokens, targets, n_microbatches, block_fn,
                pipe_axis)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(st.params)
        # Assemble: shared-param grads live on the stages that produced them
        # (stage 0: embedding lookup; last: head) -> structural psum over
        # pipe; block grads are already stage-local. Then mean over data.
        grads = jax.tree_util.tree_map_with_path(
            lambda path, g: g if "blocks" in jax.tree_util.keystr(path)
            else lax.psum(g, pipe_axis),
            grads)
        loss = lax.psum(loss, pipe_axis)
        if data_axis is not None:
            grads = jax.tree.map(lambda g: lax.pmean(g, data_axis), grads)
            loss = lax.pmean(loss, data_axis)
        updates, new_opt = tx.update(grads, st.opt_state, st.params)
        new_params = optax.apply_updates(st.params, updates)
        return st.replace(
            step=st.step + 1,
            params=new_params,
            opt_state=new_opt,
            loss_sum=st.loss_sum + loss,
        ), loss

    pp_state_specs = pipeline_spec_tree(pp_state, pipe_axis)
    tok_spec = P(data_axis) if data_axis is not None else P()

    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pp_state_specs, tok_spec, tok_spec),
        out_specs=(pp_state_specs, P()),
        check_vma=False,
    )
    step = jax.jit(sharded, donate_argnums=(0,) if donate else ())

    from jax.sharding import NamedSharding

    placed = jax.device_put(
        pp_state,
        jax.tree.map(lambda sp: NamedSharding(mesh, sp), pp_state_specs,
                     is_leaf=lambda x: isinstance(x, P)),
    )
    return placed, step

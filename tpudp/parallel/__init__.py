"""Parallelism layer: gradient-sync strategy ladder and hand-rolled collectives."""

from tpudp.parallel.sync import SYNC_STRATEGIES, get_sync  # noqa: F401
from tpudp.parallel.ring import ring_all_reduce_mean, ring_all_reduce  # noqa: F401

"""Parallelism layer: gradient-sync strategy ladder and hand-rolled collectives."""

from tpudp.parallel.sync import SYNC_STRATEGIES, get_sync  # noqa: F401
from tpudp.parallel.ring import ring_all_reduce_mean, ring_all_reduce  # noqa: F401
from tpudp.parallel.compress import (Int8EfState,  # noqa: F401
                                     int8_ef_allreduce,
                                     state_partition_specs)

"""Expert parallelism: DP x EP train step for MoE models.

Beyond-parity capability (SURVEY.md §2.2: no MoE anywhere in the
reference).  The mesh is ``(data, expert)``: the token batch shards over
BOTH axes (every device is a data-parallel worker), while the stacked
``(E, ...)`` expert FFN weights shard their leading axis over ``expert``
only — so devices in the same expert-column hold the same experts and
devices in the same data-row hold disjoint ones.  Token routing crosses the
``expert`` axis via ``lax.all_to_all`` inside the model
(tpudp/models/moe.py); this module supplies the matching gradient assembly:

  * shared params (attention, norms, router gate, embeddings): local grads
    mean-reduced over the WHOLE mesh — the plain DP contract.
  * expert params: devices in one expert-column compute grads for the same
    expert slice from different data shards -> mean over ``data`` only,
    then divide by the ``expert``-axis size so the result is the gradient
    of the same global-mean loss the shared params use (other columns
    contribute exactly zero to these experts, so the division replaces the
    missing zero terms of a whole-mesh mean).

Verified against the dense single-device oracle in tests/test_expert.py
(exact trajectory match when capacity is large enough that no token drops;
capacity is a function of local token count, so drop *patterns* — like the
reference's per-rank BatchNorm statistics, SURVEY.md §7 — legitimately
depend on the partitioning).
"""

from __future__ import annotations

from typing import Any

import jax
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudp.mesh import DATA_AXIS

EXPERT_AXIS = "expert"


def expert_spec_tree(tree: Any, expert_axis: str = EXPERT_AXIS) -> Any:
    """Per-leaf specs: stacked expert weights (param names prefixed
    ``experts_``, and their momentum twins) shard their leading E axis over
    ``expert``; everything else replicates."""

    def one(path, _leaf):
        name = jax.tree_util.keystr(path)
        return P(expert_axis) if "experts_" in name else P()

    return jax.tree_util.tree_map_with_path(one, tree)


def make_ep_eval_step(
    model,
    mesh: Mesh,
    state,
    *,
    data_axis: str = DATA_AXIS,
    expert_axis: str = EXPERT_AXIS,
):
    """Expert-parallel eval with the Trainer contract: tokens shard over the
    flattened (data, expert) device grid, expert weights stay sharded, the
    MoE all_to_all fires inside the bound mesh, and the weighted metrics
    psum over the whole mesh."""

    def body(st, tokens, targets, weights):
        from tpudp.train import eval_metrics

        loss_sum, correct, count = eval_metrics(
            model, st, tokens, targets, weights)
        axes = (data_axis, expert_axis)
        return (lax.psum(loss_sum, axes), lax.psum(correct, axes),
                lax.psum(count, axes))

    state_specs = expert_spec_tree(state, expert_axis)
    tok_spec = P((data_axis, expert_axis))
    return jax.jit(jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(state_specs, tok_spec, tok_spec, tok_spec),
        out_specs=(P(), P(), P()),
        check_vma=False,
    ))


def make_ep_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    state,
    *,
    data_axis: str = DATA_AXIS,
    expert_axis: str = EXPERT_AXIS,
    aux_loss_coef: float = 0.01,
    donate: bool = True,
):
    """Build ``(ep_state, step_fn)`` with the framework-wide step contract
    ``step_fn(state, tokens, targets) -> (state, loss)``.

    ``model`` must be built with ``expert_axis=expert_axis`` so its MoE
    layers issue the all_to_all when the axis is bound.

    ``aux_loss_coef`` weights the Switch load-balancing loss the MoE layers
    sow (``E * sum(f_e * P_e)``, minimized at 1 by uniform routing) — it
    keeps the top-1 router from collapsing onto few experts and overflowing
    their capacity.  The returned/logged loss stays the pure CE term so it
    remains comparable across rungs; set 0.0 to disable balancing."""
    n_exp = mesh.shape[expert_axis]
    for path, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]:
        if "experts_" in jax.tree_util.keystr(path) and leaf.shape[0] % n_exp:
            raise ValueError(
                f"{leaf.shape[0]} experts not divisible by expert-axis "
                f"size {n_exp} ({jax.tree_util.keystr(path)})")

    def body(st, tokens, targets):
        def loss_fn(params):
            logits, inter = model.apply(
                {"params": params}, tokens, train=True,
                mutable=["intermediates"])
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()
            aux = 0.0
            if aux_loss_coef:
                from tpudp.models.moe import collect_moe_aux

                aux = aux_loss_coef * collect_moe_aux(inter)
            return ce + aux, ce

        (_, loss), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(st.params)
        grads = jax.tree_util.tree_map_with_path(
            lambda path, g: (
                lax.pmean(g, data_axis) / n_exp
                if "experts_" in jax.tree_util.keystr(path)
                else lax.pmean(g, (data_axis, expert_axis))),
            grads)
        loss = lax.pmean(loss, (data_axis, expert_axis))
        updates, new_opt = tx.update(grads, st.opt_state, st.params)
        new_params = optax.apply_updates(st.params, updates)
        return st.replace(
            step=st.step + 1,
            params=new_params,
            opt_state=new_opt,
            loss_sum=st.loss_sum + loss,
        ), loss

    state_specs = expert_spec_tree(state, expert_axis)
    tok_spec = P((data_axis, expert_axis))

    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(state_specs, tok_spec, tok_spec),
        out_specs=(state_specs, P()),
        check_vma=False,
    )
    step = jax.jit(sharded, donate_argnums=(0,) if donate else ())

    placed = jax.device_put(
        state,
        jax.tree.map(lambda sp: NamedSharding(mesh, sp), state_specs,
                     is_leaf=lambda x: isinstance(x, P)),
    )
    return placed, step

"""Error-feedback gradient compression as optax transforms.

The stateless ``allreduce_int8`` sync rung (tpudp.parallel.sync) drops up
to half a quantization step of every device's gradient each step — a bias
that does not vanish over training.  Error feedback (the standard fix,
kept as state by e.g. torch-DDP's PowerSGD hook) carries each device's
local quantization residual into the next step, so the *time-averaged*
applied update equals the true mean gradient and the bias stays bounded
instead of accumulating.

TPU-native twist: the compressor is an **optax transform**, not a sync
function.  Optax update fns run inside the shard_map'd train step where
the mesh axis is bound, so the collective (the int8-wire ppermute ring)
lives in the optimizer chain.  The residuals are genuinely PER-DEVICE
data, so the state is stored honestly as a stacked ``(N, *shape)`` tree
(an :class:`Int8EfState`) sharded ``P(data)`` over the mesh — never
mislabeled as replicated — and ``make_train_step`` recognizes the state
type and threads the matching shard_map specs
(:func:`state_partition_specs`).  Checkpointing then saves every device's
residual, and restore puts each back where it belongs.

Place the transform FIRST in the chain (it turns per-device gradients
into the compressed cross-device mean; weight decay and momentum then see
identical values on every device) and build the train step with
``sync="none"`` so nothing double-reduces.

Wire cost per step: 1 byte/element per ring hop plus one fp32 scalar
pmax.  Resolution: the shared grid must keep every partial ring sum
within int8 (quantized values clipped to ``+/-(127 // N)``), so effective
precision is ``log2(127 // N)`` bits of the flat buffer's max-abs — the
error feedback is what makes that affordable.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from tpudp.mesh import DATA_AXIS, axis_is_bound
from tpudp.parallel.ring import (flatten_tree, int8_headroom_quantize,
                                 ring_all_reduce)


class Int8EfState(NamedTuple):
    """Per-device EF residuals: every leaf is ``(num_devices, *param_shape)``
    fp32, sharded ``P(axis)`` on the leading dim (device i owns row i)."""

    error: Any


def int8_ef_allreduce(
    axis_name: str = DATA_AXIS,
    num_devices: int | None = None,
) -> optax.GradientTransformation:
    """int8-wire ring all-reduce with error feedback, as an optax transform.

    update: ``corrected_i = g_i / N + error_i`` (per device), quantized on a
    shared grid coarse enough that ring partial sums stay int8
    (``scale = pmax(max|corrected|) / (127 // N)``, values clipped to
    ``+/-(127 // N)``), ring-summed exactly in int8, dequantized to the
    compressed mean; the new ``error_i`` is the local residual
    ``corrected_i - q_i * scale``.

    ``num_devices`` (the mesh's ``axis_name`` size) is required at init
    time to allocate the stacked per-device state.  The update must run
    inside a shard_map with ``axis_name`` bound and the state sharded via
    :func:`state_partition_specs` (``make_train_step`` does both).
    """

    def init_fn(params):
        if num_devices is None:
            raise ValueError(
                "int8_ef_allreduce needs num_devices (the mesh axis size) "
                "at construction to allocate the per-device error state — "
                "pass make_optimizer(compress_devices=mesh.shape['data'])")
        return Int8EfState(error=jax.tree.map(
            lambda p: jnp.zeros((num_devices,) + p.shape, jnp.float32),
            params))

    def update_fn(updates, state, params=None):
        del params
        if not axis_is_bound(axis_name):
            raise ValueError(
                f"int8_ef_allreduce needs mesh axis {axis_name!r} bound — "
                "use a shard_map DP step (sync='none'), not gspmd/single")
        n = lax.axis_size(axis_name)
        # Inside shard_map each device sees its (1, *shape) row of the
        # stacked state; squeeze for the math, restore on the way out.
        e_local = jax.tree.map(lambda e: e[0], state.error)
        corrected = jax.tree.map(
            lambda g, e: g.astype(jnp.float32) / n + e, updates, e_local)
        flat, unflatten = flatten_tree(corrected)
        # Shared +/-(127 // N) headroom grid (int8_headroom_quantize): a
        # wrapped ring total here could not even be repaired by the error
        # feedback, which only sees the device's own q.  The EF residual
        # absorbs the rounding AND any clipping.
        q, scale = int8_headroom_quantize(flat, axis_name)
        total = ring_all_reduce(q, axis_name)  # int8 wire, exact adds
        mean = unflatten(total.astype(jnp.float32) * scale, cast=False)
        err = unflatten(flat - q.astype(jnp.float32) * scale, cast=False)
        mean = jax.tree.map(lambda m, g: m.astype(g.dtype), mean, updates)
        return mean, Int8EfState(error=jax.tree.map(
            lambda e: e[None], err))

    return optax.GradientTransformation(init_fn, update_fn)


def state_partition_specs(state, data_axis: str = DATA_AXIS):
    """shard_map PartitionSpecs for a TrainState(-like) pytree: ``P()``
    (replicated) everywhere EXCEPT :class:`Int8EfState` subtrees, whose
    stacked per-device leaves shard their leading dim over ``data_axis``.
    The single source ``make_train_step`` uses so per-device optimizer
    state is never mislabeled as replicated."""
    return jax.tree.map(
        lambda node: (jax.tree.map(lambda _: P(data_axis), node)
                      if isinstance(node, Int8EfState)
                      else P()),
        state, is_leaf=lambda node: isinstance(node, Int8EfState))


def has_per_device_state(state) -> bool:
    """Does this (Train)state contain stacked per-device optimizer state?"""
    found = False

    def visit(node):
        nonlocal found
        if isinstance(node, Int8EfState):
            found = True
        return node

    jax.tree.map(visit, state,
                 is_leaf=lambda node: isinstance(node, Int8EfState))
    return found

"""Ring attention: sequence-parallel exact attention for long contexts.

The reference has no sequence dimension at all (SURVEY.md §5: "long-context /
sequence parallelism: absent entirely") — this is a first-class tpudp
capability, not a port.  Sequences are sharded along a mesh axis; each device
holds one contiguous block of Q/K/V.  K/V blocks circulate around the ring
via ``lax.ppermute`` while each device accumulates its Q block's attention
with a numerically-stable online softmax (flash-attention style running
max / denominator), so attention over a sequence of length ``N * t_local``
never materializes more than a ``t_local x t_local`` score tile per step and
the ICI ring carries each K/V block exactly once.

Causal masking uses *global* positions reconstructed from the ring step and
``lax.axis_index``, so the sharded result matches single-device causal
attention exactly (see tests/test_ring_attention.py).

Known non-goal (documented): causal ring attention has the classic tail
imbalance (later blocks do more useful work); zigzag/striped block layouts
rebalance it and can be layered on the same primitive later.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    *,
    causal: bool = True,
) -> jnp.ndarray:
    """Blockwise ring attention inside ``shard_map``.

    Args:
      q, k, v: local blocks, shape ``(batch, t_local, heads, head_dim)``;
        the global sequence is the concatenation of blocks in mesh order.
      axis_name: mesh axis the sequence is sharded over.
      causal: apply a global causal mask.

    Returns:
      Local attention output block ``(batch, t_local, heads, head_dim)``.
    """
    n = lax.axis_size(axis_name)
    i = lax.axis_index(axis_name)
    b, t, h, dh = q.shape
    scale = dh ** -0.5
    q32 = q.astype(jnp.float32) * scale

    # Online-softmax state: running max m, denominator l, accumulator o.
    m = jnp.full((b, h, t), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, t), jnp.float32)
    o = jnp.zeros((b, t, h, dh), jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]
    kv = (k, v)
    local_pos = jnp.arange(t)
    q_pos = i * t + local_pos

    for s in range(n):
        k_blk, v_blk = kv
        src = (i - s) % n  # ring origin of the block currently held
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if causal:
            k_pos = src * t + local_pos
            mask = q_pos[:, None] >= k_pos[None, :]  # (t_q, t_k), global
            logits = jnp.where(mask[None, None], logits, _NEG_INF)
        blk_max = logits.max(axis=-1)  # (b, h, t)
        m_new = jnp.maximum(m, blk_max)
        # exp(_NEG_INF - m_new) underflows to 0, which is exactly right for
        # not-yet-seen rows; fully-masked tiles are re-zeroed via the mask.
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        m = m_new
        if s < n - 1:
            kv = lax.ppermute(kv, axis_name, perm)

    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def dense_causal_attention(q, k, v):
    """Single-device reference implementation (the equivalence oracle)."""
    b, t, h, dh = q.shape
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * dh ** -0.5
    mask = jnp.tril(jnp.ones((t, t), bool))
    logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jnp.exp(logits - logits.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)

"""Native (C++/OpenMP) host data-path kernels, loaded via ctypes.

The shared library is built lazily from ``augment.cpp`` with the system
``g++`` on first use (sub-second) and cached next to the source; any failure
(no compiler, exotic platform) degrades silently to the pure-numpy path in
``tpudp.data.loader`` — the two paths are bit-identical by construction
(Python draws the random crop/flip decisions for both; see augment.cpp).

This is the framework's analogue of the native layer the reference borrows
from its dependencies (torch's C++ DataLoader workers + torchvision
transforms, ``src/Part 2a/main.py:24-44``) — here it is first-party,
in-process, and fused.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "augment.cpp")
_LIB = os.path.join(_DIR, "_tpudp_native.so")
_ABI_VERSION = 1

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_attempted = False


def _build() -> None:
    # Unlink first: dlopen caches by path/inode, so rebuilding in place and
    # re-CDLL'ing would hand back the stale already-loaded handle.
    try:
        os.unlink(_LIB)
    except FileNotFoundError:
        pass
    subprocess.run(
        ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-fopenmp",
         "-ffp-contract=off", "-o", _LIB, _SRC],
        check=True, capture_output=True,
    )


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64, f32p = ctypes.c_int64, ctypes.POINTER(ctypes.c_float)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p, i64p = ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64)
    lib.tpudp_augment_normalize.argtypes = [
        u8p, f32p, i32p, u8p, i64, i64, i64, i64, i64, i64, i64, f32p, f32p]
    lib.tpudp_augment_normalize.restype = None
    lib.tpudp_normalize.argtypes = [u8p, f32p, i64, i64, f32p, f32p]
    lib.tpudp_normalize.restype = None
    lib.tpudp_gather_u8.argtypes = [u8p, i64p, u8p, i64, i64]
    lib.tpudp_gather_u8.restype = None
    lib.tpudp_native_abi_version.restype = ctypes.c_int
    return lib


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_attempted
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        try:
            stale = (not os.path.exists(_LIB)
                     or os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
            if stale:
                _build()
            lib = _bind(ctypes.CDLL(_LIB))
            if lib.tpudp_native_abi_version() != _ABI_VERSION:
                _build()
                lib = _bind(ctypes.CDLL(_LIB))
                if lib.tpudp_native_abi_version() != _ABI_VERSION:
                    lib = None  # stale handle survived; use the numpy path
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def augment_normalize(
    images_u8: np.ndarray,
    offsets: np.ndarray,
    flips: np.ndarray,
    mean: np.ndarray,
    std: np.ndarray,
    *,
    out_hw: tuple[int, int] | None = None,
    pad: int = 4,
) -> np.ndarray:
    """Fused pad->crop->flip->normalize: uint8 (B,Hi,Wi,C) -> f32 (B,Ho,Wo,C).

    ``offsets`` are (B,2) crop origins in the zero-padded frame, ``flips``
    (B,) booleans — the caller draws both (see loader.draw_augment_params)
    so numpy and native paths share one RNG stream.
    """
    lib = load()
    assert lib is not None, "native library unavailable"
    b, hi, wi, c = images_u8.shape
    ho, wo = out_hw if out_hw is not None else (hi, wi)
    images_u8 = np.ascontiguousarray(images_u8)
    assert images_u8.dtype == np.uint8, images_u8.dtype
    offsets = np.ascontiguousarray(offsets, dtype=np.int32)
    flips = np.ascontiguousarray(flips, dtype=np.uint8)
    mean = np.ascontiguousarray(mean, dtype=np.float32)
    std = np.ascontiguousarray(std, dtype=np.float32)
    out = np.empty((b, ho, wo, c), dtype=np.float32)
    lib.tpudp_augment_normalize(
        _ptr(images_u8, ctypes.c_uint8), _ptr(out, ctypes.c_float),
        _ptr(offsets, ctypes.c_int32), _ptr(flips, ctypes.c_uint8),
        b, hi, wi, ho, wo, c, pad,
        _ptr(mean, ctypes.c_float), _ptr(std, ctypes.c_float))
    return out


def normalize(images_u8: np.ndarray, mean: np.ndarray, std: np.ndarray) -> np.ndarray:
    """uint8 (..., C) -> normalized float32, the ToTensor+Normalize pair."""
    lib = load()
    assert lib is not None, "native library unavailable"
    images_u8 = np.ascontiguousarray(images_u8)
    assert images_u8.dtype == np.uint8, images_u8.dtype
    c = images_u8.shape[-1]
    n = images_u8.size // c
    mean = np.ascontiguousarray(mean, dtype=np.float32)
    std = np.ascontiguousarray(std, dtype=np.float32)
    out = np.empty(images_u8.shape, dtype=np.float32)
    lib.tpudp_normalize(_ptr(images_u8, ctypes.c_uint8),
                        _ptr(out, ctypes.c_float), n, c,
                        _ptr(mean, ctypes.c_float), _ptr(std, ctypes.c_float))
    return out


def gather(data: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Parallel ``data[idx]`` for a C-contiguous uint8 array of samples."""
    lib = load()
    assert lib is not None, "native library unavailable"
    data = np.ascontiguousarray(data)
    assert data.dtype == np.uint8
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    sample_bytes = int(np.prod(data.shape[1:]))
    out = np.empty((len(idx), *data.shape[1:]), dtype=np.uint8)
    lib.tpudp_gather_u8(_ptr(data, ctypes.c_uint8), _ptr(idx, ctypes.c_int64),
                        _ptr(out, ctypes.c_uint8), len(idx), sample_bytes)
    return out

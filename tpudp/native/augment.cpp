// Native host data-path kernels for tpudp (C++/OpenMP).
//
// TPU-native replacement for the capability the reference gets from torch's
// C++ DataLoader worker pool (src/Part 2a/main.py:39-44: num_workers=2,
// pin_memory) and torchvision's per-sample C transforms
// (src/Part 2a/main.py:24-31: RandomCrop(32, padding=4) ->
// RandomHorizontalFlip -> ToTensor -> Normalize).  One fused pass over the
// uint8 batch produces the normalized float32 NHWC tensor XLA wants, with
// OpenMP supplying the worker-pool parallelism in-process (no IPC, no
// per-sample Python).
//
// Random decisions (crop origins, flip flags) are made by the caller in
// Python so the numpy fallback path and this kernel are bit-identical given
// the same RNG stream.  Float math is ordered exactly like the numpy path
// ((x / 255 - mean) / std, all fp32) and the build disables FP contraction,
// so outputs match numpy to the last bit.

#include <cstdint>
#include <cstring>

extern "C" {

// Fused zero-pad -> crop -> horizontal-flip -> normalize.
//   in:      (b, hi, wi, c) uint8, NHWC
//   out:     (b, ho, wo, c) float32, NHWC
//   offsets: (b, 2) int32 crop origins (row, col) in the zero-padded frame;
//            valid range [0, hi + 2*pad - ho] x [0, wi + 2*pad - wo]
//   flips:   (b,) uint8 booleans — flip the crop along the width axis
//   mean/std: (c,) float32 channel statistics
void tpudp_augment_normalize(const uint8_t* in, float* out,
                             const int32_t* offsets, const uint8_t* flips,
                             int64_t b, int64_t hi, int64_t wi,
                             int64_t ho, int64_t wo, int64_t c, int64_t pad,
                             const float* mean, const float* std_) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < b; ++i) {
    const uint8_t* img = in + i * hi * wi * c;
    float* dst = out + i * ho * wo * c;
    // Crop origin in unpadded source coordinates (may be negative: zero pad).
    const int64_t r0 = (int64_t)offsets[2 * i] - pad;
    const int64_t c0 = (int64_t)offsets[2 * i + 1] - pad;
    const bool flip = flips[i] != 0;
    for (int64_t r = 0; r < ho; ++r) {
      const int64_t sr = r0 + r;
      const bool row_in = sr >= 0 && sr < hi;
      for (int64_t col = 0; col < wo; ++col) {
        const int64_t dc = flip ? wo - 1 - col : col;
        float* o = dst + (r * wo + dc) * c;
        const int64_t sc = c0 + col;
        if (row_in && sc >= 0 && sc < wi) {
          const uint8_t* p = img + (sr * wi + sc) * c;
          for (int64_t k = 0; k < c; ++k)
            o[k] = ((float)p[k] / 255.0f - mean[k]) / std_[k];
        } else {  // zero-padding region: normalize a zero pixel
          for (int64_t k = 0; k < c; ++k)
            o[k] = (0.0f - mean[k]) / std_[k];
        }
      }
    }
  }
}

// Normalize only (the eval-path ToTensor+Normalize pair): uint8 -> float32,
// n pixels of c channels each.
void tpudp_normalize(const uint8_t* in, float* out, int64_t n, int64_t c,
                     const float* mean, const float* std_) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* p = in + i * c;
    float* o = out + i * c;
    for (int64_t k = 0; k < c; ++k)
      o[k] = ((float)p[k] / 255.0f - mean[k]) / std_[k];
  }
}

// Parallel batch gather: out[i] = data[idx[i]] for fixed-size samples.
// (numpy fancy indexing is single-threaded; at ImageNet sample sizes the
// copy is worth spreading across cores.)
void tpudp_gather_u8(const uint8_t* data, const int64_t* idx, uint8_t* out,
                     int64_t b, int64_t sample_bytes) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < b; ++i)
    std::memcpy(out + i * sample_bytes, data + idx[i] * sample_bytes,
                (size_t)sample_bytes);
}

int tpudp_native_abi_version(void) { return 1; }

}  // extern "C"

"""Chunked vocabulary loss: exact-match against the dense tied-head CE in
value AND gradients, plus the integrated train path (loss_chunk) following
the dense trajectory."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudp.ops.losses import chunked_lm_metrics, chunked_softmax_xent

B, T, D, V = 2, 12, 16, 37  # deliberately awkward: T*B not chunk-divisible


def _setup(seed=0):
    rng = np.random.default_rng(seed)
    hidden = jnp.asarray(rng.normal(size=(B, T, D)), jnp.float32)
    emb = jnp.asarray(rng.normal(size=(V, D)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, V, size=(B, T)), jnp.int32)
    return hidden, emb, targets


def _dense_sum(hidden, emb, targets):
    import optax

    logits = (hidden.reshape(-1, D) @ emb.T).astype(jnp.float32)
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, targets.reshape(-1)).sum()


@pytest.mark.parametrize("chunk", [5, 8, 24, 1000])
def test_value_and_grads_match_dense(chunk):
    hidden, emb, targets = _setup()
    dense_val, dense_grads = jax.value_and_grad(_dense_sum, argnums=(0, 1))(
        hidden, emb, targets)
    chunk_val, chunk_grads = jax.value_and_grad(
        chunked_softmax_xent, argnums=(0, 1))(hidden, emb, targets, chunk)
    np.testing.assert_allclose(float(chunk_val), float(dense_val),
                               rtol=1e-5, atol=1e-5)
    for cg, dg in zip(chunk_grads, dense_grads):
        np.testing.assert_allclose(np.asarray(cg), np.asarray(dg),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_metrics_match_dense_eval():
    from tpudp.models.gpt2 import gpt2_small
    from tpudp.train import eval_metrics, init_state, make_optimizer

    tiny = dict(vocab_size=V, max_seq_len=T, num_layers=1, num_heads=2,
                d_model=D)
    model = gpt2_small(**tiny)
    state = init_state(model, make_optimizer(), input_shape=(1, T))
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, V, size=(B, T)), jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    weights = jnp.asarray([1.0, 0.0], jnp.float32)  # second sample padded out

    dense = eval_metrics(model, state, tokens, targets, weights)
    hidden = model.apply({"params": state.params}, tokens, train=False,
                         return_hidden=True)
    emb = state.params["wte"]["embedding"]
    chunked = chunked_lm_metrics(hidden, emb, targets, weights, chunk_size=7)
    for a, b in zip(dense, chunked):
        np.testing.assert_allclose(float(a), float(b), rtol=1e-5, atol=1e-5)


@pytest.mark.slow  # ~12s (two 3-step GPT-2 trainings); value+grad
# equality of the chunked loss is pinned fast-tier at the function level
# by test_value_and_grads_match_dense (4 chunk sizes), and the
# loss_chunk wiring through the step/Trainer by the slow
# test_trainer_loss_chunk_end_to_end sibling — this mid-level
# integration adds no coverage class between them.
def test_train_path_loss_chunk_matches_dense(mesh4):
    """GPT-2 trained with loss_chunk follows the dense-loss trajectory."""
    from tpudp.models.gpt2 import gpt2_small
    from tpudp.train import init_state, make_optimizer, make_train_step

    tiny = dict(vocab_size=V, max_seq_len=T, num_layers=2, num_heads=2,
                d_model=D)
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, V, size=(8, T)), jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    losses = {}
    for chunk in (None, 6):
        model = gpt2_small(**tiny)
        tx = make_optimizer(learning_rate=0.01)
        state = init_state(model, tx, input_shape=(1, T))
        step = make_train_step(model, tx, mesh4, "allreduce", donate=False,
                               loss_chunk=chunk)
        for _ in range(3):
            state, loss = step(state, tokens, targets)
        losses[chunk] = float(loss)
    np.testing.assert_allclose(losses[6], losses[None], rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_trainer_loss_chunk_end_to_end(mesh4):
    """Trainer(loss_chunk=...) drives both the chunked train step and the
    chunked eval; metrics match the dense Trainer."""
    from tpudp.models.gpt2 import gpt2_small
    from tpudp.train import Trainer

    tiny = dict(vocab_size=V, max_seq_len=T, num_layers=1, num_heads=2,
                d_model=D)

    class Loader:
        def __init__(self):
            rng = np.random.default_rng(3)  # same data for both trainers
            toks = rng.integers(0, V, size=(3, 8, T)).astype(np.int32)
            self.b = [(jnp.asarray(x), jnp.roll(jnp.asarray(x), -1, axis=1),
                       jnp.ones((8,), jnp.float32)) for x in toks]

        def set_epoch(self, e):
            pass

        def __iter__(self):
            return iter(self.b)

        def __len__(self):
            return len(self.b)

    results = {}
    for chunk in (None, 5):
        trainer = Trainer(gpt2_small(**tiny), mesh4, input_shape=(1, T),
                          learning_rate=0.01, log_fn=lambda s: None,
                          loss_chunk=chunk)
        loader = Loader()
        trainer.train_epoch(loader, epoch=0)
        results[chunk] = trainer.evaluate(loader)
    np.testing.assert_allclose(results[5][0], results[None][0],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(results[5][1], results[None][1],
                               rtol=1e-5, atol=1e-6)

"""KV-cached generation: the decode path must match the training model's
logits exactly — greedy generate == iterative full-forward argmax."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudp.models.generate import generate
from tpudp.models.gpt2 import gpt2_small
from tpudp.train import init_state, make_optimizer

TINY = dict(vocab_size=61, max_seq_len=32, num_layers=2, num_heads=2,
            d_model=32)


def _model_and_params(seed=0, **overrides):
    model = gpt2_small(**{**TINY, **overrides})
    state = init_state(model, make_optimizer(), input_shape=(1, 8), seed=seed)
    return model, state.params


@pytest.mark.parametrize("dtype", [
    jnp.float32,
    pytest.param(jnp.bfloat16, marks=pytest.mark.slow),
])
def test_greedy_matches_full_forward(dtype):
    """Parity must hold for bf16 too — the op/dtype sequence of the decode
    attention mirrors the training path exactly."""
    model, params = _model_and_params(dtype=dtype)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, TINY["vocab_size"], size=(2, 5)),
                         jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=6)
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(np.asarray(out[:, :5]), np.asarray(prompt))

    # Oracle: grow the sequence token by token through the TRAINING model.
    seq = prompt
    for _ in range(6):
        logits = model.apply({"params": params}, seq, train=False)
        nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_temperature_sampling_reproducible_and_in_range():
    model, params = _model_and_params()
    prompt = jnp.zeros((3, 4), jnp.int32)
    key = jax.random.PRNGKey(7)
    a = generate(model, params, prompt, 5, temperature=0.8, key=key)
    b = generate(model, params, prompt, 5, temperature=0.8, key=key)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # same key
    assert np.asarray(a).min() >= 0
    assert np.asarray(a).max() < TINY["vocab_size"]
    c = generate(model, params, prompt, 5, temperature=0.8,
                 key=jax.random.PRNGKey(8))
    assert not np.array_equal(np.asarray(a), np.asarray(c))  # different key


def test_validation():
    model, params = _model_and_params()
    prompt = jnp.zeros((1, 30), jnp.int32)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, params, prompt, 10)  # 40 > 32
    with pytest.raises(ValueError, match="PRNG key"):
        generate(model, params, prompt, 1, temperature=0.5)
    moe_model, moe_params = _model_and_params(
        mlp_impl="moe", num_experts=2, capacity_factor=4.0)
    with pytest.raises(ValueError, match="dense"):
        generate(moe_model, moe_params, prompt, 1)
    # flash-trained configs are rejected too (round-5 advisor): decode
    # runs dense math, so exact greedy train/decode parity would be lost
    # silently for a Pallas-online-softmax-trained model.  (Validation
    # fires before params are touched, so the dense params stand in.)
    flash_model = gpt2_small(**{**TINY, "attn_impl": "flash"})
    with pytest.raises(ValueError, match="attn_impl='dense'"):
        generate(flash_model, params, jnp.zeros((1, 4), jnp.int32), 1)


def test_top_k_and_top_p_sampling():
    model, params = _model_and_params()
    prompt = jnp.zeros((2, 4), jnp.int32)
    key = jax.random.PRNGKey(11)
    # top_k=1 at any temperature collapses to greedy.
    greedy = generate(model, params, prompt, 5)
    k1 = generate(model, params, prompt, 5, temperature=2.0, top_k=1, key=key)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(k1))
    # tiny top_p keeps only the argmax token -> also greedy.
    p_tiny = generate(model, params, prompt, 5, temperature=2.0, top_p=1e-6,
                      key=key)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(p_tiny))
    # joint truncation runs and stays in range
    out = generate(model, params, prompt, 5, temperature=1.0, top_k=10,
                   top_p=0.9, key=key)
    assert 0 <= np.asarray(out).min() and np.asarray(out).max() < TINY["vocab_size"]
    with pytest.raises(ValueError, match="top_k/top_p"):
        generate(model, params, prompt, 2, top_k=5)
    with pytest.raises(ValueError, match="top_p"):
        generate(model, params, prompt, 2, temperature=1.0, top_p=1.5,
                 key=key)


def test_beam_width_1_equals_greedy():
    from tpudp.models.generate import beam_search

    model, params = _model_and_params()
    rng = np.random.default_rng(5)
    prompt = jnp.asarray(rng.integers(0, TINY["vocab_size"], size=(2, 4)),
                         jnp.int32)
    greedy = generate(model, params, prompt, 6)
    beams, scores = beam_search(model, params, prompt, 6, beam_width=1)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(beams))
    assert np.all(np.isfinite(np.asarray(scores)))


@pytest.mark.slow
def test_beam_search_finds_optimal_sequence():
    """With beam_width = vocab^n the search is exhaustive, so it must find
    the true max-logprob continuation — checked against brute force."""
    import itertools

    from tpudp.models.generate import beam_search

    v, n = 7, 2
    model, params = _model_and_params(vocab_size=v, num_layers=1)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    beams, scores = beam_search(model, params, prompt, n, beam_width=v ** n)

    # Brute force: total logprob of every continuation via full forwards.
    def seq_logprob(cont):
        seq = jnp.asarray([[1, 2, 3] + list(cont)], jnp.int32)
        logits = model.apply({"params": params}, seq, train=False)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return sum(float(lp[0, 2 + j, cont[j]]) for j in range(n))

    best_cont, best_lp = None, -np.inf
    for cont in itertools.product(range(v), repeat=n):
        lp = seq_logprob(cont)
        if lp > best_lp:
            best_cont, best_lp = cont, lp
    assert tuple(np.asarray(beams)[0, 3:]) == best_cont
    np.testing.assert_allclose(float(scores[0]), best_lp, rtol=1e-4,
                               atol=1e-5)


@pytest.mark.slow
def test_beam_search_batch_independence():
    """batch=3, width=3: each batch element's beams must equal the beams of
    a standalone batch=1 search on that element — pins the cross-batch
    indexing (batch_offset + parent flattening, per-step KV-cache reorder),
    where a beam-major/batch-major mix-up would leak tokens across batch
    elements while every all-zeros-offset test still passed."""
    from tpudp.models.generate import beam_search

    model, params = _model_and_params()
    rng = np.random.default_rng(7)
    prompt = jnp.asarray(rng.integers(0, TINY["vocab_size"], size=(3, 4)),
                         jnp.int32)
    beams, scores = beam_search(model, params, prompt, 5, beam_width=3)
    for i in range(prompt.shape[0]):
        solo, solo_scores = beam_search(model, params, prompt[i:i + 1], 5,
                                        beam_width=3)
        np.testing.assert_array_equal(np.asarray(beams[i]),
                                      np.asarray(solo[0]))
        np.testing.assert_allclose(float(scores[i]), float(solo_scores[0]),
                                   rtol=1e-5, atol=1e-6)


def test_beam_search_validation():
    from tpudp.models.generate import beam_search

    model, params = _model_and_params()
    with pytest.raises(ValueError, match="beam_width"):
        beam_search(model, params, jnp.zeros((1, 4), jnp.int32), 2,
                    beam_width=0)

"""tpudp.serve: the continuous-batching engine's contract.

The two properties everything else rests on:

  1. GREEDY PARITY — every request's tokens from the engine are
     bit-identical to a standalone ``generate()`` with the same params,
     regardless of admission order, prompt-length mix, co-resident
     requests, or slot reuse after retirement (the slot-masked decode
     must be exactly the per-request math, just batched).
  2. STATIC SHAPES — the jitted decode step compiles exactly once per
     (config, num_slots, max_len); admission/retirement churn never
     recompiles (TRACE_COUNTS observes trace-time side effects).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpudp.models.generate import generate
from tpudp.models.gpt2 import gpt2_small
from tpudp.serve import Engine, TRACE_COUNTS
from tpudp.train import init_state, make_optimizer

TINY = dict(vocab_size=61, max_seq_len=64, num_layers=2, num_heads=2,
            d_model=32)


@pytest.fixture(scope="module")
def model_and_params():
    model = gpt2_small(**TINY)
    state = init_state(model, make_optimizer(), input_shape=(1, 8))
    return model, state.params


def _reference(model, params, prompt, n):
    return np.asarray(generate(model, params, jnp.asarray(prompt[None]), n))


def test_greedy_parity_staggered_admissions(model_and_params):
    """Five requests with mixed prompt lengths (several longer than the
    prefill chunk) staggered through a 2-slot engine: every output must
    equal its standalone generate(), and 5 > 2 slots forces retirement +
    slot reuse along the way."""
    model, params = model_and_params
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, TINY["vocab_size"], size=n)
               .astype(np.int32) for n in (5, 19, 3, 9, 24)]
    max_new = [6, 4, 8, 5, 7]

    eng = Engine(model, params, num_slots=2, max_len=48, prefill_chunk=8)
    handles = [eng.submit(prompts[0], max_new[0])]
    eng.step()
    eng.step()  # request 0 mid-flight before anyone else arrives
    handles.append(eng.submit(prompts[1], max_new[1]))
    handles.append(eng.submit(prompts[2], max_new[2]))
    eng.step()
    handles.append(eng.submit(prompts[3], max_new[3]))
    handles.append(eng.submit(prompts[4], max_new[4]))
    eng.run_until_complete()

    for p, n, h in zip(prompts, max_new, handles):
        ref = _reference(model, params, p, n)
        got = np.concatenate([p, np.asarray(h.tokens, np.int32)])
        np.testing.assert_array_equal(ref[0], got)
    assert eng.stats["completed"] == 5


def test_decode_step_compiles_once_across_churn(model_and_params):
    """The static-shape invariant: a fresh engine geometry compiles the
    decode step exactly once, and admitting/retiring many requests with
    different prompt lengths, sampling params, and slot assignments
    never triggers a recompile."""
    model, params = model_and_params
    rng = np.random.default_rng(1)
    # A geometry no other test uses, so the module-level jit cache cannot
    # have compiled it already.
    eng = Engine(model, params, num_slots=3, max_len=40, prefill_chunk=8)
    h = eng.submit(rng.integers(0, 61, size=4).astype(np.int32), 3)
    while not h.done:
        eng.step()
    base_decode = TRACE_COUNTS["decode_step"]
    base_prefill = TRACE_COUNTS["prefill_chunk"]

    for i in range(6):  # 6 requests through 3 slots: reuse + churn
        eng.submit(rng.integers(0, 61, size=3 + 5 * (i % 3))
                   .astype(np.int32), 2 + i,
                   temperature=0.5 * (i % 2), top_k=4 if i % 2 else None,
                   seed=i)
    eng.run_until_complete()
    assert TRACE_COUNTS["decode_step"] == base_decode
    assert TRACE_COUNTS["prefill_chunk"] == base_prefill


def test_parity_after_masked_garbage_accumulation(model_and_params):
    """The overwrite-before-visible invariant, adversarially: while slot 0
    decodes alone, every masked decode step writes garbage KV into slot
    1's row at its current depth; a long prompt (3 chunks, padded final
    chunk) then admitted into slot 1 must still decode bit-identically —
    every position its queries can see was rewritten by its own
    prefill/decode before becoming visible."""
    model, params = model_and_params
    rng = np.random.default_rng(9)
    p0 = rng.integers(0, 61, size=4).astype(np.int32)
    p1 = rng.integers(0, 61, size=21).astype(np.int32)

    eng = Engine(model, params, num_slots=2, max_len=48, prefill_chunk=8)
    h0 = eng.submit(p0, 20)
    for _ in range(9):  # slot 0 solo; slot 1's row accumulates garbage
        eng.step()
    h1 = eng.submit(p1, 12)
    eng.run_until_complete()
    np.testing.assert_array_equal(
        _reference(model, params, p0, 20)[0, 4:], np.asarray(h0.tokens))
    np.testing.assert_array_equal(
        _reference(model, params, p1, 12)[0, 21:], np.asarray(h1.tokens))


def test_generate_many_matches_generate(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, 61, size=n).astype(np.int32)
               for n in (4, 12, 7)]
    eng = Engine(model, params, num_slots=2, max_len=32, prefill_chunk=8)
    outs = eng.generate_many(prompts, 5)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(_reference(model, params, p, 5)[0], o)


def test_streaming_iterator_and_token_order(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(3)
    p = rng.integers(0, 61, size=6).astype(np.int32)
    eng = Engine(model, params, num_slots=2, max_len=32, prefill_chunk=8)
    h = eng.submit(p, 6)
    streamed = list(h)  # iteration drives the engine
    assert h.done
    assert streamed == h.tokens
    np.testing.assert_array_equal(
        _reference(model, params, p, 6)[0, 6:], np.asarray(streamed))


def test_eos_retirement_and_slot_recycling(model_and_params):
    """A sampled EOS retires the request early (eos included, trailing
    budget unused) and frees its slot for the queued request."""
    model, params = model_and_params
    rng = np.random.default_rng(4)
    p = rng.integers(0, 61, size=5).astype(np.int32)
    ref = _reference(model, params, p, 8)[0, 5:]
    eos = int(ref[3])
    first_hit = int(np.nonzero(ref == eos)[0][0])

    eng = Engine(model, params, num_slots=1, max_len=32, prefill_chunk=8)
    h = eng.submit(p, 8, eos_id=eos)
    q = eng.submit(rng.integers(0, 61, size=4).astype(np.int32), 3)
    eng.run_until_complete()
    assert h.tokens == ref[:first_hit + 1].tolist()  # stops AT the eos
    assert h.done and q.done and len(q.tokens) == 3
    assert eng.stats["completed"] == 2


def test_sampled_requests_reproducible_and_coresident_independent(
        model_and_params):
    """Per-slot key chains: a sampled request's tokens depend only on its
    own seed/params — not on admission order or which other requests
    share the arena (each slot's chain advances once per OWN token)."""
    model, params = model_and_params
    rng = np.random.default_rng(5)
    p = rng.integers(0, 61, size=5).astype(np.int32)

    def tokens_of(crowded):
        eng = Engine(model, params, num_slots=3, max_len=32,
                     prefill_chunk=8)
        if crowded:
            eng.submit(rng.integers(0, 61, size=7).astype(np.int32), 9,
                       temperature=1.3, seed=99)
        h = eng.submit(p, 8, temperature=0.9, top_k=12, top_p=0.9, seed=7)
        if crowded:
            eng.submit(rng.integers(0, 61, size=3).astype(np.int32), 4)
        eng.run_until_complete()
        return list(h.tokens)

    alone = tokens_of(False)
    assert tokens_of(False) == alone      # same seed -> same draws
    assert tokens_of(True) == alone       # co-residents don't perturb
    assert all(0 <= t < TINY["vocab_size"] for t in alone)


def test_submit_validation(model_and_params):
    model, params = model_and_params
    eng = Engine(model, params, num_slots=2, max_len=32, prefill_chunk=8)
    p = np.zeros(30, np.int32)
    with pytest.raises(ValueError, match="max_len"):
        eng.submit(p, 10)  # 40 > 32
    with pytest.raises(ValueError, match="top_k/top_p"):
        eng.submit(p[:4], 2, top_k=5)
    with pytest.raises(ValueError, match="temperature"):
        eng.submit(p[:4], 2, temperature=-1.0)
    with pytest.raises(ValueError, match="top_p"):
        eng.submit(p[:4], 2, temperature=1.0, top_p=1.5)
    with pytest.raises(ValueError, match="eos_id"):
        eng.submit(p[:4], 2, eos_id=61)
    with pytest.raises(ValueError, match="prompt"):
        eng.submit(np.asarray([], np.int32), 2)
    moe = gpt2_small(**{**TINY, "mlp_impl": "moe", "num_experts": 2,
                        "capacity_factor": 4.0})
    with pytest.raises(ValueError, match="dense"):
        Engine(moe, params, num_slots=2)
    flash = gpt2_small(**{**TINY, "attn_impl": "flash"})
    with pytest.raises(ValueError, match="dense"):
        Engine(flash, params, num_slots=2)


@pytest.mark.slow
def test_llama_family_greedy_parity():
    """The engine serves the other decoder lineage too: RoPE positions
    per slot depth, GQA-width arena rows."""
    from tpudp.models.llama import llama_small

    model = llama_small(vocab_size=61, max_seq_len=64, num_layers=2,
                        num_heads=4, num_kv_heads=2, d_model=32)
    params = init_state(model, make_optimizer(),
                        input_shape=(1, 8)).params
    rng = np.random.default_rng(6)
    prompts = [rng.integers(0, 61, size=n).astype(np.int32)
               for n in (4, 11, 17)]
    eng = Engine(model, params, num_slots=2, max_len=32, prefill_chunk=8)
    outs = eng.generate_many(prompts, 6)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(_reference(model, params, p, 6)[0], o)


def test_sample_tokens_masks():
    """The masked-sampling op row-wise: greedy rows ignore the key;
    top_k=1 collapses to greedy; a tiny nucleus keeps only the argmax;
    disabled rows (k=0, p=1) sample the full vocab in range."""
    import jax

    from tpudp.ops.sampling import sample_tokens

    rng = np.random.default_rng(7)
    logits = jnp.asarray(rng.normal(size=(4, 33)), jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(4, dtype=jnp.uint32))
    greedy = np.asarray(jnp.argmax(logits, -1))

    toks = np.asarray(sample_tokens(
        logits,
        jnp.asarray([0.0, 2.0, 2.0, 1.0], jnp.float32),
        jnp.asarray([0, 1, 0, 0], jnp.int32),       # row1: top_k=1
        jnp.asarray([1.0, 1.0, 1e-6, 1.0], jnp.float32),  # row2: tiny p
        keys))
    assert toks[0] == greedy[0]   # temperature 0 -> argmax
    assert toks[1] == greedy[1]   # top_k=1 -> argmax at any temperature
    assert toks[2] == greedy[2]   # nucleus always keeps the argmax
    assert 0 <= toks[3] < 33

    # all-greedy batch takes the argmax-only branch (the lax.cond fast
    # path) and must still match row-wise argmax exactly
    all_greedy = np.asarray(sample_tokens(
        logits, jnp.zeros(4), jnp.zeros(4, jnp.int32), jnp.ones(4), keys))
    np.testing.assert_array_equal(all_greedy, greedy)


# Demoted to slow (PR 20 durations audit): the combined top_k+top_p
# sampling semantics are covered fast by
# tests/test_generate.py::test_top_k_and_top_p_sampling.
@pytest.mark.slow
def test_combined_top_k_top_p_composes_like_truncate_logits():
    """top_k THEN nucleus-over-the-renormalized-distribution — the same
    composition as generate()'s _truncate_logits.  Pinned with the case
    that separates the orders: probs (0.4, 0.35, 0.25), k=2, p=0.5 keeps
    ONLY the argmax (renormalized preceding mass of token 1 is 0.533 >=
    0.5); a full-vocab nucleus would wrongly keep {0, 1}.  With k=2
    keeping {0, 1} the sampler can only ever emit token 0."""
    import jax

    from tpudp.ops.sampling import sample_tokens

    logits = jnp.log(jnp.asarray([[0.4, 0.35, 0.25]], jnp.float32))
    for seed in range(20):
        tok = np.asarray(sample_tokens(
            logits, jnp.asarray([1.0], jnp.float32),
            jnp.asarray([2], jnp.int32), jnp.asarray([0.5], jnp.float32),
            jax.random.PRNGKey(seed)[None]))
        assert tok[0] == 0, (seed, tok)


def test_serve_bench_gap_gate(tmp_path):
    """tools/bench_gaps serve stage: CPU smoke rows and error rows never
    close a concurrency level; banked TPU rows do (the watcher's
    window-accumulation contract, same rules as the mfu stage)."""
    import json
    import os

    from tools.bench_gaps import SERVE_CONCURRENCIES, serve_missing

    d = str(tmp_path)
    assert serve_missing(d) == list(SERVE_CONCURRENCIES)
    rows = [
        {"metric": "serve_tokens_per_sec", "concurrency": 1,
         "value": 900.0, "device_kind": "cpu"},          # smoke: no
        {"metric": "serve_tokens_per_sec", "concurrency": 4,
         "error": "relay wedged"},                       # error: no
        {"metric": "serve_tokens_per_sec", "concurrency": 8,
         "value": 9000.0, "device_kind": "TPU v5 lite"},  # real: yes
    ]
    with open(os.path.join(d, "serve.jsonl"), "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    assert serve_missing(d) == [1, 4]
    with open(os.path.join(d, "serve.history.jsonl"), "w") as f:
        f.write(json.dumps(
            {"metric": "serve_tokens_per_sec", "concurrency": 1,
             "value": 7000.0, "device_kind": "TPU v5 lite"}) + "\n")
    assert serve_missing(d) == [4]  # banked history row counts

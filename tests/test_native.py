"""Native C++ data-path kernels: bit-exact parity with the numpy backend.

Both backends consume the same Python-drawn random decisions (crop offsets,
flip flags), so equality is exact, not approximate — any mismatch is a real
kernel bug, not float noise.
"""

import numpy as np
import pytest

from tpudp import native
from tpudp.data.cifar10 import CIFAR10_MEAN, CIFAR10_STD, Dataset
from tpudp.data.loader import (DataLoader, apply_crop_flip, draw_augment_params,
                               normalize_batch)
from tpudp.data.sampler import ShardedSampler

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


def _images(n=16, h=32, w=32, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=(n, h, w, 3)).astype(np.uint8)


def test_augment_normalize_bit_exact():
    imgs = _images()
    rng = np.random.default_rng(7)
    offsets, flips = draw_augment_params(len(imgs), rng)
    want = normalize_batch(apply_crop_flip(imgs, offsets, flips))
    got = native.augment_normalize(imgs, offsets, flips,
                                   CIFAR10_MEAN, CIFAR10_STD)
    np.testing.assert_array_equal(got, want)


def test_augment_normalize_all_flips_and_corners():
    """Extremes: every sample flipped, crop origins at the 4 padded corners."""
    imgs = _images(8)
    offsets = np.array([[0, 0], [0, 8], [8, 0], [8, 8]] * 2, dtype=np.int32)
    flips = np.ones(8, dtype=bool)
    want = normalize_batch(apply_crop_flip(imgs, offsets, flips))
    got = native.augment_normalize(imgs, offsets, flips,
                                   CIFAR10_MEAN, CIFAR10_STD)
    np.testing.assert_array_equal(got, want)


def test_downscale_crop_no_pad():
    """ImageNet-style crop: 256x256 -> 224x224 with pad=0."""
    imgs = _images(4, h=256, w=256, seed=3)
    rng = np.random.default_rng(11)
    offsets, flips = draw_augment_params(4, rng, crop_range=256 - 224 + 1)
    mean = np.array([0.485, 0.456, 0.406], np.float32)
    std = np.array([0.229, 0.224, 0.225], np.float32)
    got = native.augment_normalize(imgs, offsets, flips, mean, std,
                                   out_hw=(224, 224), pad=0)
    assert got.shape == (4, 224, 224, 3)
    # Spot-check sample 0 against pure numpy.
    r0, c0 = offsets[0]
    crop = imgs[0, r0:r0 + 224, c0:c0 + 224]
    if flips[0]:
        crop = crop[:, ::-1]
    want = (crop.astype(np.float32) / 255.0 - mean) / std
    np.testing.assert_array_equal(got[0], want)


def test_normalize_only_bit_exact():
    imgs = _images(8)
    got = native.normalize(imgs, CIFAR10_MEAN, CIFAR10_STD)
    np.testing.assert_array_equal(got, normalize_batch(imgs))


def test_gather_matches_fancy_indexing():
    data = _images(32)
    idx = np.random.default_rng(5).integers(0, 32, size=20)
    np.testing.assert_array_equal(native.gather(data, idx), data[idx])


def _dataset(n=64, seed=0):
    rng = np.random.default_rng(seed)
    return Dataset(
        rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.uint8),
        rng.integers(0, 10, size=n).astype(np.int32),
    )


@pytest.mark.parametrize("train", [True, False])
def test_loader_backends_identical(train):
    ds = _dataset()
    kw = dict(batch_size=16, train=train, seed=0)
    batches_np = list(DataLoader(ds, backend="numpy", **kw))
    batches_cc = list(DataLoader(ds, backend="native", **kw))
    assert len(batches_np) == len(batches_cc) > 0
    for (xi, yi, wi), (xj, yj, wj) in zip(batches_np, batches_cc):
        np.testing.assert_array_equal(xi, xj)
        np.testing.assert_array_equal(yi, yj)
        np.testing.assert_array_equal(wi, wj)

"""Sequence-parallel (DP x SP) GPT-2 training: the 2-D mesh trajectory must
match a single-device dense-attention run exactly (no BN, so the math is
identical up to float association)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tpudp.models.gpt2 import gpt2_small
from tpudp.train import (init_state, make_optimizer, make_seq_parallel_train_step,
                         make_train_step)

TINY = dict(vocab_size=96, max_seq_len=64, num_layers=2, num_heads=2, d_model=32)


@pytest.fixture(scope="module")
def mesh2x4():
    devices = np.asarray(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devices, ("data", "seq"))


def _data(batch=4, t=64, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, 96, size=(batch, t)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1).astype(np.int32)
    return jnp.asarray(tokens), jnp.asarray(targets)


@pytest.mark.slow  # ~12s (3 single-device + 3 sharded GPT-2 steps); the
# ring-attention math itself is exact-match-pinned fast-tier in
# tests/test_ring_attention.py, the global-position wiring by
# test_sp_positions_are_global below, and the SP rung's full
# fit/eval/checkpoint trajectory by the strategy suite
# (tests/test_strategies.py) — this composition re-times the pieces.
def test_dp_sp_matches_single_device(mesh2x4):
    tokens, targets = _data()
    tx = make_optimizer(learning_rate=0.01)

    dense = gpt2_small(**TINY)
    state = init_state(dense, tx, input_shape=(1, 16), seed=0)
    single_step = make_train_step(dense, tx, None, "none", donate=False)
    single_losses = []
    s = state
    for _ in range(3):
        s, loss = single_step(s, tokens, targets)
        single_losses.append(float(loss))

    ring = gpt2_small(attn_impl="ring", seq_axis="seq", **TINY)
    sp_step = make_seq_parallel_train_step(ring, tx, mesh2x4, donate=False)
    s = state  # same init: param structure/values identical across impls
    sp_losses = []
    for _ in range(3):
        s, loss = sp_step(s, tokens, targets)
        sp_losses.append(float(loss))

    np.testing.assert_allclose(sp_losses, single_losses, rtol=5e-4, atol=1e-5)


def test_sp_positions_are_global(mesh2x4):
    """A model whose output depends on absolute position must produce the
    same logits sharded as dense — catches local-vs-global wpe indexing."""
    tokens, _ = _data(seed=3)
    dense = gpt2_small(**TINY)
    variables = dense.init(jax.random.PRNGKey(0), tokens[:, :16], train=False)
    dense_logits = dense.apply(variables, tokens, train=False)

    ring = gpt2_small(attn_impl="ring", seq_axis="seq", **TINY)
    from jax.sharding import PartitionSpec as P

    sharded = jax.jit(jax.shard_map(
        lambda v, tok: ring.apply(v, tok, train=False),
        mesh=mesh2x4,
        in_specs=(P(), P("data", "seq")),
        out_specs=P("data", "seq"),
        check_vma=False,
    ))
    ring_logits = sharded(variables, tokens)
    np.testing.assert_allclose(np.asarray(ring_logits),
                               np.asarray(dense_logits), rtol=2e-4, atol=2e-4)

# tpudp: compile-once-module
"""Corrected twin of bad_unregistered_jit: every jit bumps its
TRACE_COUNTS entry as the first traced side effect."""

import collections
import functools

import jax

TRACE_COUNTS = collections.Counter()


@functools.partial(jax.jit, donate_argnums=(0,))
def loud_step(cache, tokens):
    TRACE_COUNTS["loud_step"] += 1
    return cache + tokens


def plain_helper(x):                # not jitted: no counter required
    return x * 2


def _loud_body(cache, tokens):
    TRACE_COUNTS["loud_body"] += 1
    return cache * tokens


fast_loud = jax.jit(_loud_body)     # call-form with its counter: fine

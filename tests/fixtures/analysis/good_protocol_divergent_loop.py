# tpudp: protocol-module
"""Corrected twin: the trip count is itself collectively agreed (the
aligned minimum over hosts), so every host loops the same number of
times."""

import os


def verify_all(root):
    count = min(gather_host_values(len(os.listdir(root))))  # noqa: F821
    for _ in range(count):
        all_hosts_ok(True)  # noqa: F821


def drain(root):
    rounds = min(gather_host_values(len(os.listdir(root))))  # noqa: F821
    remaining = rounds
    while remaining:
        gather_host_values(remaining)  # noqa: F821
        remaining -= 1

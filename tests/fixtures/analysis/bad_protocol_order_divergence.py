# tpudp: protocol-module
"""Seeded protocol-order-divergence violation: both arms rendezvous,
but in different orders — hosts taking different arms deadlock pairwise
(one waits in the vote, its peer in the barrier)."""

import os


def commit(root):
    # BAD: a per-host probe picks WHICH order the two collectives run.
    if os.path.exists(root):
        _vote(1)  # noqa: F821
        commit_after_all_hosts(root)  # noqa: F821
    else:
        commit_after_all_hosts(root)  # noqa: F821
        _vote(0)  # noqa: F821

# tpudp: kernel-module
"""Corrected twin of bad_unregistered_kernel: every pallas_call site
is tied to a registered program — through the dispatching program's
TRACE_COUNTS bump, or a kernel-program marker naming a registered
program."""

import collections

import jax.experimental.pallas as pl

TRACE_COUNTS = collections.Counter()


def _body(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2


# tpudp: kernel-program(serve.decode_paged_kernel)
def pinned_kernel(x):
    return pl.pallas_call(_body, out_shape=x)(x)


def counted_step(x):
    TRACE_COUNTS["decode_paged_kernel"] += 1
    return pl.pallas_call(_body, out_shape=x)(x)


def plain_helper(x):                # no kernel inside: no obligation
    return x * 2

# tpudp: kernel-module
"""Seeded violation for unregistered-kernel: Pallas kernels whose
dispatch sites tie to no registered trace-audit program."""

import jax.experimental.pallas as pl


def _body(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2


def orphan_kernel(x):
    # finding: no TRACE_COUNTS bump anywhere up the enclosing chain and
    # no kernel-program marker — the kernel body is unpinned
    return pl.pallas_call(_body, out_shape=x)(x)


# tpudp: kernel-program(serve.not_a_program)
def mislabeled_kernel(x):
    # finding: the marker names a program the registry does not know
    return pl.pallas_call(_body, out_shape=x)(x)

# tpudp: protocol-module
"""Corrected twin: the early exits are guarded by collectively-agreed
predicates, so every host departs (or proceeds) together."""

import os


def restore(root):
    if not coordinated_any(os.path.exists(root)):  # noqa: F821
        return None
    return gather_host_values(1)  # noqa: F821


def save(root, state):
    if not all_hosts_ok(os.stat(root).st_size > 0):  # noqa: F821
        raise RuntimeError("empty root on some host")
    commit_after_all_hosts(root)  # noqa: F821

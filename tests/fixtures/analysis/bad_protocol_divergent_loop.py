# tpudp: protocol-module
"""Seeded protocol-divergent-loop violations: a rendezvous inside a
loop whose trip count is per-host — hosts iterating different counts
issue different numbers of collectives and desync."""

import os


def verify_all(root):
    # BAD: the listing length differs per host (stale attribute cache),
    # so hosts run different numbers of gathers.
    for name in os.listdir(root):
        all_hosts_ok(True)  # noqa: F821


def drain(root):
    # BAD: while-loop twin — the continuation condition is host-local.
    pending = os.listdir(root)
    while pending:
        gather_host_values(len(pending))  # noqa: F821
        pending = pending[1:]

"""Seeded violations for host-sync: device→host round trips in traced
code and on a marked scheduler hot path."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad_traced(x):
    return float(x.sum())           # finding: concretizes in trace


class Scheduler:
    def __init__(self, step):
        self.step = step

    # tpudp: hot-path
    def drive(self, state, batch):
        logits = jnp.matmul(state, batch)
        score = float(logits.sum())          # finding: per-step fetch
        # finding: the sync hides inside a host call AND untaints its
        # own target — must still fire with the pre-assignment taint
        score = max(float(logits.sum()), 0.0)
        toks = np.asarray(logits)            # finding: per-step fetch
        jax.device_get(logits)               # finding: explicit fetch
        logits.block_until_ready()           # finding: explicit barrier
        return score, toks

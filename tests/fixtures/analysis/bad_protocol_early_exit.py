# tpudp: protocol-module
"""Seeded protocol-early-exit violations: a return/raise under a
per-host guard skips a rendezvous peers still issue — the unmatched-
gather deadlock (one host departs, its peer parks alone)."""

import os


def restore(root):
    # BAD: a host whose listing probe fails returns early; its peer
    # proceeds into the gather and waits forever.
    if not os.path.exists(root):
        return None
    return gather_host_values(1)  # noqa: F821


def save(root, state):
    # BAD: same shape, raising instead of returning.
    if os.stat(root).st_size == 0:
        raise RuntimeError("empty root")
    commit_after_all_hosts(root)  # noqa: F821

"""Seeded violations for use-after-donation: buffers read after being
handed to a donating jitted program."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,))
def update(buf, delta):
    return buf + delta


def read_after_donate(buf, delta):
    new = update(buf, delta)
    stale = buf.sum()               # finding: buf was donated above
    return new, stale


def donate_in_loop(buf, deltas):
    outs = []
    for d in deltas:
        outs.append(update(buf, d))  # finding: never rebound in loop
    return outs

"""Corrected twin of bad_divergent_collective: every host reaches every
collective; per-host facts travel THROUGH the collective instead of
gating it."""

import os

import jax
import jax.numpy as jnp
from jax import lax


def uniform_reduce(x, axis):
    total = lax.psum(x, axis)           # every host, unconditionally
    if jax.process_index() == 0:
        print("sum ready")              # host-local side effect is fine
    return total


def recover(x, axis, root):
    # the per-host fact becomes collective INPUT, not a gate
    have = jnp.float32(1.0 if os.path.exists(root) else 0.0)
    everyone_has = lax.pmin(have, axis)  # agreed value on every host
    gathered = lax.all_gather(x, axis)   # unconditional rendezvous
    return gathered, everyone_has


def static_branch(x, axis, world):
    if world > 1:                        # host-uniform config value
        return lax.psum(x, axis)
    return x


def voted_gate(x, axis, root, step, all_hosts_ok):
    have = os.path.exists(root)          # per-host fact...
    if all_hosts_ok(have, step):         # ...voted: the RESULT is
        return lax.all_gather(x, axis)   # host-uniform, branch is safe
    return x

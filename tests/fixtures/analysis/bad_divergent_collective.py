"""Seeded violations for divergent-collective: rendezvous ops under
per-host control flow."""

import os

import jax
from jax import lax


def rank0_reduce(x, axis):
    if jax.process_index() == 0:        # finding: only rank 0 arrives
        return lax.psum(x, axis)
    return x


def recover(x, axis, root):
    head = os.path.exists(root)
    if head:                            # finding: filesystem condition
        x = lax.all_gather(x, axis)
    try:
        return lax.psum(x, axis)
    except RuntimeError:
        return lax.pmean(x, axis)       # finding: inside except handler


def flag_gate(x, axis, root):
    ready = False
    if os.path.exists(root):
        ready = True                    # control-dependent constant
    if ready:                           # finding: the flag carries the
        return lax.psum(x, axis)        # per-host divergence anyway
    return x

"""Seeded violations for traced-branch: Python control flow on traced
values inside jitted functions."""

import functools

import jax
import jax.numpy as jnp


@jax.jit
def clamp(x, lo):
    if x > lo:                      # finding: traced comparison
        return x
    return lo


@functools.partial(jax.jit, donate_argnums=(0,))
def normalize(buf, scale):
    total = jnp.sum(buf) * scale
    while total > 1.0:              # finding: traced while
        total = total / 2.0
    return buf * total


@functools.partial(jax.jit, static_argnames=("mode",))
def dispatch(x, mode):
    y = x * 2
    if y.sum() > 0:                 # finding: derived traced value
        return y
    return x

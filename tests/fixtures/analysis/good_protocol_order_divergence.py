# tpudp: protocol-module
"""Corrected twin: the per-host fact feeds the vote's PAYLOAD, never
the collective order — both arms issue the identical sequence."""

import os


def commit(root):
    have = 1 if os.path.exists(root) else 0
    _vote(have)  # noqa: F821
    commit_after_all_hosts(root)  # noqa: F821

"""Corrected twin of bad_obs_in_hot_path.py: the hot path records
through the allocation-free begin()/end()/count() API, and the
allocating event moved off the hot path (retirement)."""


class Scheduler:
    def __init__(self, obs):
        self.obs = obs

    # tpudp: hot-path
    def step(self, batch):
        tok = self.obs.begin("step")  # OK: preallocated ring write
        out = [t + 1 for t in batch]
        self.obs.count("tokens", len(out))  # OK: counter bump
        self.obs.end(tok)
        return out

    def retire(self, request):  # not a hot path: allocating API is fine
        self.obs.event("finish", rid=request)

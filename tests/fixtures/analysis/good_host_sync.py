"""Corrected twin of bad_host_sync: metrics accumulate on device; the
one sanctioned window-edge fetch carries a visible suppression."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def good_traced(x):
    return x.sum()                   # stays on device


class Scheduler:
    def __init__(self, step):
        self.step = step
        self.loss_sum = jnp.zeros(())

    # tpudp: hot-path
    def drive(self, state, batch, log_now):
        logits = jnp.matmul(state, batch)
        self.loss_sum = self.loss_sum + logits.sum()  # device accumulate
        shape = logits.shape                          # static: no sync
        n = int(batch.shape[0])                       # host value: fine
        if log_now:
            # tpudp: lint-ok(host-sync): the once-per-window fetch —
            # the sanctioned cadence, not a per-step sync.
            return logits, shape, n, float(self.loss_sum)
        return logits, shape, n, None

# tpudp: protocol-module
"""Corrected twin: entry into the rendezvous is itself a collective
decision — the per-host fact travels THROUGH the vote, so every host
takes the same arm."""

import os


def resume_direct(root):
    # GOOD: coordinated_any's result is host-uniform by construction.
    if coordinated_any(os.path.exists(root)):  # noqa: F821
        gather_host_values(1)  # noqa: F821


def newest_checkpoint(root):
    dirs = os.listdir(root)
    return dirs[0] if dirs else None


def resume_interprocedural(root):
    if coordinated_any(newest_checkpoint(root) is not None):  # noqa: F821
        all_hosts_ok(True)  # noqa: F821

# tpudp: protocol-module
"""Seeded protocol-divergent-entry violations: entry into a rendezvous
decided by per-host state — directly, and through a helper (the PR 7
entry-probe bug shape: the probe is one function, the collective
another)."""

import os


def resume_direct(root):
    # BAD: a per-host filesystem probe decides whether this host joins
    # the allgather — a peer with a stale listing never arrives.
    if os.path.exists(root):
        gather_host_values(1)  # noqa: F821


def newest_checkpoint(root):
    dirs = os.listdir(root)
    return dirs[0] if dirs else None


def resume_interprocedural(root):
    # BAD: same bug, one call deep — the probe's host-locality travels
    # through the helper's return-value summary.
    if newest_checkpoint(root) is not None:
        all_hosts_ok(True)  # noqa: F821

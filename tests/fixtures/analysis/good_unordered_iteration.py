# tpudp: collective-module
"""Corrected twin of bad_unordered_iteration: sorted orders
everywhere the interpreter's hash order could leak in."""

import os

import jax
import jax.numpy as jnp

AXES = {"data", "model", "seq"}


@jax.jit
def reduce_axes(x):
    total = x
    for axis in sorted({"a", "b"}):       # deterministic order
        total = total + jnp.sum(x)
    parts = [jnp.sum(x) for a in sorted(AXES)]
    return total, parts


def newest_checkpoint(root):
    dirs = sorted(os.listdir(root))       # every host walks one order
    return dirs[-1]


def newest_step(root):
    # sorted() enclosing a comprehension also normalizes the order
    steps = sorted(int(d.split("_")[1]) for d in os.listdir(root))
    return steps[-1]

"""Corrected twin of bad_traced_branch: lax.cond/jnp.where for traced
decisions, Python branches only on static facts."""

import functools

import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def clamp(x, lo):
    return jnp.where(x > lo, x, lo)         # traced select, no branch


@functools.partial(jax.jit, donate_argnums=(0,))
def normalize(buf, scale):
    total = jnp.sum(buf) * scale
    total = lax.while_loop(lambda t: t > 1.0, lambda t: t / 2.0, total)
    return buf * total


@functools.partial(jax.jit, static_argnames=("mode",))
def dispatch(x, mode):
    if mode == "double":                    # static argument: fine
        return x * 2
    if x.ndim == 2:                         # shape facts are static
        return x.sum(axis=-1)
    if x is None:                           # identity tests are static
        return jnp.zeros(())
    return x

# tpudp: compile-once-module
"""Seeded violation for unregistered-jit: a jitted program in a
compile-once module with no TRACE_COUNTS bump."""

import collections
import functools

import jax

TRACE_COUNTS = collections.Counter()


@functools.partial(jax.jit, donate_argnums=(0,))
def silent_step(cache, tokens):     # finding: recompiles are invisible
    return cache + tokens


@jax.jit
def counted_step(x):
    TRACE_COUNTS["counted_step"] += 1
    return x * 2


def _silent_body(cache, tokens):
    return cache * tokens


fast_silent = jax.jit(_silent_body)  # finding: call-form, no counter

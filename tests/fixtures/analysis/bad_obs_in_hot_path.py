"""Seeded violations for the obs-in-hot-path rule: allocating recorder
calls (.span()/.event()) inside functions designated as scheduler hot
paths.  Exactly 2 findings expected."""


class Scheduler:
    def __init__(self, obs):
        self.obs = obs

    # tpudp: hot-path
    def step(self, batch):
        with self.obs.span("step", batch=len(batch)):  # BAD: allocates
            out = [t + 1 for t in batch]
        for tok in out:
            self.obs.event("commit", token=tok)  # BAD: dict per token
        return out

"""Seeded violations for trace-nondeterminism: host clock/RNG values
frozen into a traced program.  Lint fixture — parsed, never imported."""

import random
import time

import jax
import numpy as np


@jax.jit
def noisy_step(x):
    jitter = time.perf_counter()          # finding: wall clock in trace
    noise = np.random.normal(size=3)      # finding: host RNG in trace
    return x * jitter + noise


def scan_body(carry, _):
    return carry + random.random(), None  # finding: traced via lax.scan


def run(xs):
    return jax.lax.scan(scan_body, 0.0, xs)

# tpudp: collective-module
"""Seeded violations for unordered-iteration: set iteration inside a
trace, unsorted os.listdir in a coordination module."""

import os

import jax
import jax.numpy as jnp

AXES = {"data", "model", "seq"}


@jax.jit
def reduce_axes(x):
    total = x
    for axis in {"a", "b"}:        # finding: set iteration in trace
        total = total + jnp.sum(x)
    parts = [jnp.sum(x) for a in frozenset(AXES)]  # finding: set iter
    return total, parts


def newest_checkpoint(root):
    dirs = os.listdir(root)        # finding: unsorted listing feeds walk
    return dirs[-1]

"""Corrected twin of bad_trace_nondeterminism: randomness comes from
jax.random keys passed in; host timestamps stay on the host."""

import time

import jax
import jax.numpy as jnp


@jax.jit
def noisy_step(x, key):
    noise = jax.random.normal(key, (3,))  # explicit key: deterministic
    return x + noise


def scan_body(carry, key):
    return carry + jax.random.uniform(key), None


def run(xs, keys):
    return jax.lax.scan(scan_body, 0.0, keys)


def host_timing(step, x, key):
    t0 = time.perf_counter()  # host code: clocks are fine here
    out = step(x, key)
    return out, time.perf_counter() - t0

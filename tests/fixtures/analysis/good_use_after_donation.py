"""Corrected twin of bad_use_after_donation: the donated buffer is
rebound by the same statement, loops carry the fresh result."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,))
def update(buf, delta):
    return buf + delta


def rebind_then_read(buf, delta):
    buf = update(buf, delta)        # same-statement rebind: the idiom
    return buf, buf.sum()           # reads the NEW buffer


def donate_in_loop(buf, deltas):
    for d in deltas:
        buf = update(buf, d)        # refreshed every iteration
    return buf

"""Integration: every advanced rung (TP/FSDP/PP/EP/SP) driven end-to-end by
the Trainer — fit() with reference-format logging, sharded eval, watchdog
heartbeats, and an orbax checkpoint round-trip (VERDICT r1 #5)."""

import pytest

pytestmark = pytest.mark.slow  # integration tier (VERDICT r3 #6): rung oracles stay in the fast tier

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudp.mesh import make_mesh, make_mesh_nd
from tpudp.models.gpt2 import gpt2_small
from tpudp.train import Trainer
from tpudp.utils.checkpoint import restore_checkpoint, save_checkpoint
from tpudp.utils.watchdog import Watchdog

VOCAB, T, BATCH = 64, 16, 8
DENSE = dict(vocab_size=VOCAB, max_seq_len=T, num_layers=2, num_heads=2,
             d_model=32)
MOE = dict(**DENSE, mlp_impl="moe", num_experts=4, capacity_factor=4.0)


class TokenLoader:
    """Tiny synthetic LM loader with the framework loader contract."""

    def __init__(self, steps=4, seed=0):
        rng = np.random.default_rng(seed)
        toks = rng.integers(0, VOCAB, size=(steps, BATCH, T)).astype(np.int32)
        self.batches = [
            (jnp.asarray(x), jnp.roll(jnp.asarray(x), -1, axis=1),
             jnp.ones((BATCH,), jnp.float32))
            for x in toks
        ]

    def set_epoch(self, epoch):
        pass

    def __iter__(self):
        return iter(self.batches)

    def __len__(self):
        return len(self.batches)


def _drive(strategy, mesh, model_kwargs, options, tmp_path):
    """fit + eval + checkpoint round-trip for one rung; returns log lines."""
    lines = []
    wd = Watchdog(timeout_s=300.0, kill=False, poll_s=0.1).start()
    try:
        trainer = Trainer(
            gpt2_small(**model_kwargs), mesh,
            strategy=strategy, strategy_options=options,
            input_shape=(1, T), learning_rate=0.01, log_every=2,
            log_fn=lines.append, watchdog=wd, seed=0)
        loader = TokenLoader()
        trainer.fit(loader, test_loader=loader, epochs=1)
    finally:
        wd.stop()

    # reference-format logging reached the rung
    assert any(l.startswith("Training loss after 2 iterations") for l in lines)
    assert any(l.startswith("Training time after 1 epoch") for l in lines)
    assert any(l.startswith("Test set: Average loss") for l in lines)

    # eval contract: finite per-token loss, accuracy in [0, 1]
    loss, acc = trainer.evaluate(loader)
    assert np.isfinite(loss) and 0.0 <= acc <= 1.0

    # checkpoint round-trip on the rung's (sharded) state
    path = save_checkpoint(tmp_path / "ckpt", trainer.state)
    fresh = Trainer(
        gpt2_small(**model_kwargs), mesh,
        strategy=strategy, strategy_options=options,
        input_shape=(1, T), learning_rate=0.01, log_every=2,
        log_fn=lambda s: None, seed=1)  # different seed: restore must win
    restored = restore_checkpoint(path, fresh.state)
    for a, b in zip(jax.tree.leaves(trainer.state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    # the restored state drives the rung's step function
    x, y, _ = next(iter(TokenLoader()))
    if fresh._put is not None:
        x, y = fresh._put(x), fresh._put(y)
    _, loss2 = fresh.train_step(restored, x, y)
    assert np.isfinite(float(loss2))
    return lines


def test_trainer_tp_rung(tmp_path):
    from tpudp.parallel.tensor import gpt2_tp_rules

    mesh = make_mesh_nd({"data": 2, "model": 2}, devices=jax.devices()[:4])
    _drive("tp", mesh, DENSE, {"rules": gpt2_tp_rules()}, tmp_path)


def test_trainer_fsdp_rung(tmp_path):
    mesh = make_mesh(8)
    _drive("fsdp", mesh, DENSE, {"min_size": 128}, tmp_path)


def test_trainer_zero1_rung(tmp_path):
    mesh = make_mesh(8)
    _drive("zero1", mesh, DENSE, {"min_size": 128}, tmp_path)


def test_trainer_pp_rung(tmp_path):
    mesh = make_mesh_nd({"data": 2, "pipe": 2}, devices=jax.devices()[:4])
    _drive("pp", mesh, DENSE, {"n_microbatches": 2}, tmp_path)


def test_trainer_ep_rung(tmp_path):
    mesh = make_mesh_nd({"data": 2, "expert": 2}, devices=jax.devices()[:4])
    _drive("ep", mesh, dict(**MOE, expert_axis="expert"), {}, tmp_path)


def test_trainer_sp_rung(tmp_path):
    mesh = make_mesh_nd({"data": 2, "seq": 2}, devices=jax.devices()[:4])
    _drive("sp", mesh, dict(**DENSE, attn_impl="ring", seq_axis="seq"), {},
           tmp_path)


def test_trainer_pp_mpmd_rung(tmp_path):
    """The unrolled 1F1B MPMD schedule (tpudp/parallel/schedule.py) as a
    first-class pp option: same Trainer loop, in-step sharded optimizer,
    checkpoint round-trip on the flat-sharded state."""
    mesh = make_mesh_nd({"data": 2, "pipe": 2}, devices=jax.devices()[:4])
    _drive("pp", mesh, DENSE,
           {"n_microbatches": 2, "schedule": "1f1b_mpmd"}, tmp_path)


def test_trainer_rejects_bad_strategy_combos():
    mesh = make_mesh(4)
    with pytest.raises(ValueError, match="unknown strategy"):
        Trainer(gpt2_small(**DENSE), mesh, strategy="zz", input_shape=(1, T))
    with pytest.raises(ValueError, match="split"):
        Trainer(gpt2_small(**DENSE), mesh, strategy="fsdp",
                timing_mode="split", input_shape=(1, T))
    with pytest.raises(ValueError, match="grad_accum"):
        Trainer(gpt2_small(**DENSE), mesh, strategy="fsdp", grad_accum=2,
                input_shape=(1, T))

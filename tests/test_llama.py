"""LLaMA-family decoder (tpudp/models/llama.py): RoPE relative-position
property, GQA correctness, end-to-end training through the shared step
machinery, and (slow tier) sequence-parallel + TP parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudp.models.llama import Llama, LlamaConfig, apply_rope, llama_small

TINY = dict(vocab_size=64, max_seq_len=64, num_layers=2, num_heads=4,
            d_model=32)


def test_shapes_gqa_shrink_and_gqa_equals_mha():
    """Logits shape contract; GQA shrinks the KV projections by the group
    factor while q/wo stay full-width; and GQA is exactly MHA whose KV
    heads are tied within each group — the GQA forward equals the MHA
    forward whose wk/wv columns are the GQA ones repeated per group.
    (One test so the tiny models compile once each — fast-tier margin.)"""
    rng = np.random.default_rng(1)
    tok = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
    gqa = llama_small(num_kv_heads=2, **TINY)
    p = gqa.init(jax.random.PRNGKey(3), tok)["params"]
    out_gqa = gqa.apply({"params": p}, tok)
    assert out_gqa.shape == (2, 16, 64)

    dh = TINY["d_model"] // TINY["num_heads"]
    assert dh % 2 == 0  # RoPE precondition
    groups = TINY["num_heads"] // 2

    # widen: (d, kv*dh) -> (d, h*dh) with each KV head's block duplicated
    def widen(kern):
        blocks = np.split(np.asarray(kern), 2, axis=1)
        return jnp.asarray(np.concatenate(
            [b for blk in blocks for b in [blk] * groups], axis=1))

    p_mha = jax.tree.map(lambda a: a, p)
    for i in range(TINY["num_layers"]):
        attn = p_mha[f"h_{i}"]["attn"]
        assert (attn["wk"]["kernel"].shape[1]
                == attn["wq"]["kernel"].shape[1] // groups)  # KV shrink
        attn["wk"] = {"kernel": widen(attn["wk"]["kernel"])}
        attn["wv"] = {"kernel": widen(attn["wv"]["kernel"])}
    mha = llama_small(**TINY)
    out_mha = mha.apply({"params": p_mha}, tok)
    assert out_mha.shape == (2, 16, 64)
    np.testing.assert_allclose(np.asarray(out_gqa), np.asarray(out_mha),
                               rtol=1e-5, atol=1e-5)


def test_config_validation():
    with pytest.raises(ValueError, match="divisible"):
        LlamaConfig(num_heads=3, num_kv_heads=2, d_model=48)
    with pytest.raises(ValueError, match="even head dim"):
        LlamaConfig(num_heads=16, d_model=48)  # head dim 3
    for kv in (0, -2, 5):  # 0 would silently degrade to MHA; <0/overwide
        with pytest.raises(ValueError, match="num_kv_heads"):
            LlamaConfig(num_heads=4, num_kv_heads=kv, d_model=32)


def test_rope_is_relative():
    """The defining RoPE property: q·k between positions (i, j) depends
    only on i - j, so shifting every position by a constant leaves all
    attention scores unchanged."""
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 6, 2, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 6, 2, 8)), jnp.float32)

    def scores(shift):
        pos = jnp.arange(6) + shift
        qr, kr = apply_rope(q, pos), apply_rope(k, pos)
        return jnp.einsum("bqhd,bkhd->bhqk", qr, kr)

    np.testing.assert_allclose(np.asarray(scores(0)),
                               np.asarray(scores(17)), rtol=1e-5, atol=1e-5)
    # and rotation by position 0 is the identity
    np.testing.assert_allclose(
        np.asarray(apply_rope(q[:, :1], jnp.arange(1))), np.asarray(q[:, :1]),
        rtol=1e-6, atol=1e-6)


def test_trains_and_loss_decreases():
    """End to end through the shared step machinery (make_train_step,
    sync='none', single device): overfit a tiny batch."""
    from tpudp.train import init_state, make_optimizer, make_train_step

    model = llama_small(num_kv_heads=2, **TINY)
    tx = make_optimizer(learning_rate=0.05)
    state = init_state(model, tx, input_shape=(1, 8))
    step = make_train_step(model, tx, None, "none", spmd_mode="single",
                           donate=False)
    rng = np.random.default_rng(2)
    tok = jnp.asarray(rng.integers(0, 64, (4, 16)), jnp.int32)
    tgt = jnp.roll(tok, -1, axis=1)
    state, first = step(state, tok, tgt)
    for _ in range(12):
        state, loss = step(state, tok, tgt)
    assert np.isfinite(float(loss))
    assert float(loss) < float(first), (float(first), float(loss))


@pytest.mark.slow
def test_kv_cached_greedy_decode_matches_full_forward():
    """The llama decode path (GQA-width KV cache, RoPE at absolute
    positions, RMSNorm/SwiGLU raw-param twins) must reproduce the naive
    full-forward greedy rollout EXACTLY — and the cache must really be
    allocated at KV width, the memory saving GQA exists for.
    Slow tier (fast-tier margin, r4 #8): the scan-program compile costs
    ~19s and test_generate's GPT-2 greedy parity keeps the shared decode
    machinery fast-covered; the GQA-width cache assert below is cheap
    and stays fast via test_gqa_cache_width."""
    from tpudp.models.generate import KVCache, generate

    model = llama_small(num_kv_heads=2, **TINY)
    tok = jnp.asarray(np.random.default_rng(7).integers(0, 64, (2, 6)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(4), tok)["params"]

    out = generate(model, params, tok, max_new_tokens=6)
    assert out.shape == (2, 12)

    # naive rollout: full forward on the growing sequence, argmax
    seq = tok
    for _ in range(6):
        logits = model.apply({"params": params}, seq)
        seq = jnp.concatenate(
            [seq, jnp.argmax(logits[:, -1], -1)[:, None].astype(jnp.int32)],
            axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))

    # GQA cache is allocated at kv_heads width (2), not num_heads (4)
    cache = KVCache.zeros(model.config, batch=2, max_len=12)
    assert cache.k.shape[3] == 2


def test_gqa_cache_width():
    """The decode cache must allocate at kv_heads width — the memory
    saving GQA exists for (no jit; stays in the fast tier)."""
    from tpudp.models.generate import KVCache

    cfg = llama_small(num_kv_heads=2, **TINY).config
    cache = KVCache.zeros(cfg, batch=2, max_len=12)
    assert cache.k.shape == (2, 2, 12, 2, 8)  # (layers, b, len, KV, dh)


@pytest.mark.slow
def test_beam_search_runs_on_llama():
    """Beam search rides the same dispatching decode path; beam-1 must
    equal greedy, and a wider beam's score can only be >= beam-1's.
    Slow tier: three scan-program compiles (fast-tier margin, r4 #8)."""
    from tpudp.models.generate import beam_search, generate

    model = llama_small(num_kv_heads=2, **TINY)
    tok = jnp.asarray(np.random.default_rng(8).integers(0, 64, (1, 4)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(5), tok)["params"]
    greedy = generate(model, params, tok, max_new_tokens=4)
    seqs1, score1 = beam_search(model, params, tok, max_new_tokens=4,
                                beam_width=1)
    np.testing.assert_array_equal(np.asarray(seqs1), np.asarray(greedy))
    _, score4 = beam_search(model, params, tok, max_new_tokens=4,
                            beam_width=4)
    assert float(score4[0]) >= float(score1[0]) - 1e-6


@pytest.mark.slow
def test_seq_parallel_ring_matches_single_device(mesh8):
    """DPxSP: ring-attention Llama over a (data, seq) mesh must reproduce
    the single-device dense trajectory — RoPE's global-position offsets
    across sequence shards are exactly what this pins."""
    from tpudp.mesh import make_mesh_nd
    from tpudp.train import (init_state, make_optimizer,
                             make_seq_parallel_train_step, make_train_step)

    tx = make_optimizer(learning_rate=0.05)
    rng = np.random.default_rng(5)
    tok = jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32)
    tgt = jnp.roll(tok, -1, axis=1)

    dense = llama_small(**TINY)
    st = init_state(dense, tx, input_shape=(1, 8), seed=0)
    dense_step = make_train_step(dense, tx, None, "none",
                                 spmd_mode="single", donate=False)
    st, dense_loss = dense_step(st, tok, tgt)

    mesh2d = make_mesh_nd({"data": 2, "seq": 2},
                          devices=jax.devices()[:4])
    ring = llama_small(attn_impl="ring", seq_axis="seq", **TINY)
    st2 = init_state(ring, tx, input_shape=(1, 8), seed=0)
    sp_step = make_seq_parallel_train_step(ring, tx, mesh2d, donate=False)
    st2, sp_loss = sp_step(st2, tok, tgt)
    np.testing.assert_allclose(float(sp_loss), float(dense_loss),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_tp_matches_single_device(mesh8):
    """DPxTP via llama_tp_rules: GSPMD-sharded params must reproduce the
    single-device loss (XLA inserts the row-parallel psums)."""
    from tpudp.mesh import make_mesh_nd
    from tpudp.parallel.tensor import llama_tp_rules
    from tpudp.train import (init_state, make_optimizer, make_tp_train_step,
                             make_train_step)

    tx = make_optimizer(learning_rate=0.05)
    rng = np.random.default_rng(6)
    tok = jnp.asarray(rng.integers(0, 64, (4, 16)), jnp.int32)
    tgt = jnp.roll(tok, -1, axis=1)

    model = llama_small(num_kv_heads=2, **TINY)
    st = init_state(model, tx, input_shape=(1, 8), seed=0)
    dense_step = make_train_step(model, tx, None, "none",
                                 spmd_mode="single", donate=False)
    _, ref_loss = dense_step(st, tok, tgt)

    mesh_tp = make_mesh_nd({"data": 2, "model": 2},
                           devices=jax.devices()[:4])
    tp_state, tp_step = make_tp_train_step(
        model, tx, mesh_tp, init_state(model, tx, input_shape=(1, 8),
                                       seed=0),
        llama_tp_rules(), donate=False)
    _, tp_loss = tp_step(tp_state, tok, tgt)
    np.testing.assert_allclose(float(tp_loss), float(ref_loss),
                               rtol=1e-4, atol=1e-4)

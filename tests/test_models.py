"""VGG family shape/param tests (SURVEY.md §7 build order step 1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudp.models.vgg import CONFIGS, VGG11, VGG13, VGG16, VGG19


def _param_count(params):
    return sum(np.prod(p.shape) for p in jax.tree.leaves(params))


def _expected_params(cfg, num_classes=10):
    """Analytic count for conv(3x3,bias)+BN stacks + Linear(512,nc)."""
    total, in_ch = 0, 3
    for v in cfg:
        if v == "M":
            continue
        total += 3 * 3 * in_ch * v + v  # conv w + b
        total += 2 * v  # BN scale + bias
        in_ch = v
    total += 512 * num_classes + num_classes
    return total


@pytest.mark.parametrize("factory,name", [
    (VGG11, "VGG11"), (VGG13, "VGG13"), (VGG16, "VGG16"), (VGG19, "VGG19"),
])
def test_shapes_and_params(factory, name):
    model = factory()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 32, 32, 3)),
                           train=False)
    logits = model.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False)
    assert logits.shape == (2, 10)
    assert _param_count(variables["params"]) == _expected_params(CONFIGS[name])


def test_batch_stats_update():
    model = VGG11()
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    _, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    before = jax.tree.leaves(variables["batch_stats"])
    after = jax.tree.leaves(mutated["batch_stats"])
    assert any(not np.allclose(b, a) for b, a in zip(before, after))


def test_param_count_matches_torch_vgg11():
    """Cross-check against torch's module arithmetic for the same topology
    (reference model: src/Part 1/model.py:30-46)."""
    torch = pytest.importorskip("torch")
    import torch.nn as nn

    layers, in_ch = [], 3
    for v in CONFIGS["VGG11"]:
        if v == "M":
            layers.append(nn.MaxPool2d(2, 2))
        else:
            layers += [nn.Conv2d(in_ch, v, 3, padding=1), nn.BatchNorm2d(v),
                       nn.ReLU(True)]
            in_ch = v
    torch_model = nn.Sequential(*layers, nn.Flatten(), nn.Linear(512, 10))
    torch_count = sum(p.numel() for p in torch_model.parameters())

    model = VGG11()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                           train=False)
    assert _param_count(variables["params"]) == torch_count


def test_bfloat16_compute():
    model = VGG11(dtype=jnp.bfloat16)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.dtype == jnp.float32  # logits cast back for the loss

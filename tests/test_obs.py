"""tpudp.obs — structured telemetry: recorder ring semantics, overhead
budget, Perfetto export round-trip, Prometheus exposition, zero-sync
device counters, flight-recorder dumps on serve step faults / watchdog
timeouts / training rollbacks, and the lint cleanliness of the obs
layer itself (the telemetry must pass the repo's own static analysis —
the design constraint the whole subsystem is shaped around)."""

import glob
import json
import os
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.small_model import SmallConv
from tpudp import obs
from tpudp.data.cifar10 import _synthetic
from tpudp.data.loader import DataLoader
from tpudp.models.generate import generate
from tpudp.models.gpt2 import GPT2, GPT2Config
from tpudp.serve import Engine
from tpudp.serve.engine import OBS_DEVICE_COUNTERS
from tpudp.serve.faults import FaultySteps
from tpudp.train import Trainer, init_state, make_optimizer
from tpudp.utils.watchdog import StepHangError, Watchdog

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- recorder core ----------------------------------------------------


def test_ring_is_bounded_and_drops_oldest():
    rec = obs.Recorder(name="t", capacity=4)
    for i in range(10):
        tok = rec.begin(f"s{i}")
        rec.end(tok)
    snap = rec.snapshot()
    assert len(snap) == 4
    assert [r["name"] for r in snap] == ["s6", "s7", "s8", "s9"]
    # a token the ring lapped is silently dropped, never an error
    rec.end(0)


def test_disabled_recorder_is_noop():
    rec = obs.Recorder(enabled=False)
    tok = rec.begin("x")
    assert tok == obs.NO_SPAN
    rec.end(tok)
    rec.event("e", a=1)
    rec.count("c")
    with rec.span("s"):
        pass
    assert rec.snapshot() == [] and not rec.counters


def test_span_event_counter_semantics():
    rec = obs.Recorder(capacity=16)
    with rec.span("outer", tag="v"):
        rec.event("point", a=1)
        rec.count("tokens", 3)
        rec.count("tokens", 2)
    snap = rec.snapshot()
    kinds = {(r["name"], r["kind"]) for r in snap}
    assert ("outer", "span") in kinds and ("point", "event") in kinds
    outer = next(r for r in snap if r["name"] == "outer")
    assert outer["dur"] is not None and outer["dur"] >= 0.0
    assert outer["fields"] == {"tag": "v"}
    assert rec.counters["tokens"] == 5
    assert rec.summary()["outer"]["count"] == 1
    # last completed record is the span (it closed after the event)
    assert rec.last_span()["name"] == "outer"


def test_open_span_snapshot_and_nesting():
    rec = obs.Recorder(capacity=8)
    a = rec.begin("a")
    b = rec.begin("b")
    rec.end(b)
    snap = {r["name"]: r for r in rec.snapshot()}
    assert snap["a"]["dur"] is None          # still open
    assert snap["b"]["dur"] is not None
    rec.end(a)
    assert {r["name"]: r for r in rec.snapshot()}["a"]["dur"] is not None


def test_overhead_budget_for_hot_path_api():
    """The allocation-free begin/end pair must cost microseconds — the
    budget that makes leaving spans ON in production (and inside the
    tier-1 engines) a non-decision.  Generous bound: 50us/pair mean
    over 20k pairs on an arbitrarily-loaded CI host (measured ~1-2us)."""
    rec = obs.Recorder(capacity=1024)
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        rec.end(rec.begin("hot"))
    per_pair = (time.perf_counter() - t0) / n
    assert per_pair < 50e-6, f"begin/end pair cost {per_pair * 1e6:.1f}us"


# -- exports -----------------------------------------------------------


def test_chrome_trace_schema_round_trip():
    """to_chrome_trace -> json -> spans_from_chrome_trace is the
    identity on (name, kind, t0, dur, fields) — the Perfetto schema
    can't drift from what the parser (and the UI) reads."""
    rec = obs.Recorder(name="rt", capacity=8)
    with rec.span("win", idx=3):
        rec.event("commit", token=7)
    rec.count("tokens", 11)
    open_tok = rec.begin("open")  # still-open span survives the trip
    trace = json.loads(json.dumps(obs.to_chrome_trace(rec, pid=2)))
    back = obs.spans_from_chrome_trace(trace)
    orig = rec.snapshot()
    assert len(back) == len(orig)
    for o, b in zip(orig, back):
        assert b["name"] == o["name"] and b["kind"] == o["kind"]
        assert b["t0"] == pytest.approx(o["t0"], abs=1e-9)
        if o["kind"] == "span":
            if o["dur"] is None:
                assert b["dur"] is None
            else:
                assert b["dur"] == pytest.approx(o["dur"], abs=1e-9)
        assert b.get("fields") == o.get("fields")
    assert obs.counters_from_chrome_trace(trace) == {"tokens": 11}
    # every event is well-formed trace_event JSON
    for ev in trace["traceEvents"]:
        assert ev["ph"] in ("X", "i", "C") and "ts" in ev
    rec.end(open_tok)


def test_snapshot_json_parses():
    rec = obs.Recorder(name="s")
    rec.event("e", x=1)
    doc = json.loads(obs.snapshot_json(rec, extra_field=True))
    assert doc["component"] == "s" and doc["extra_field"] is True
    assert doc["spans"][0]["name"] == "e"


def test_prometheus_text_flattens_numeric_leaves():
    text = obs.prometheus_text(
        {"stats": {"tokens": 42, "ok": True},
         "nested": {"deep": {"v": 1.5}},
         "big": 123456789,  # counters keep full precision (no %g)
         "skipped": "a string", "also_skipped": None})
    assert "tpudp_big 123456789\n" in text
    assert "tpudp_stats_tokens 42\n" in text
    assert "tpudp_stats_ok 1\n" in text
    assert "tpudp_nested_deep_v 1.5\n" in text
    assert "# TYPE tpudp_stats_tokens gauge" in text
    assert "skipped" not in text


def test_metrics_server_serves_live_snapshot():
    state = {"v": 1}
    srv = obs.MetricsServer(0, lambda: {"counter": state["v"]})
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        body = urllib.request.urlopen(url, timeout=10).read().decode()
        assert "tpudp_counter 1" in body
        state["v"] = 2  # supplier is called per request — live values
        body = urllib.request.urlopen(url, timeout=10).read().decode()
        assert "tpudp_counter 2" in body
    finally:
        srv.close()


# -- reference-parity window formatter --------------------------------


def test_reference_window_lines_are_byte_exact():
    """The span-backed formatter must print the reference's strings
    byte-for-byte (src/Part 2a/main.py:100-112 cadence) — the window
    print refactor is parity-neutral by construction."""
    assert obs.reference_window_lines(
        40, 1.25, 4.0, 20, first_window=False) == [
        "Training loss after 40 iterations is 1.25",
        "Average Pass time in iter 40 is 0.2",
    ]
    assert obs.reference_window_lines(
        20, 2.5, 4.0, 20, first_window=True) == [
        "Training loss after 20 iterations is 2.5",
    ]
    assert obs.reference_window_lines(
        40, 1.0, 4.0, 20, fwd_t=2.0, bwd_t=6.0, first_window=False) == [
        "Training loss after 40 iterations is 1.0",
        "Forward Pass time in iter 40 is 0.1",
        "Backward Pass time in iter 40 is 0.3",
        "Average Pass time in iter 40 is 0.2",
    ]


def test_one_timing_api_reexports():
    """The fold-under-obs satellite: the old import paths keep working
    and resolve to the SAME objects as the obs package's."""
    from tpudp.utils.profiler import step_annotation, trace
    from tpudp.utils.timing import StepTimer

    assert trace is obs.trace
    assert step_annotation is obs.step_annotation
    assert StepTimer is obs.StepTimer


# -- flight recorder ---------------------------------------------------


def test_flight_dump_and_merge(tmp_path):
    rec = obs.Recorder(name="f")
    with rec.span("region"):
        rec.event("ev", k=1)
    fl = obs.FlightRecorder(rec, str(tmp_path), component="t")
    p1 = fl.dump("first", extra={"why": "test"})
    p2 = fl.dump("second")
    assert p1 and p2 and fl.dumps == 2
    doc = json.load(open(p1))
    assert doc["reason"] == "first" and doc["extra"] == {"why": "test"}
    assert any(s["name"] == "region" for s in doc["spans"])
    assert doc["last_span"] is not None
    merged = obs.merge_dumps(str(tmp_path))
    mdoc = json.load(open(merged))
    assert mdoc["merged"] == 2
    assert [r["reason"] for r in mdoc["records"]] == ["first", "second"]
    # single-process coordinated merge degenerates to the local merge
    assert obs.coordinated_merge(str(tmp_path)) == merged


def test_flight_disabled_without_directory(monkeypatch):
    monkeypatch.delenv(obs.FLIGHT_DIR_ENV, raising=False)
    fl = obs.FlightRecorder(obs.Recorder(), None)
    assert not fl.enabled and fl.dump("x") is None
    monkeypatch.setenv(obs.FLIGHT_DIR_ENV, "/tmp/some-dir")
    assert obs.resolve_flight_dir(None) == "/tmp/some-dir"
    assert obs.resolve_flight_dir("/explicit") == "/explicit"


# -- serve engine integration -----------------------------------------


@pytest.fixture(scope="module")
def lm():
    cfg = GPT2Config(vocab_size=64, max_seq_len=64, num_layers=2,
                     num_heads=2, d_model=32)
    model = GPT2(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    return model, params


PROMPTS = [np.arange(1, 9, dtype=np.int32),
           np.arange(3, 11, dtype=np.int32)]


def test_engine_device_counters_match_host_stats(lm):
    """The zero-sync device counters must agree with the host-side
    accounting they mirror: on a pure greedy decode run, the device
    'tokens' counter is exactly stats['tokens'] minus the first tokens
    (those ride the prefill's sample_row, which the device counters
    deliberately exclude), and slot_steps matches active_slot_steps."""
    model, params = lm
    eng = Engine(model, params, num_slots=2, max_len=32, prefill_chunk=8)
    eng.generate_many(PROMPTS, 8)
    m = eng.metrics()
    dev = m["device_counters"]
    assert set(dev) == set(OBS_DEVICE_COUNTERS)
    assert dev["tokens"] == m["stats"]["tokens"] - len(PROMPTS)
    assert dev["slot_steps"] == m["stats"]["active_slot_steps"]
    assert dev["steps"] == m["stats"]["decode_steps"]
    assert dev["eos_exits"] == 0.0
    # spans cover the whole device-call taxonomy of this run
    assert {"prefill", "sample", "decode"} <= set(m["spans"])
    # lifecycle events landed (admit + finish per request)
    names = [r["name"] for r in eng.obs.snapshot() if r["kind"] == "event"]
    assert names.count("admit") == 2 and names.count("finish") == 2


def test_engine_obs_off_is_inert_and_parity_neutral(lm):
    model, params = lm
    ref = [np.asarray(generate(model, params, jnp.asarray(p[None]), 8))[0]
           for p in PROMPTS]
    eng = Engine(model, params, num_slots=2, max_len=32, prefill_chunk=8,
                 obs=False)
    outs = eng.generate_many(PROMPTS, 8)
    for o, r in zip(outs, ref):
        assert np.array_equal(o, r)
    assert eng.obs.snapshot() == []
    # device counters still accumulate (they ride the programs, not the
    # host recorder) — metrics() stays truthful either way
    assert eng.metrics()["device_counters"]["tokens"] > 0


def test_fused_window_counts_eos_exit_on_device(lm):
    """Only the fused loop knows per-slot eos ids on device — its
    eos_exits counter must record an in-window EOS exit."""
    model, params = lm
    probe = Engine(model, params, num_slots=1, max_len=32,
                   prefill_chunk=8)
    toks = probe.generate_many([PROMPTS[0]], 6)[0][PROMPTS[0].size:]
    eos = int(toks[2])  # a token produced by DECODE (not the prefill
    #                     sample), so the exit happens inside a window
    eng = Engine(model, params, num_slots=1, max_len=32, prefill_chunk=8,
                 decode_fuse=4)
    h = eng.submit(PROMPTS[0], 6, eos_id=eos)
    eng.run_until_complete()
    assert h.finish_reason.value == "eos"
    assert eng.metrics()["device_counters"]["eos_exits"] == 1.0


def test_engine_step_fault_dumps_flight_record(tmp_path, lm):
    """An injected device-step fault (tpudp.serve.faults) must leave a
    black box: containment dumps the ring, and the dump's span timeline
    names the failing device call."""
    model, params = lm
    hook = FaultySteps(fail_at={5}, kind="decode")
    eng = Engine(model, params, num_slots=2, max_len=32, prefill_chunk=8,
                 step_fault_hook=hook, flight_dir=str(tmp_path))
    outs = eng.generate_many(PROMPTS, 8)
    assert hook.fired and eng.stats["step_failures"] == 1
    # requeue-once containment: outputs still bit-exact
    ref = [np.asarray(generate(model, params, jnp.asarray(p[None]), 8))[0]
           for p in PROMPTS]
    for o, r in zip(outs, ref):
        assert np.array_equal(o, r)
    dumps = glob.glob(os.path.join(str(tmp_path), "flightrec-*.json"))
    assert len(dumps) == 1
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "step_failure"
    assert "InjectedFault" in doc["extra"]["error"]
    # the failing region is the LAST span in the timeline (the decode
    # call the fault landed in), and the containment event follows
    span_names = [s["name"] for s in doc["spans"]]
    assert "decode" in span_names
    assert span_names[-1] == "containment"
    assert eng.metrics()["flight_dumps"] == 1


def test_serve_watchdog_timeout_names_region_and_dumps(tmp_path, lm):
    """The serve-step-timeout acceptance path: a wedged decode call is
    killed by the watchdog, the StepHangError names the armed region
    ('decode') + arm timing, and the flight record lands BEFORE the
    engine's containment handles the hang."""
    from tpudp.serve.faults import SlowSteps

    model, params = lm
    # Warm the step programs first (shared through the per-(cfg,
    # params) ProgramCache): a cold compile inside the tight scoped
    # budget would read as a hang — the real deployments arm the
    # watchdog around warm engines.
    Engine(model, params, num_slots=1, max_len=32,
           prefill_chunk=8).generate_many(PROMPTS[:1], 2)
    wd = Watchdog(timeout_s=0.2, kill=False, poll_s=0.02).start()
    try:
        eng = Engine(model, params, num_slots=1, max_len=32,
                     prefill_chunk=8, watchdog=wd, step_timeout_s=0.2,
                     step_fault_hook=SlowSteps({4}, 0.8, kind="decode"),
                     flight_dir=str(tmp_path))
        assert wd.flight is eng.flight  # engine claimed the watchdog
        eng.generate_many(PROMPTS[:1], 8)
        # the hang was contained (requeued); the black box must exist
        assert eng.stats["step_failures"] >= 1
        dumps = sorted(glob.glob(
            os.path.join(str(tmp_path), "flightrec-*.json")))
        reasons = [json.load(open(p))["reason"] for p in dumps]
        assert any(r.startswith("watchdog_timeout") for r in reasons)
        wd_doc = json.load(open(dumps[reasons.index(next(
            r for r in reasons if r.startswith("watchdog_timeout")))]))
        assert wd_doc["extra"]["region"] == "decode"
        assert wd_doc["extra"]["armed_for_s"] is not None
        assert wd.last_hang["region"] == "decode"
    finally:
        wd.stop()


def test_watchdog_hang_error_carries_region_and_last_span():
    rec = obs.Recorder(name="w")
    fl = obs.FlightRecorder(rec, None)  # disabled: message still works
    wd = Watchdog(timeout_s=0.1, kill=False, poll_s=0.02,
                  flight=fl).start()
    try:
        done = rec.begin("healthy_step")
        rec.end(done)
        with wd.step(name="wedged_collective"):
            time.sleep(0.4)
        with pytest.raises(StepHangError) as ei:
            with wd.step(name="next"):
                pass
        msg = str(ei.value)
        assert "wedged_collective" in msg
        assert "healthy_step" in msg  # last completed span
        assert ei.value.hang["region"] == "wedged_collective"
    finally:
        wd.stop()


# -- trainer integration ----------------------------------------------


def _tiny_loader():
    return DataLoader(_synthetic(64, seed=3), 16, train=True, seed=2,
                      backend="numpy")


def test_trainer_metrics_and_grad_norm():
    tr = Trainer(SmallConv(), None, "none", spmd_mode="single",
                 log_every=2, log_fn=lambda s: None,
                 track_grad_norm=True)
    tr.train_epoch(_tiny_loader(), 0)
    m = tr.metrics()
    assert m["step"] == 4
    assert m["grad_norm_mean"] > 0 and m["grad_norm_rms"] > 0
    assert m["last_window_loss"] is not None
    assert {"train.window", "train.dispatch", "train.data",
            "train.fetch_fence"} <= set(m["spans"])
    assert m["counters"]["train.windows"] == 2
    assert m["counters"]["train.samples"] == 64


def test_track_grad_norm_off_adds_no_pytree_leaf():
    """The default TrainState layout is byte-for-byte pre-obs: the
    obs_norms field contributes NO leaf unless explicitly enabled —
    checkpoints, shardings, and fingerprints are unchanged."""
    tx = make_optimizer()
    st = init_state(SmallConv(), tx)
    st_on = init_state(SmallConv(), tx, track_grad_norm=True)
    assert st.obs_norms is None
    assert len(jax.tree.leaves(st_on)) == len(jax.tree.leaves(st)) + 1


def test_training_rollback_dumps_flight_record(tmp_path):
    """The training-rollback acceptance path: a NaN window rolls back
    under the supervisor and the flight record lands, its ring carrying
    the window timeline plus the typed resilience event."""
    from tpudp.data.cifar10 import _synthetic as _syn
    from tpudp.resilience import ResiliencePolicy
    from tpudp.training_faults import CorruptingLoader

    flight = tmp_path / "flight"
    ckpt = tmp_path / "ckpt"
    tr = Trainer(SmallConv(), None, "none", spmd_mode="single",
                 log_every=2, log_fn=lambda s: None,
                 flight_dir=str(flight))
    loader = CorruptingLoader(
        DataLoader(_syn(64, seed=3), 16, train=True, seed=2,
                   backend="numpy"), nan_at={5})
    tr.fit(loader, epochs=2,
           resilience=ResiliencePolicy(checkpoint_dir=str(ckpt)))
    assert tr.stats["rollbacks"] == 1
    dumps = glob.glob(os.path.join(str(flight), "flightrec-*.json"))
    assert len(dumps) == 1
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "rollback"
    assert "FloatingPointError" in doc["extra"]["error"]
    names = [s["name"] for s in doc["spans"]]
    assert "train.window" in names
    # the recovery event stream is mirrored into the same ring
    post = [r["name"] for r in tr.obs.snapshot()]
    assert "resilience.rollback" in post


def test_coordinated_rollback_dumps_and_merges(tmp_path, monkeypatch):
    """The VOTED recovery path banks a black box on every live host and
    rank 0 merges — exercised through the Supervisor's coordinated seam
    with the cross-host protocol monkeypatched to its single-host
    identities (the same seam-testing pattern as the PR 7 walk tests);
    the real gather ride-along is covered by the slow pod suite."""
    from tpudp.resilience import (OUTCOME_DIVERGENCE, ResiliencePolicy,
                                  Supervisor)
    from tpudp.utils.checkpoint import save_checkpoint

    flight = tmp_path / "flight"
    ckpt = tmp_path / "ckpt"
    tr = Trainer(SmallConv(), None, "none", spmd_mode="single",
                 log_every=2, log_fn=lambda s: None,
                 flight_dir=str(flight))
    save_checkpoint(os.path.join(str(ckpt), "step_0"), tr.state)
    sup = Supervisor(tr, ResiliencePolicy(checkpoint_dir=str(ckpt)))
    sup._per_epoch = 4
    sup._multihost = True  # exercise the coordinated arm single-process
    monkeypatch.setattr(sup, "_assert_replicas_agree", lambda: None)
    epoch, skip = sup._coordinated_recover(
        OUTCOME_DIVERGENCE, FloatingPointError("nan window"))
    assert (epoch, skip) == (0, 0)
    assert tr.stats["rollbacks"] == 1
    dumps = glob.glob(os.path.join(
        str(flight), "flightrec-*coordinated_divergence*"))
    assert len(dumps) == 1
    doc = json.load(open(dumps[0]))
    assert doc["extra"]["worst"] == "divergence"
    assert "FloatingPointError" in doc["extra"]["error"]
    # rank 0 merged the per-host dumps after the recovery
    merged = os.path.join(str(flight), "flightrec-merged.json")
    assert os.path.exists(merged)
    assert json.load(open(merged))["merged"] == 1


# -- the telemetry layer passes its own static analysis ---------------


def test_obs_package_lints_clean():
    """The satellite pin: tpudp.obs adds ZERO findings — the telemetry
    passes the same hazard rules (host-sync on hot paths included) it
    was designed around."""
    from tpudp.analysis import lint_paths

    findings, errors = lint_paths(["tpudp/obs"], ROOT)
    assert errors == []
    assert findings == [], "\n".join(f.render() for f in findings)


def test_bench_gaps_obs_stage(tmp_path):
    """The obs sidecar gate: measured serve rows without the metrics
    sidecar = gap; sidecar present (or nothing measured) = clean."""
    from tools.bench_gaps import OBS_SIDECAR_NAME, obs_missing

    d = str(tmp_path)
    assert obs_missing(d) == []  # nothing measured, nothing owed
    with open(os.path.join(d, "serve.jsonl"), "w") as f:
        f.write(json.dumps({"metric": "serve_tokens_per_sec",
                            "concurrency": 1, "value": 5.0,
                            "device_kind": "cpu"}) + "\n")
    assert obs_missing(d) == ["sidecar"]
    with open(os.path.join(d, OBS_SIDECAR_NAME), "w") as f:
        f.write("{}\n")
    assert obs_missing(d) == []

"""Checkpoint/resume roundtrip: restored state continues training with the
exact same trajectory as the uninterrupted run."""

import jax.numpy as jnp
import numpy as np

from tpudp.models.vgg import VGG11
from tpudp.train import init_state, make_optimizer, make_train_step
from tpudp.utils.checkpoint import restore_checkpoint, save_checkpoint


def test_latest_step_dir_ignores_orbax_tmp(tmp_path):
    """Interrupted saves leave step_N.orbax-checkpoint-tmp-* dirs; resume
    must skip them (code-review finding, round 1)."""
    from tpudp.utils.checkpoint import latest_step_dir

    (tmp_path / "step_1").mkdir()
    (tmp_path / "step_2").mkdir()
    (tmp_path / "step_3.orbax-checkpoint-tmp-1234").mkdir()
    assert latest_step_dir(tmp_path).endswith("step_2")
    assert latest_step_dir(tmp_path / "missing") is None


def test_roundtrip_resume(tmp_path, mesh4):
    model = VGG11()
    tx = make_optimizer()
    state = init_state(model, tx)
    step = make_train_step(model, tx, mesh4, "allreduce", donate=False)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=8), jnp.int32)

    state, _ = step(state, x, y)
    ckpt = save_checkpoint(tmp_path / "ckpt", state)

    # Continue the original for 2 more steps.
    cont = state
    for _ in range(2):
        cont, loss_a = step(cont, x, y)

    # Restore and continue from the checkpoint: identical trajectory.
    restored = restore_checkpoint(ckpt, init_state(model, tx))
    assert int(restored.step) == 1
    for _ in range(2):
        restored, loss_b = step(restored, x, y)
    assert float(loss_b) == float(loss_a)
    np.testing.assert_array_equal(
        np.asarray(cont.params["Dense_0"]["kernel"]),
        np.asarray(restored.params["Dense_0"]["kernel"]),
    )

"""Checkpoint/resume roundtrip: restored state continues training with the
exact same trajectory as the uninterrupted run."""

import pytest
import os
import jax.numpy as jnp
import numpy as np

from tpudp.models.vgg import VGG11
from tpudp.train import init_state, make_optimizer, make_train_step
from tpudp.utils.checkpoint import restore_checkpoint, save_checkpoint


def test_latest_step_dir_ignores_orbax_tmp(tmp_path):
    """Interrupted saves leave step_N.orbax-checkpoint-tmp-* dirs; resume
    must skip them (code-review finding, round 1)."""
    from tpudp.utils.checkpoint import latest_step_dir

    (tmp_path / "step_1").mkdir()
    (tmp_path / "step_2").mkdir()
    (tmp_path / "step_3.orbax-checkpoint-tmp-1234").mkdir()
    assert latest_step_dir(tmp_path).endswith("step_2")
    assert latest_step_dir(tmp_path / "missing") is None


@pytest.mark.slow
def test_async_writer_roundtrip(tmp_path, mesh4):
    """AsyncCheckpointWriter under the CLI's actual hazard: training
    continues with a DONATING step while the write is in flight, so the
    saved state's device buffers are invalidated mid-write.  The writer
    must have copied device->host before save() returned (orbax's async
    contract) for the restored snapshot to be intact."""
    from tpudp.utils.checkpoint import AsyncCheckpointWriter

    model = VGG11()
    tx = make_optimizer()
    state = init_state(model, tx)
    step = make_train_step(model, tx, mesh4, "allreduce", donate=True)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=8), jnp.int32)
    state, _ = step(state, x, y)
    # Host-side reference copy of what the snapshot must contain.
    expect_kernel = np.asarray(state.params["Dense_0"]["kernel"]).copy()

    with AsyncCheckpointWriter() as writer:
        path = writer.save(tmp_path / "async_ckpt", state)
        # The donating step invalidates `state`'s buffers while the write
        # is (potentially) still in flight — exactly what the CLI's next
        # epoch does after epoch_end_fn staged an async save.
        state2, _ = step(state, x, y)
        writer.wait()
    assert int(state2.step) == 2

    restored = restore_checkpoint(path, init_state(model, tx))
    assert int(restored.step) == 1  # the snapshot, not the later state2
    np.testing.assert_array_equal(
        expect_kernel, np.asarray(restored.params["Dense_0"]["kernel"]))


def test_roundtrip_resume(tmp_path, mesh4):
    # SmallConv, not VGG: round-trip fidelity is model-agnostic and the
    # VGG compile dominated the test's 35s (fast-tier margin, r4 #8).
    from tests.small_model import SmallConv

    model = SmallConv()
    tx = make_optimizer()
    state = init_state(model, tx)
    step = make_train_step(model, tx, mesh4, "allreduce", donate=False)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=8), jnp.int32)

    state, _ = step(state, x, y)
    ckpt = save_checkpoint(tmp_path / "ckpt", state)

    # Continue the original for 2 more steps.
    cont = state
    for _ in range(2):
        cont, loss_a = step(cont, x, y)

    # Restore and continue from the checkpoint: identical trajectory.
    restored = restore_checkpoint(ckpt, init_state(model, tx))
    assert int(restored.step) == 1
    for _ in range(2):
        restored, loss_b = step(restored, x, y)
    assert float(loss_b) == float(loss_a)
    np.testing.assert_array_equal(
        np.asarray(cont.params["Dense_0"]["kernel"]),
        np.asarray(restored.params["Dense_0"]["kernel"]),
    )


def test_prune_step_dirs(tmp_path):
    """Retention: keep the newest N step dirs; never touch orbax tmp dirs
    or the emergency dump; numeric (not lexicographic) ordering."""
    import pytest

    from tpudp.utils.checkpoint import latest_step_dir, prune_step_dirs

    for name in ("step_1", "step_2", "step_9", "step_10", "emergency",
                 "step_11.orbax-checkpoint-tmp-7"):
        (tmp_path / name).mkdir()
    deleted = prune_step_dirs(tmp_path, keep=2)
    assert sorted(os.path.basename(d) for d in deleted) == ["step_1", "step_2"]
    left = sorted(p.name for p in tmp_path.iterdir())
    assert left == ["emergency", "step_10", "step_11.orbax-checkpoint-tmp-7",
                    "step_9"]
    assert latest_step_dir(tmp_path).endswith("step_10")
    assert prune_step_dirs(tmp_path / "missing", keep=2) == []
    with pytest.raises(ValueError):
        prune_step_dirs(tmp_path, keep=0)


def test_restore_params_ignores_optimizer_structure(tmp_path):
    """restore_params: decode/eval tools restore ONLY the weights, so a
    checkpoint saved under a clip-wrapped optimizer (extra opt_state
    leaves) restores fine without knowing the training flags."""
    import flax.linen as nn
    import jax
    import numpy as np

    from tpudp.train import init_state, make_optimizer
    from tpudp.utils.checkpoint import restore_params, save_checkpoint

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(4)(x.reshape((x.shape[0], -1)))

    tx = make_optimizer(clip_norm=1.0)  # clip wrapper changes opt_state
    state = init_state(M(), tx, input_shape=(1, 2, 2, 1))
    path = str(tmp_path / "step_1")
    save_checkpoint(path, state)

    params = restore_params(path)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # save OUTSIDE the raises block: only restore_params's error path may
    # satisfy the assertion
    save_checkpoint(str(tmp_path / "junk"), {"not_params": 1})
    with pytest.raises(ValueError, match="params"):
        restore_params(str(tmp_path / "junk"))


def test_ensure_writable_probe(tmp_path, monkeypatch):
    """Fail-fast --save-checkpoint probe: creates the destination and
    verifies writability up front; without orbax it refuses BEFORE any
    training compute would be spent."""
    from tpudp.utils import checkpoint as ck

    root = ck.ensure_writable(tmp_path / "new" / "dir")
    import os

    assert os.path.isdir(root)
    assert not os.listdir(root)  # the probe file was removed

    monkeypatch.setattr(ck, "HAVE_ORBAX", False)
    with pytest.raises(RuntimeError, match="orbax"):
        ck.ensure_writable(tmp_path / "other")


def test_multihost_sidecars_leave_with_the_dump(tmp_path):
    """Multi-host integrity sidecars (per-host shard manifests, COMMITTED
    marker) must travel with the emergency dump on consume/quarantine,
    and a later SINGLE-host save over the same name must clear any
    stragglers: a stale host manifest left at the base name would be
    verified against the next dump/save's bytes (e.g. after the pod
    shrank) and reject every future one at this root forever."""
    from tpudp.utils import checkpoint as ck

    state = {"w": np.arange(4.0)}
    root = str(tmp_path)
    emerg = os.path.join(root, "emergency")
    ck.save_checkpoint(emerg, state)
    # fabricate the sidecars a 2-host dump would have left
    for fabricate in (ck.host_manifest_path(emerg, 1),
                      ck.commit_marker_path(emerg)):
        with open(fabricate, "w") as f:
            f.write("{}")
    consumed = ck.consume_emergency(root)
    assert not os.path.exists(ck.host_manifest_path(emerg, 1))
    assert not os.path.exists(ck.commit_marker_path(emerg))
    assert os.path.exists(ck.host_manifest_path(consumed, 1))

    # quarantine path too
    ck.save_checkpoint(emerg, state)
    with open(ck.host_manifest_path(emerg, 1), "w") as f:
        f.write("{}")
    ck.quarantine_emergency(root)
    assert not os.path.exists(ck.host_manifest_path(emerg, 1))
    assert os.path.exists(ck.host_manifest_path(emerg + ".corrupt", 1))

    # a fresh single-host save clears stale multi-host sidecars under
    # its name, and then verifies cleanly
    step = str(tmp_path / "step_1")
    for fabricate in (ck.host_manifest_path(step, 0),
                      ck.commit_marker_path(step)):
        with open(fabricate, "w") as f:
            f.write("{}")
    ck.save_checkpoint(step, state)
    assert not os.path.exists(ck.host_manifest_path(step, 0))
    assert not os.path.exists(ck.commit_marker_path(step))
    ck.restore_checkpoint(step, state, verify=True)


# -- transient-filesystem retry (tpudp/utils/checkpoint.py::_retry_fs) --


class _FlakyFS:
    """Flaky-fs injector: the first ``failures`` calls raise
    ``OSError(errno_)``, then the wrapped callable runs for real."""

    def __init__(self, fn, failures, errno_):
        self.fn = fn
        self.failures = failures
        self.errno_ = errno_
        self.calls = 0

    def __call__(self, *a, **kw):
        self.calls += 1
        if self.calls <= self.failures:
            raise OSError(self.errno_, "injected transient fault")
        return self.fn(*a, **kw)


def test_retry_fs_recovers_from_transient_eio(monkeypatch):
    """EIO (a shared-FS blip) is retried with backoff and the call
    succeeds once the FS heals — the save/restore seam never surfaces a
    transient error the retry budget could have absorbed."""
    import errno

    from tpudp.utils import checkpoint as ck

    monkeypatch.setattr(ck, "FS_BACKOFF_S", 0.0)
    flaky = _FlakyFS(lambda: 7, failures=ck.FS_RETRIES, errno_=errno.EIO)
    assert ck._retry_fs(flaky, "probe") == 7
    assert flaky.calls == ck.FS_RETRIES + 1


def test_retry_fs_budget_is_bounded(monkeypatch):
    """A path that stays broken must become the caller's loud error
    after exactly FS_RETRIES + 1 attempts — bounded by construction,
    never a silent spin."""
    import errno

    import pytest

    from tpudp.utils import checkpoint as ck

    monkeypatch.setattr(ck, "FS_BACKOFF_S", 0.0)
    flaky = _FlakyFS(lambda: 7, failures=99, errno_=errno.ESTALE)
    with pytest.raises(OSError) as ei:
        ck._retry_fs(flaky, "probe")
    assert ei.value.errno == errno.ESTALE
    assert flaky.calls == ck.FS_RETRIES + 1


def test_retry_fs_non_transient_propagates_immediately(monkeypatch):
    """ENOENT is a CORRECTNESS signal (wrong path, deleted step dir),
    not weather — retrying it would mask the bug and burn the backoff
    budget where no retry can succeed."""
    import errno

    import pytest

    from tpudp.utils import checkpoint as ck

    monkeypatch.setattr(ck, "FS_BACKOFF_S", 0.0)
    flaky = _FlakyFS(lambda: 7, failures=99, errno_=errno.ENOENT)
    with pytest.raises(FileNotFoundError):
        ck._retry_fs(flaky, "probe")
    assert flaky.calls == 1


def test_save_restore_ride_through_flaky_fs(tmp_path, monkeypatch):
    """End-to-end through the real seams: the orbax save and restore
    calls each eat injected EIO blips (strictly fewer than the budget)
    and the roundtrip completes bit-exactly — the retry wrapper wraps
    the actual checkpointer calls, not just a helper."""
    import errno

    from tpudp.utils import checkpoint as ck

    monkeypatch.setattr(ck, "FS_BACKOFF_S", 0.0)
    state = {"w": np.arange(8.0), "b": np.ones(3, np.float32)}
    path = str(tmp_path / "step_5")

    real_ckptr = ck._checkpointer
    blips = {"save": 2, "restore": 1}

    def flaky_ckptr():
        real = real_ckptr()

        class _Proxy:
            def save(self, *a, **kw):
                if blips["save"]:
                    blips["save"] -= 1
                    raise OSError(errno.EIO, "injected EIO on save")
                return real.save(*a, **kw)

            def restore(self, *a, **kw):
                if blips["restore"]:
                    blips["restore"] -= 1
                    raise OSError(errno.EIO, "injected EIO on restore")
                return real.restore(*a, **kw)

            def __getattr__(self, k):
                return getattr(real, k)

        return _Proxy()

    monkeypatch.setattr(ck, "_checkpointer", flaky_ckptr)
    ck.save_checkpoint(path, state)
    assert blips["save"] == 0
    got = ck.restore_checkpoint(path, state, verify=True)
    assert blips["restore"] == 0
    np.testing.assert_array_equal(got["w"], state["w"])
    np.testing.assert_array_equal(got["b"], state["b"])

"""--eval-only: restore the latest checkpoint and run only the reference
eval loop (no training).  Drives run_part in-process on the CPU mesh."""

import pytest

pytestmark = pytest.mark.slow  # integration tier (VERDICT r3 #6): rung oracles stay in the fast tier

import numpy as np

from tpudp.cli import run_part


def _argv(tmp_path, *extra):
    return ["--synthetic-train-size", "64", "--synthetic-test-size", "64",
            "--batch-size", "32", "--checkpoint-dir", str(tmp_path / "ckpt"),
            *extra]


def test_eval_only_restores_and_skips_training(tmp_path, capsys):
    trained = run_part("allreduce", "t", argv=_argv(tmp_path))
    step_after_train = int(trained.state.step)
    assert step_after_train > 0
    capsys.readouterr()  # flush the training run's output

    evaluated = run_part("allreduce", "t", argv=_argv(tmp_path, "--eval-only"))
    out = capsys.readouterr().out
    assert "resumed from" in out
    assert "Test set: Average loss" in out
    assert "Training time" not in out  # the epoch loop never ran
    # No training happened: the restored step counter is unchanged.
    assert int(evaluated.state.step) == step_after_train
    # And the restored model evaluates to the same metrics as the trained
    # one would (same weights).
    np.testing.assert_allclose(
        np.asarray(evaluated.state.params["Dense_0"]["bias"]),
        np.asarray(trained.state.params["Dense_0"]["bias"]), rtol=1e-6)


def test_eval_only_requires_checkpoint_dir():
    import pytest

    with pytest.raises(SystemExit, match="checkpoint-dir"):
        run_part("allreduce", "t", argv=["--eval-only"])


def test_eval_only_empty_checkpoint_dir_errors(tmp_path):
    """Silently evaluating random weights would report meaningless metrics
    with exit code 0 — an empty/typo'd checkpoint dir must be an error."""
    import pytest

    with pytest.raises(SystemExit, match="no checkpoint"):
        run_part("allreduce", "t", argv=_argv(tmp_path, "--eval-only"))


def test_emergency_resume_fast_forwards_cli(tmp_path, capsys):
    """CLI wiring of the mid-epoch fast-forward: an emergency dump whose
    optimizer-step counter sits 2 batches into epoch 1 must resume at
    epoch 1 skipping exactly those 2 of 4 batches (step // per_epoch and
    step % per_epoch derivation in tpudp/cli.py), then finish the epoch —
    no batch trained twice."""
    import jax.numpy as jnp

    from tpudp.utils.checkpoint import (clear_emergency_sentinel,
                                        save_checkpoint,
                                        write_emergency_sentinel)

    argv = ["--synthetic-train-size", "128", "--synthetic-test-size", "64",
            "--batch-size", "32", "--checkpoint-dir", str(tmp_path / "ckpt")]
    trained = run_part("allreduce", "t", argv=argv)  # epoch 0 -> step_1
    assert int(trained.state.step) == 4  # 128/32 batches per epoch

    # Manufacture the watchdog's mid-epoch dump: 2 batches into epoch 1.
    root = str(tmp_path / "ckpt")
    dumped = trained.state.replace(step=jnp.asarray(6, jnp.int32))
    clear_emergency_sentinel(root)
    save_checkpoint(f"{root}/emergency", dumped)
    write_emergency_sentinel(root, step=6)
    capsys.readouterr()

    resumed = run_part("allreduce", "t", argv=argv + ["--epochs", "2"])
    out = capsys.readouterr().out
    assert "fast-forwarding 2/4 already-trained batches" in out
    assert "fast-forwarded 2 already-trained batches of epoch 1" in out
    # 6 (dump) + the 2 never-trained batches of epoch 1 = 8, and nothing
    # beyond: epoch 1 completed exactly once.
    assert int(resumed.state.step) == 8


def test_emergency_resume_refuses_changed_batch_grid(tmp_path, capsys):
    """Round-3 advisor: the fast-forward maps the dump's step counter onto
    the loader's batch grid, so a relaunch with a different batches/epoch
    (changed --batch-size here) must REFUSE instead of silently
    re-training or dropping batches — and must leave the dump in place so
    a correctly-configured relaunch can still consume it."""
    import os

    import jax.numpy as jnp
    import pytest

    from tpudp.utils.checkpoint import (clear_emergency_sentinel,
                                        save_checkpoint,
                                        write_emergency_sentinel)

    argv = ["--synthetic-train-size", "128", "--synthetic-test-size", "64",
            "--batch-size", "32", "--checkpoint-dir", str(tmp_path / "ckpt")]
    trained = run_part("allreduce", "t", argv=argv)  # 4 batches/epoch
    root = str(tmp_path / "ckpt")
    dumped = trained.state.replace(step=jnp.asarray(6, jnp.int32))
    clear_emergency_sentinel(root)
    save_checkpoint(f"{root}/emergency", dumped)
    write_emergency_sentinel(root, step=6, per_epoch_batches=4)
    capsys.readouterr()

    with pytest.raises(SystemExit, match="batches/epoch"):
        run_part("allreduce", "t",
                 argv=["--synthetic-train-size", "128",
                       "--synthetic-test-size", "64", "--batch-size", "16",
                       "--checkpoint-dir", root, "--epochs", "2"])
    # The refusal happened BEFORE the dump was consumed.
    assert os.path.isdir(f"{root}/emergency")
    capsys.readouterr()

    resumed = run_part("allreduce", "t", argv=argv + ["--epochs", "2"])
    out = capsys.readouterr().out
    assert "fast-forwarding 2/4 already-trained batches" in out
    assert int(resumed.state.step) == 8

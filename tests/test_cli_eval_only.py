"""--eval-only: restore the latest checkpoint and run only the reference
eval loop (no training).  Drives run_part in-process on the CPU mesh."""

import numpy as np

from tpudp.cli import run_part


def _argv(tmp_path, *extra):
    return ["--synthetic-train-size", "64", "--synthetic-test-size", "64",
            "--batch-size", "32", "--checkpoint-dir", str(tmp_path / "ckpt"),
            *extra]


def test_eval_only_restores_and_skips_training(tmp_path, capsys):
    trained = run_part("allreduce", "t", argv=_argv(tmp_path))
    step_after_train = int(trained.state.step)
    assert step_after_train > 0
    capsys.readouterr()  # flush the training run's output

    evaluated = run_part("allreduce", "t", argv=_argv(tmp_path, "--eval-only"))
    out = capsys.readouterr().out
    assert "resumed from" in out
    assert "Test set: Average loss" in out
    assert "Training time" not in out  # the epoch loop never ran
    # No training happened: the restored step counter is unchanged.
    assert int(evaluated.state.step) == step_after_train
    # And the restored model evaluates to the same metrics as the trained
    # one would (same weights).
    np.testing.assert_allclose(
        np.asarray(evaluated.state.params["Dense_0"]["bias"]),
        np.asarray(trained.state.params["Dense_0"]["bias"]), rtol=1e-6)


def test_eval_only_requires_checkpoint_dir():
    import pytest

    with pytest.raises(SystemExit, match="checkpoint-dir"):
        run_part("allreduce", "t", argv=["--eval-only"])


def test_eval_only_empty_checkpoint_dir_errors(tmp_path):
    """Silently evaluating random weights would report meaningless metrics
    with exit code 0 — an empty/typo'd checkpoint dir must be an error."""
    import pytest

    with pytest.raises(SystemExit, match="no checkpoint"):
        run_part("allreduce", "t", argv=_argv(tmp_path, "--eval-only"))

"""Gradient accumulation and LR schedules."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudp.models.gpt2 import gpt2_small
from tpudp.models.vgg import VGG11
from tpudp.train import init_state, make_optimizer, make_train_step

TINY = dict(vocab_size=64, max_seq_len=32, num_layers=2, num_heads=4, d_model=32)


def test_accum_matches_oneshot_exactly():
    """No BatchNorm (GPT-2): mean-of-microbatch grads == one-shot grads, so
    the 3-step trajectory must match to float tolerance."""
    model = gpt2_small(**TINY)
    tx = make_optimizer(learning_rate=0.01)
    s1 = init_state(model, tx, input_shape=(1, 8), seed=0)
    s4 = init_state(model, tx, input_shape=(1, 8), seed=0)
    step1 = make_train_step(model, tx, None, "none", donate=False)
    step4 = make_train_step(model, tx, None, "none", donate=False, grad_accum=4)

    rng = np.random.default_rng(0)
    for _ in range(3):
        x = jnp.asarray(rng.integers(0, 64, size=(8, 16)), jnp.int32)
        y = jnp.roll(x, -1, axis=1)
        s1, l1 = step1(s1, x, y)
        s4, l4 = step4(s4, x, y)
        np.testing.assert_allclose(float(l1), float(l4), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(s1.params["h_0"]["mlp_fc"]["kernel"]),
        np.asarray(s4.params["h_0"]["mlp_fc"]["kernel"]), atol=1e-5)


@pytest.mark.slow  # full VGG mesh8 accum compile (~26s) for a
# finite-loss smoke; accumulation exactness is pinned fast by
# test_accum_matches_oneshot_exactly and the sharded VGG step compile
# by test_train.py::test_gspmd_vgg_step_compiles
def test_accum_with_batchnorm_trains(mesh8):
    """VGG (BatchNorm): per-microbatch stats are a documented semantic
    difference — assert the sharded accum step runs and learns."""
    model = VGG11()
    tx = make_optimizer()
    state = init_state(model, tx, seed=0)
    step = make_train_step(model, tx, mesh8, "allreduce", donate=False,
                           grad_accum=2)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=16), jnp.int32)
    state, loss = step(state, x, y)
    assert np.isfinite(float(loss))
    assert int(state.step) == 1


def test_cosine_schedule_warms_up_and_decays():
    tx = make_optimizer(learning_rate=0.1, weight_decay=0.0, momentum=0.0,
                        schedule="cosine", warmup_steps=2, total_steps=10)
    params = {"w": jnp.ones((4,))}
    opt = tx.init(params)
    g = {"w": jnp.ones((4,))}
    sizes = []
    for _ in range(10):
        upd, opt = tx.update(g, opt, params)
        sizes.append(float(jnp.abs(upd["w"]).max()))
    assert sizes[0] < sizes[2]            # warmup: tiny first step
    assert sizes[-1] < sizes[3]           # decay at the end
    assert max(sizes) <= 0.1 + 1e-6       # peak == lr


def test_linear_schedule_and_validation():
    tx = make_optimizer(schedule="linear", warmup_steps=1, total_steps=5)
    assert tx is not None
    with pytest.raises(ValueError, match="total_steps"):
        make_optimizer(schedule="cosine")
    with pytest.raises(ValueError, match="unknown schedule"):
        make_optimizer(schedule="exponential", total_steps=5)

"""Pallas flash attention vs dense oracle — forward and gradients.

Runs in Pallas interpret mode on the CPU simulator (the kernel auto-selects
interpret off-TPU). Interpret mode checks the kernel math, not Mosaic
lowering constraints — the small block sizes used here (64) are
interpret-only; compiled TPU mode enforces 128-multiples and is exercised
by benchmarks/flash_attention_bench.py on real hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudp.ops.flash_attention import flash_attention
from tpudp.parallel.ring_attention import dense_causal_attention


def _dense(q, k, v, causal):
    b, t, h, dh = q.shape
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * dh ** -0.5
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))


def _rand_qkv(key, b=2, t=256, h=2, dh=32):
    ks = jax.random.split(key, 3)
    shape = (b, t, h, dh)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_dense(causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0))
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = _dense(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_forward_matches_ring_oracle():
    q, k, v = _rand_qkv(jax.random.PRNGKey(1))
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_grads_match_dense(causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), b=1, t=128, h=2, dh=16)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        return jnp.sum(o * jnp.cos(o))  # nonlinear reduction

    def loss_dense(q, k, v):
        o = _dense(q, k, v, causal).astype(q.dtype)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=5e-4, atol=5e-4, err_msg=name)


def test_uneven_blocks():
    # block_q != block_k exercises the causal loop-bound arithmetic
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), t=256)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=64)
    ref = _dense(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    out2 = flash_attention(q, k, v, causal=True, block_q=64, block_k=128)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_bf16_io():
    q, k, v = _rand_qkv(jax.random.PRNGKey(4), t=128, dh=64)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(qb, kb, vb, causal=True, block_q=64, block_k=64)
    assert out.dtype == jnp.bfloat16
    ref = _dense(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=0.05, atol=0.05)

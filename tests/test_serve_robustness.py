"""tpudp.serve robustness layer: the contract is that NOTHING a client,
drafter, or device step does can wedge the arena or corrupt a surviving
stream.

  1. BOUNDED ADMISSION — ``queue_limit`` sheds overload with a typed
     ``QueueFull`` instead of growing the host queue; draining the queue
     re-opens admission.
  2. DEADLINES — expired ``deadline_s``/``ttft_deadline_s`` budgets
     retire requests with ``FinishReason.DEADLINE``; emitted tokens stay
     on the handle, the slot frees for queued work.
  3. DRAFTER QUARANTINE — a raising / malformed / slow drafter is
     permanently quarantined and every surviving greedy output stays
     bit-identical to ``generate()`` (drafts are hints; the referee is
     parity, exactly as in tests/test_speculate.py).
  4. STEP CONTAINMENT — an exception escaping a device step requeues the
     in-flight requests once (tokens + PRNG chain carried over, so the
     retry continues bit-identically) and retires second-time failures
     with ``ERROR``; the arena keeps serving.
  5. GRACEFUL SHUTDOWN — ``drain()`` finishes accepted work and rejects
     new submits; ``close()`` retires everything immediately.
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from tpudp.models.generate import generate
from tpudp.models.gpt2 import gpt2_small
from tpudp.serve import (Engine, EngineClosed, FinishReason, NgramDrafter,
                         QueueFull, RequestFailed)
from tpudp.serve.faults import (BitFlipLogits, FailingDrafter, FaultySteps,
                                InjectedFault, MalformedDrafter, SlowDrafter,
                                SlowSteps)
from tpudp.train import init_state, make_optimizer
from tpudp.utils.watchdog import Watchdog

TINY = dict(vocab_size=61, max_seq_len=64, num_layers=2, num_heads=2,
            d_model=32)


@pytest.fixture(scope="module")
def model_and_params():
    model = gpt2_small(**TINY)
    state = init_state(model, make_optimizer(), input_shape=(1, 8))
    return model, state.params


def _reference(model, params, prompt, n):
    return np.asarray(generate(model, params, jnp.asarray(prompt[None]), n))


def _engine(model, params, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("prefill_chunk", 8)
    return Engine(model, params, **kw)


# -- bounded admission -------------------------------------------------


def test_queue_limit_sheds_with_queue_full(model_and_params):
    """Submits past queue_limit raise QueueFull and bump the shed
    counter; draining the queue (admission) re-opens the door —
    backpressure, not a one-way valve."""
    model, params = model_and_params
    rng = np.random.default_rng(0)
    p = rng.integers(0, 61, size=4).astype(np.int32)
    eng = _engine(model, params, num_slots=1, queue_limit=2)
    h1 = eng.submit(p, 3)
    h2 = eng.submit(p, 3)
    with pytest.raises(QueueFull, match="queue_limit"):
        eng.submit(p, 3)
    assert eng.stats["shed"] == 1
    eng.step()  # admits h1 -> queue depth back under the limit
    h3 = eng.submit(p, 3)
    eng.run_until_complete()
    assert all(h.finish_reason is FinishReason.COMPLETE
               for h in (h1, h2, h3))
    ref = _reference(model, params, p, 3)[0, 4:]
    for h in (h1, h2, h3):
        np.testing.assert_array_equal(ref, np.asarray(h.tokens))


def test_queue_limit_validation(model_and_params):
    model, params = model_and_params
    with pytest.raises(ValueError, match="queue_limit"):
        _engine(model, params, queue_limit=0)
    with pytest.raises(ValueError, match="drafter_timeout_s"):
        _engine(model, params, drafter_timeout_s=0.0)
    with pytest.raises(ValueError, match="step_timeout_s"):
        _engine(model, params, step_timeout_s=-1.0)


# -- deadlines ---------------------------------------------------------


def test_ttft_deadline_expires_queued_request(model_and_params):
    """A queued request whose TTFT budget expires before it reaches a
    slot retires with DEADLINE (no slot, no prefill chunk wasted); the
    co-resident request is untouched."""
    model, params = model_and_params
    rng = np.random.default_rng(1)
    p = rng.integers(0, 61, size=4).astype(np.int32)
    eng = _engine(model, params, num_slots=1)
    h1 = eng.submit(p, 6)
    eng.step()  # h1 takes the only slot
    h2 = eng.submit(p, 3, ttft_deadline_s=1e-6)
    time.sleep(0.002)
    eng.step()
    assert h2.done and h2.finish_reason is FinishReason.DEADLINE
    assert h2.tokens == [] and h2._slot is None
    assert eng.stats["deadline_expired"] == 1
    with pytest.raises(RequestFailed, match="deadline"):
        h2.result()
    eng.run_until_complete()
    np.testing.assert_array_equal(
        _reference(model, params, p, 6)[0, 4:], np.asarray(h1.tokens))


def test_deadline_mid_flight_keeps_tokens_and_frees_slot(model_and_params):
    """An in-flight request past deadline_s retires with DEADLINE: the
    tokens already emitted stay on the handle and the freed slot serves
    the next queued request (bit-exact, proving clean slot reuse)."""
    model, params = model_and_params
    rng = np.random.default_rng(2)
    p1 = rng.integers(0, 61, size=4).astype(np.int32)
    p2 = rng.integers(0, 61, size=9).astype(np.int32)
    eng = _engine(model, params, num_slots=1)
    h1 = eng.submit(p1, 20, deadline_s=0.05)
    h2 = eng.submit(p2, 4)
    while not h1.tokens:
        eng.step()
    assert not h1.done
    time.sleep(0.06)  # blow h1's total budget mid-flight
    eng.step()
    assert h1.done and h1.finish_reason is FinishReason.DEADLINE
    assert len(h1.tokens) >= 1  # partial progress preserved
    partial = list(h1.tokens)
    eng.run_until_complete()
    assert h1.tokens == partial  # nothing appended after expiry
    np.testing.assert_array_equal(
        _reference(model, params, p2, 4)[0, 9:], np.asarray(h2.tokens))
    assert eng.stats["deadline_expired"] == 1
    assert eng.slots_in_use == 0 and eng.queue_depth == 0


def test_ttft_deadline_stops_applying_after_first_token(model_and_params):
    """ttft_deadline_s is a first-token SLO only: once a token has been
    emitted, an elapsed TTFT budget must not retire the request."""
    model, params = model_and_params
    rng = np.random.default_rng(3)
    p = rng.integers(0, 61, size=4).astype(np.int32)
    eng = _engine(model, params, num_slots=1)
    h = eng.submit(p, 4, ttft_deadline_s=5.0)
    while not h.tokens:
        eng.step()
    time.sleep(0.002)  # well under 5s; and the budget no longer applies
    eng.run_until_complete()
    assert h.finish_reason is FinishReason.COMPLETE
    np.testing.assert_array_equal(
        _reference(model, params, p, 4)[0, 4:], np.asarray(h.tokens))


def test_deadline_validation(model_and_params):
    model, params = model_and_params
    eng = _engine(model, params)
    p = np.zeros(4, np.int32)
    with pytest.raises(ValueError, match="deadline_s"):
        eng.submit(p, 2, deadline_s=0.0)
    with pytest.raises(ValueError, match="ttft_deadline_s"):
        eng.submit(p, 2, ttft_deadline_s=-1.0)


# -- drafter quarantine ------------------------------------------------


def _parity_run(model, params, eng, prompts, max_new):
    handles = [eng.submit(p, n) for p, n in zip(prompts, max_new)]
    eng.run_until_complete()
    for p, n, h in zip(prompts, max_new, handles):
        assert h.finish_reason is FinishReason.COMPLETE
        np.testing.assert_array_equal(
            _reference(model, params, p, n)[0, p.size:],
            np.asarray(h.tokens))
    return handles


def test_raising_drafter_quarantined_with_parity(model_and_params):
    """A drafter that dies mid-run is quarantined; every output stays
    bit-identical to generate(), and the engine stops paying for verify
    windows from the quarantine on."""
    model, params = model_and_params
    rng = np.random.default_rng(4)
    # Repetitive prompts so the healthy inner drafter actually drafts.
    prompts = [np.tile(rng.integers(0, 61, size=3), 4)[:9].astype(np.int32)
               for _ in range(3)]
    eng = _engine(model, params, speculate_k=2,
                  drafter=FailingDrafter(inner=NgramDrafter(),
                                         ok_proposals=2))
    _parity_run(model, params, eng, prompts, [6, 6, 6])
    assert eng.drafter_quarantined
    assert "InjectedFault" in eng.drafter_quarantine_reason
    assert eng.stats["drafter_quarantined"] == 1
    # Quarantine is permanent: later requests never re-enter the verify
    # path (no drafter call can stall or corrupt them again).
    verify_steps = eng.stats["verify_steps"]
    _parity_run(model, params, eng, prompts[:1], [4])
    assert eng.stats["verify_steps"] == verify_steps


@pytest.mark.parametrize("mode", MalformedDrafter.MODES)
def test_malformed_drafter_quarantined_with_parity(model_and_params, mode):
    model, params = model_and_params
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, 61, size=5).astype(np.int32)]
    eng = _engine(model, params, speculate_k=3,
                  drafter=MalformedDrafter(mode))
    _parity_run(model, params, eng, prompts, [6])
    assert eng.drafter_quarantined
    assert eng.stats["drafter_quarantined"] == 1


def test_malformed_proposal_counts_as_rejected(model_and_params):
    """An out-of-vocab proposal is charged proposed-and-rejected, so
    acceptance accounting stays truthful through a quarantine."""
    model, params = model_and_params
    rng = np.random.default_rng(6)
    p = rng.integers(0, 61, size=5).astype(np.int32)
    eng = _engine(model, params, speculate_k=2,
                  drafter=MalformedDrafter("out_of_vocab"))
    h = eng.submit(p, 4)
    eng.run_until_complete()
    assert h.draft_proposed > 0 and h.draft_accepted == 0
    assert h.acceptance_rate == 0.0 and eng.acceptance_rate == 0.0


def test_slow_drafter_quarantined_by_time_budget(model_and_params):
    """A drafter exceeding drafter_timeout_s per propose is quarantined
    even though its tokens are valid — a stalling drafter is as bad as a
    lying one for a latency SLO."""
    model, params = model_and_params
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 61, size=5).astype(np.int32)]
    eng = _engine(model, params, speculate_k=2, drafter_timeout_s=0.01,
                  drafter=SlowDrafter(0.05))
    _parity_run(model, params, eng, prompts, [5])
    assert eng.drafter_quarantined
    assert "drafter_timeout_s" in eng.drafter_quarantine_reason


def test_blocking_drafter_detected_by_watchdog(model_and_params):
    """A drafter that BLOCKS past the watchdog deadline (no
    drafter_timeout_s set — the host-side timing check never sees a call
    that hasn't returned) is caught by the scoped watchdog guard armed
    around propose(): the monitor fires while propose is blocked
    (kill=True would exit for the scheduler right there) and kill=False
    quarantines the drafter the moment the call comes back.  Outputs
    stay bit-identical throughout."""
    model, params = model_and_params
    rng = np.random.default_rng(18)
    prompts = [rng.integers(0, 61, size=5).astype(np.int32)]
    wd = Watchdog(timeout_s=0.05, kill=False, poll_s=0.01).start()
    try:
        eng = _engine(model, params, speculate_k=2, watchdog=wd,
                      step_timeout_s=0.05, drafter=SlowDrafter(0.2))
        _parity_run(model, params, eng, prompts, [5])
        assert eng.drafter_quarantined
        assert "watchdog deadline" in eng.drafter_quarantine_reason
        assert eng.stats["step_failures"] == 0  # charged to the drafter
    finally:
        wd.stop()


# -- step-failure containment ------------------------------------------


def test_transient_step_fault_requeues_and_completes_with_parity(
        model_and_params):
    """One injected device-step failure: every in-flight request is
    requeued once and finishes bit-identically to generate() — a
    transient fault costs latency, never correctness or data."""
    model, params = model_and_params
    rng = np.random.default_rng(8)
    prompts = [rng.integers(0, 61, size=n).astype(np.int32)
               for n in (5, 9, 3)]
    hook = FaultySteps(fail_at={6})  # whatever program call 6 lands on
    eng = _engine(model, params, step_fault_hook=hook)
    _parity_run(model, params, eng, prompts, [6, 5, 7])
    assert hook.fired and eng.stats["step_failures"] == 1
    assert eng.stats["requeued"] >= 1 and eng.stats["errors"] == 0
    assert eng.slots_in_use == 0 and eng.queue_depth == 0


def test_step_fault_sampled_request_resumes_bit_identically(
        model_and_params):
    """The requeue carries the per-slot PRNG chain, so even a SAMPLED
    request survives a step failure with bit-identical draws (the
    serving analogue of elastic resume's exactly-once contract)."""
    model, params = model_and_params
    rng = np.random.default_rng(9)
    p = rng.integers(0, 61, size=5).astype(np.int32)

    def tokens_of(hook):
        eng = _engine(model, params, num_slots=1, step_fault_hook=hook)
        h = eng.submit(p, 8, temperature=0.9, top_k=12, seed=7)
        eng.run_until_complete()
        assert h.finish_reason is FinishReason.COMPLETE
        return list(h.tokens)

    clean = tokens_of(None)
    faulted = tokens_of(FaultySteps(fail_at={4}, kind="decode"))
    assert faulted == clean


def test_persistent_step_fault_retires_error_and_arena_survives(
        model_and_params):
    """A fault that keeps firing exhausts the requeue-once budget: the
    affected requests retire with ERROR (result() raises; partial tokens
    stay) while the arena itself keeps serving — clear the hook and the
    next request completes with parity."""
    model, params = model_and_params
    rng = np.random.default_rng(10)
    p = rng.integers(0, 61, size=4).astype(np.int32)
    hook = FaultySteps(fail_at=set(range(200)), kind="decode")
    eng = _engine(model, params, num_slots=1, step_fault_hook=hook)
    h = eng.submit(p, 6)
    eng.run_until_complete()
    assert h.done and h.finish_reason is FinishReason.ERROR
    assert isinstance(h.error, InjectedFault)
    with pytest.raises(RequestFailed, match="error"):
        h.result()
    assert eng.stats["errors"] == 1 and eng.stats["requeued"] == 1
    assert eng.slots_in_use == 0 and eng.queue_depth == 0
    # The arena was never wedged: with the fault gone, service resumes.
    eng.step_fault_hook = None
    h2 = eng.submit(p, 6)
    eng.run_until_complete()
    assert h2.finish_reason is FinishReason.COMPLETE
    np.testing.assert_array_equal(
        _reference(model, params, p, 6)[0, 4:], np.asarray(h2.tokens))


def test_step_fault_during_prefill_is_contained(model_and_params):
    """Failures in the prefill program are contained the same way as
    decode failures (the donated-arena rebuild covers every program)."""
    model, params = model_and_params
    rng = np.random.default_rng(11)
    p = rng.integers(0, 61, size=20).astype(np.int32)  # 3 chunks
    hook = FaultySteps(fail_at={1}, kind="prefill")
    eng = _engine(model, params, num_slots=1, max_len=48,
                  step_fault_hook=hook)
    h = eng.submit(p, 5)
    eng.run_until_complete()
    assert h.finish_reason is FinishReason.COMPLETE
    assert eng.stats["step_failures"] == 1
    np.testing.assert_array_equal(
        _reference(model, params, p, 5)[0, 20:], np.asarray(h.tokens))


# -- graceful shutdown -------------------------------------------------


def test_drain_finishes_accepted_work_and_rejects_new(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(12)
    prompts = [rng.integers(0, 61, size=n).astype(np.int32)
               for n in (4, 7, 5)]
    eng = _engine(model, params, num_slots=1)
    handles = [eng.submit(p, 4) for p in prompts]
    eng.step()  # first request in flight, two queued
    eng.drain()
    assert eng.closed and not eng.accepting
    assert all(h.finish_reason is FinishReason.COMPLETE for h in handles)
    for p, h in zip(prompts, handles):
        np.testing.assert_array_equal(
            _reference(model, params, p, 4)[0, p.size:],
            np.asarray(h.tokens))
    with pytest.raises(EngineClosed, match="no longer accepts"):
        eng.submit(prompts[0], 2)
    assert eng.step() == []  # closed engine's step is a no-op
    eng.drain()  # idempotent


def test_close_cancels_in_flight_and_sheds_queued(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(13)
    p = rng.integers(0, 61, size=4).astype(np.int32)
    eng = _engine(model, params, num_slots=1)
    h1 = eng.submit(p, 10)
    h2 = eng.submit(p, 3)
    h3 = eng.submit(p, 3)
    while not h1.tokens:
        eng.step()
    eng.close()
    assert h1.finish_reason is FinishReason.CANCELLED and h1.tokens
    assert h2.finish_reason is FinishReason.SHED
    assert h3.finish_reason is FinishReason.SHED
    assert eng.slots_in_use == 0 and eng.queue_depth == 0
    assert eng.stats["shed"] == 2 and eng.stats["cancelled"] == 1
    with pytest.raises(EngineClosed):
        eng.submit(p, 2)
    eng.close()  # idempotent


# -- generate_many orphan fix ------------------------------------------


def test_generate_many_failure_cancels_already_submitted(model_and_params):
    """A validation error on prompt i must not orphan prompts 0..i-1 in
    the queue forever (pre-fix they pinned queue slots until the engine
    died); the engine stays fully usable afterwards."""
    model, params = model_and_params
    rng = np.random.default_rng(14)
    good = rng.integers(0, 61, size=4).astype(np.int32)
    with_bad = [good, good, np.zeros(0, np.int32)]  # empty prompt: invalid
    eng = _engine(model, params)
    with pytest.raises(ValueError, match="prompt"):
        eng.generate_many(with_bad, 3)
    assert eng.queue_depth == 0 and eng.slots_in_use == 0
    assert eng.stats["cancelled"] == 2
    outs = eng.generate_many([good], 3)
    np.testing.assert_array_equal(
        _reference(model, params, good, 3)[0], outs[0])


# -- cancel() racing run_until_complete() ------------------------------


def test_cancel_queued_and_inflight_from_inside_token_iterator(
        model_and_params):
    """Cancel a still-queued request AND the in-flight request from
    inside the in-flight request's own token iterator (the consumer-
    disconnects-mid-stream shape): iteration ends promptly, the slot is
    reused cleanly (bit-parity referee), and stats stay consistent."""
    model, params = model_and_params
    rng = np.random.default_rng(15)
    p1 = rng.integers(0, 61, size=4).astype(np.int32)
    p2 = rng.integers(0, 61, size=6).astype(np.int32)
    p3 = rng.integers(0, 61, size=9).astype(np.int32)
    eng = _engine(model, params, num_slots=1)
    h1 = eng.submit(p1, 8)
    h2 = eng.submit(p2, 5)
    h3 = eng.submit(p3, 4)
    streamed = []
    for tok in h1:  # iteration drives the engine
        streamed.append(tok)
        if len(streamed) == 2:
            assert h2.cancel() is True   # still queued
            assert h1.cancel() is True   # in flight (this iterator!)
    assert h1.done and h1.cancelled and streamed == h1.tokens
    assert len(h1.tokens) == 2
    assert h2.done and h2.cancelled and h2.tokens == []
    assert not h3.done
    eng.run_until_complete()
    assert h3.finish_reason is FinishReason.COMPLETE
    np.testing.assert_array_equal(
        _reference(model, params, p3, 4)[0, 9:], np.asarray(h3.tokens))
    assert eng.stats["cancelled"] == 2 and eng.stats["completed"] == 1
    assert eng.stats["admitted"] == 2  # h2 never took a slot
    assert eng.slots_in_use == 0 and eng.queue_depth == 0


# -- watchdog arming ---------------------------------------------------


def test_watchdog_detects_wedged_step_and_engine_recovers(
        model_and_params):
    """A stalled device call (SlowSteps inside the watchdog's scoped
    deadline) is detected from OUTSIDE the blocked call; with kill=False
    the hang surfaces as a step failure at the next device call, is
    contained like any other, and the engine keeps serving."""
    model, params = model_and_params
    rng = np.random.default_rng(16)
    p = rng.integers(0, 61, size=4).astype(np.int32)
    wd = Watchdog(timeout_s=0.05, kill=False, poll_s=0.01).start()
    try:
        eng = _engine(model, params, num_slots=1, watchdog=wd,
                      step_timeout_s=0.05,
                      step_fault_hook=SlowSteps(stall_at={3}, delay_s=0.2))
        h = eng.submit(p, 6)
        eng.run_until_complete()  # must terminate — the one forbidden
        #                           outcome is a wedge
        assert eng.stats["step_failures"] >= 1
        assert h.done
        # Containment acknowledged the hang, so the engine still serves.
        eng.step_fault_hook = None
        h2 = eng.submit(p, 4)
        eng.run_until_complete()
        assert h2.finish_reason is FinishReason.COMPLETE
        np.testing.assert_array_equal(
            _reference(model, params, p, 4)[0, 4:], np.asarray(h2.tokens))
    finally:
        wd.stop()


# -- finish_reason contract --------------------------------------------


def test_finish_reason_success_paths(model_and_params):
    """COMPLETE vs EOS are distinguished; both are success (result()
    returns) and both count under stats['completed']."""
    model, params = model_and_params
    rng = np.random.default_rng(17)
    p = rng.integers(0, 61, size=5).astype(np.int32)
    ref = _reference(model, params, p, 8)[0, 5:]
    eos = int(ref[2])
    eng = _engine(model, params)
    h_full = eng.submit(p, 8)
    h_eos = eng.submit(p, 8, eos_id=eos)
    eng.run_until_complete()
    assert h_full.finish_reason is FinishReason.COMPLETE and h_full.ok
    assert h_eos.finish_reason is FinishReason.EOS and h_eos.ok
    assert eng.stats["completed"] == 2
    np.testing.assert_array_equal(h_full.result()[5:], ref)
    assert h_eos.result()[-1] == eos


# -- tooling gate ------------------------------------------------------


def test_serve_soak_bench_gap_gate(tmp_path):
    """tools/bench_gaps serve_soak stage: CPU smoke rows, error rows,
    and FAILED soaks (parity or leak) never close a seed; banked passing
    TPU rows do (the watcher's window-accumulation contract, same rules
    as the serve/serve_spec stages)."""
    import json
    import os

    from tools.bench_gaps import SERVE_SOAK_SEEDS, serve_soak_missing

    d = str(tmp_path)
    assert serve_soak_missing(d) == list(SERVE_SOAK_SEEDS)
    rows = [
        {"metric": "serve_soak", "seed": 0, "value": 9,
         "parity_ok": True, "no_leak": True, "canary_ok": True,
         "device_kind": "cpu"},                        # smoke: no
        {"metric": "serve_soak", "seed": 1,
         "error": "relay wedged"},                     # error: no
        {"metric": "serve_soak", "seed": 2, "value": 9,
         "parity_ok": False, "no_leak": True, "canary_ok": True,
         "device_kind": "TPU v5 lite"},                # failed soak: no
    ]
    with open(os.path.join(d, "serve_soak.jsonl"), "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    assert serve_soak_missing(d) == list(SERVE_SOAK_SEEDS)
    with open(os.path.join(d, "serve_soak.history.jsonl"), "w") as f:
        f.write(json.dumps(
            {"metric": "serve_soak", "seed": 1, "value": 11,
             "parity_ok": True, "no_leak": True, "canary_ok": True,
             "device_kind": "TPU v5 lite"}) + "\n")
    assert serve_soak_missing(d) == [0, 2]  # banked passing row counts
    # canary false-positive gate: a quarantine during the clean soak
    # (canary_ok false) keeps the seed open even with parity + no_leak
    with open(os.path.join(d, "serve_soak.jsonl"), "a") as f:
        f.write(json.dumps(
            {"metric": "serve_soak", "seed": 2, "value": 9,
             "parity_ok": True, "no_leak": True, "canary_ok": False,
             "device_kind": "TPU v5 lite"}) + "\n")
    assert serve_soak_missing(d) == [0, 2]


# -- SDC canaries (silent corruption on the serving path) --------------


def _canary_engine(model, params, **kw):
    kw.setdefault("canary_every_s", 0.0)
    kw.setdefault("canary_new_tokens", 4)
    return _engine(model, params, **kw)


def test_canary_pins_reference_and_runs_clean(model_and_params):
    """Greedy decode is deterministic, so the first clean canary run IS
    the oracle: later runs byte-compare against it.  A healthy engine
    under real traffic must pin the reference, keep re-running, and
    never quarantine — while user outputs stay bit-exact."""
    model, params = model_and_params
    rng = np.random.default_rng(0)
    p = rng.integers(0, 61, size=4).astype(np.int32)
    eng = _canary_engine(model, params)
    hs = [eng.submit(p, 5) for _ in range(3)]
    eng.run_until_complete()
    for _ in range(40):
        eng.step()
    m = eng.metrics()["canary"]
    assert m["runs"] >= 2 and m["ref_pinned"]
    assert m["mismatch"] == 0 and not m["quarantined"]
    want = _reference(model, params, p, 5)[0, p.size:]
    for h in hs:
        assert h.finish_reason is FinishReason.COMPLETE
        np.testing.assert_array_equal(want, np.asarray(h.tokens))


def test_canary_pairs_never_emitted(model_and_params):
    """Canary traffic is the engine's own probe: its (request, token)
    pairs must never reach the emitted stream a server loop forwards
    to clients."""
    model, params = model_and_params
    eng = _canary_engine(model, params)
    emitted = []
    for _ in range(60):
        emitted += eng.step()
    assert eng.metrics()["canary"]["runs"] >= 1
    assert all(not getattr(r, "_canary", False) for r, _ in emitted)


def test_canary_mismatch_quarantines_and_parks_live_work(
        model_and_params):
    """A canary-only bit flip (invisible to every loud detector — no
    raise, no NaN, no counter) must: quarantine the engine with a
    reason naming the first divergent token, stop admission with a
    typed error, make step() a no-op, and PARK live requests unfinished
    so the cluster can migrate them out — never finish them on the
    condemned engine."""
    model, params = model_and_params
    rng = np.random.default_rng(0)
    p = rng.integers(0, 61, size=4).astype(np.int32)
    # call 5 = token 1 of the SECOND canary run (4 tokens each): run 1
    # pins the reference, run 2 diverges — and because the corrupted
    # token conditions later decode steps, downstream tokens shift too.
    inj = BitFlipLogits([(5, None, 3)], vocab=61, canary_only=True)
    eng = _canary_engine(model, params, token_fault_hook=inj)
    live = eng.submit(p, 20, seed=7)
    for _ in range(200):
        if eng.quarantined:
            break
        eng.step()
    assert eng.quarantined
    m = eng.metrics()["canary"]
    assert m["mismatch"] == 1 and m["quarantined"]
    assert "canary" in eng.quarantine_reason
    assert inj.fired and inj.fired[0][0] == 5
    assert live.finish_reason is None and eng.slots_in_use >= 1
    with pytest.raises(EngineClosed):
        eng.submit(p, 3)
    assert eng.step() == []


def test_canary_loud_failure_is_error_not_corruption(model_and_params):
    """A canary that fails LOUDLY (deadline, error) is an availability
    event, not corruption evidence: counted canary_errors, engine stays
    in service."""
    model, params = model_and_params
    hook = FaultySteps(fail_at=set(range(1, 200)))  # every step raises
    eng = _canary_engine(model, params, step_fault_hook=hook)
    for _ in range(30):
        eng.step()
    m = eng.metrics()["canary"]
    assert m["errors"] >= 1 and m["mismatch"] == 0
    assert not eng.quarantined


def test_canary_config_validation(model_and_params):
    model, params = model_and_params
    with pytest.raises(ValueError, match="canary_every_s"):
        _engine(model, params, canary_every_s=-1.0)
    with pytest.raises(ValueError, match="canary_new_tokens"):
        _engine(model, params, canary_every_s=1.0, canary_new_tokens=0)


def test_bitflip_logits_schedule_determinism():
    """The serving injector mirrors the training injectors' pinned
    determinism: calls index ELIGIBLE commits only (canary_only skips
    user traffic WITHOUT counting, so a canary schedule is stable no
    matter how much real traffic interleaves), a schedule entry fires
    once, and the corrupted token is always in-vocab and different."""

    class _R:
        pass

    canary = _R()
    canary._canary = True
    user = _R()
    inj = BitFlipLogits([(1, None, 3)], vocab=61, canary_only=True)
    assert inj(0, 7, user) == 7          # user commit: not counted
    assert inj(0, 7, canary) == 7        # eligible call 0: no match
    out = inj(2, 7, canary)              # eligible call 1: fires
    assert out != 7 and 0 <= out < 61
    assert inj.fired == [(1, 2, 7, out)]
    assert inj(2, 7, canary) == 7        # schedule exhausted
    # vocab fallback: a flip that would leave the vocabulary drops to
    # lower bits until the corrupt token is decodable
    inj2 = BitFlipLogits([(0, None, 6)], vocab=61)
    got = inj2(0, 60, object())
    assert got != 60 and 0 <= got < 61
    with pytest.raises(ValueError):
        BitFlipLogits([(-1, None, 0)])
    with pytest.raises(ValueError):
        BitFlipLogits([(0, None, 0)], vocab=1)

"""ResNet numerical parity vs a reference-style torch stack.

tests/test_torch_parity.py pins the VGG family to torch; this does the
same for the ResNet family (`tpudp/models/resnet.py`, BASELINE.json
configs[3]): build the IDENTICAL bottleneck architecture in torch
(torchvision conventions: v1.5 stride placement on the 3x3, 1x1-conv+BN
downsample, zero-init last BN scale — matching our flax module's
deliberate choices), transplant the torch weights, and assert forward
logits + a short SGD training trajectory agree.

A small config (stage_sizes=(1,1), width 16, 32x32 inputs) keeps the
1-core CPU runtime sane while exercising every distinct code path of the
family: stem conv+BN+maxpool, identity blocks, projection blocks with
stride, global average pool, classifier.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from tpudp.models.resnet import ResNet  # noqa: E402
from tpudp.train import init_state, make_optimizer, make_train_step  # noqa: E402

STAGES, WIDTH, CLASSES = (1, 1), 16, 10
BATCH, STEPS, LR, MOM, WD = 8, 3, 0.01, 0.9, 1e-4


class TorchBottleneck(torch.nn.Module):
    def __init__(self, cin, features, stride):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(cin, features, 1, bias=False)
        self.bn1 = torch.nn.BatchNorm2d(features)
        self.conv2 = torch.nn.Conv2d(features, features, 3, stride=stride,
                                     padding=1, bias=False)
        self.bn2 = torch.nn.BatchNorm2d(features)
        self.conv3 = torch.nn.Conv2d(features, 4 * features, 1, bias=False)
        self.bn3 = torch.nn.BatchNorm2d(features * 4)
        # zero-init residual (matches the flax module's scale_init=zeros)
        torch.nn.init.zeros_(self.bn3.weight)
        self.down = None
        if stride != 1 or cin != 4 * features:
            self.down = torch.nn.Sequential(
                torch.nn.Conv2d(cin, 4 * features, 1, stride=stride,
                                bias=False),
                torch.nn.BatchNorm2d(4 * features))

    def forward(self, x):
        y = torch.relu(self.bn1(self.conv1(x)))
        y = torch.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        r = x if self.down is None else self.down(x)
        return torch.relu(r + y)


class TorchResNet(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.stem = torch.nn.Conv2d(3, WIDTH, 7, stride=2, padding=3,
                                    bias=False)
        self.stem_bn = torch.nn.BatchNorm2d(WIDTH)
        self.pool = torch.nn.MaxPool2d(3, stride=2, padding=1)
        blocks, cin = [], WIDTH
        for stage, num in enumerate(STAGES):
            for block in range(num):
                stride = 2 if stage > 0 and block == 0 else 1
                feats = WIDTH * (2 ** stage)
                blocks.append(TorchBottleneck(cin, feats, stride))
                cin = feats * 4
        self.blocks = torch.nn.ModuleList(blocks)
        self.fc = torch.nn.Linear(cin, CLASSES)

    def forward(self, x):
        x = self.pool(torch.relu(self.stem_bn(self.stem(x))))
        for b in self.blocks:
            x = b(x)
        x = x.mean(dim=(2, 3))
        return self.fc(x)


def transplant(tmodel, params, batch_stats):
    from parity_utils import bn_params, bn_stats, conv_params, linear_params

    params = dict(params)
    bs = dict(batch_stats)
    params["stem_conv"] = conv_params(tmodel.stem)
    params["stem_bn"] = bn_params(tmodel.stem_bn)
    bs["stem_bn"] = bn_stats(tmodel.stem_bn)
    for i, tb in enumerate(tmodel.blocks):
        name = f"BottleneckBlock_{i}"
        p = {"Conv_0": conv_params(tb.conv1),
             "BatchNorm_0": bn_params(tb.bn1),
             "Conv_1": conv_params(tb.conv2),
             "BatchNorm_1": bn_params(tb.bn2),
             "Conv_2": conv_params(tb.conv3),
             "BatchNorm_2": bn_params(tb.bn3)}
        s = {"BatchNorm_0": bn_stats(tb.bn1),
             "BatchNorm_1": bn_stats(tb.bn2),
             "BatchNorm_2": bn_stats(tb.bn3)}
        if tb.down is not None:
            p["proj_conv"] = conv_params(tb.down[0])
            p["proj_bn"] = bn_params(tb.down[1])
            s["proj_bn"] = bn_stats(tb.down[1])
        # Both trees must cover the flax structure exactly — a flax-side
        # rename would otherwise leave stale params/running-stats behind.
        assert set(p) == set(params[name]), (
            f"{name}: transplant keys {sorted(p)} != "
            f"flax keys {sorted(params[name])}")
        assert set(s) == set(batch_stats[name]), (
            f"{name}: transplant stat keys {sorted(s)} != "
            f"flax stat keys {sorted(batch_stats[name])}")
        params[name], bs[name] = p, s
    params["Dense_0"] = linear_params(tmodel.fc)
    return params, bs


@pytest.fixture
def paired():
    torch.manual_seed(0)
    torch.set_num_threads(1)
    tmodel = TorchResNet()
    model = ResNet(stage_sizes=STAGES, width=WIDTH, num_classes=CLASSES)
    tx = make_optimizer(LR, MOM, WD)
    state = init_state(model, tx, input_shape=(1, 32, 32, 3))
    params, bs = transplant(tmodel, state.params, state.batch_stats)
    return tmodel, model, tx, state.replace(params=params, batch_stats=bs)


def test_resnet_forward_parity(paired):
    tmodel, model, _, state = paired
    rng = np.random.default_rng(0)
    x = rng.normal(size=(BATCH, 32, 32, 3)).astype(np.float32)
    tmodel.eval()
    with torch.no_grad():
        t_logits = tmodel(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    j_logits = np.asarray(model.apply(
        {"params": state.params, "batch_stats": state.batch_stats},
        jnp.asarray(x), train=False))
    np.testing.assert_allclose(j_logits, t_logits, rtol=1e-3, atol=1e-3)


def test_resnet_training_trajectory_parity(paired):
    tmodel, model, tx, state = paired
    rng = np.random.default_rng(1)
    xs = rng.normal(size=(STEPS, BATCH, 32, 32, 3)).astype(np.float32)
    ys = rng.integers(0, CLASSES, size=(STEPS, BATCH))

    tmodel.train()
    opt = torch.optim.SGD(tmodel.parameters(), lr=LR, momentum=MOM,
                          weight_decay=WD)
    crit = torch.nn.CrossEntropyLoss()
    t_losses = []
    for x, y in zip(xs, ys):
        opt.zero_grad()
        loss = crit(tmodel(torch.from_numpy(x.transpose(0, 3, 1, 2))),
                    torch.from_numpy(y))
        loss.backward()
        opt.step()
        t_losses.append(float(loss.detach()))

    step = make_train_step(model, tx, None, "none", spmd_mode="single",
                           donate=False)
    j_losses = []
    for x, y in zip(xs, ys):
        state, loss = step(state, jnp.asarray(x),
                           jnp.asarray(y, dtype=jnp.int32))
        j_losses.append(float(loss))

    np.testing.assert_allclose(j_losses, t_losses, rtol=5e-3, atol=5e-3)

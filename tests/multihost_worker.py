"""Subprocess worker for tests/test_multihost.py (not itself a test module).

One OS process per "host": forces a CPU backend with N local virtual
devices, joins the jax.distributed rendezvous (the reference's
``init_process`` analogue, ``src/Part 2a/main.py:148-153``), loads its
host-local shard through ShardedSampler+DataLoader, and drives the Trainer
— whose multi-process branch assembles global batches with
``jax.make_array_from_process_local_data``.  Rank 0 writes the final loss,
eval metrics, and parameters to a JSON file for trajectory comparison.

Usage: python multihost_worker.py RANK NPROC PORT LOCAL_DEVICES OUT_JSON
       [SYNC]
"""

import json
import os
import sys


def main() -> None:
    rank = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = int(sys.argv[3])
    local_devices = int(sys.argv[4])
    out_path = sys.argv[5]
    sync = sys.argv[6] if len(sys.argv) > 6 else "allreduce"

    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={local_devices}")
    os.environ.setdefault("TPUDP_NO_DOWNLOAD", "1")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    import jax

    jax.config.update("jax_platforms", "cpu")

    from tpudp.mesh import initialize_distributed, make_mesh

    if nproc > 1:
        initialize_distributed("127.0.0.1", nproc, rank, port=port)

    import flax.linen as nn
    import numpy as np

    from tpudp.data.cifar10 import _synthetic
    from tpudp.data.loader import DataLoader
    from tpudp.data.sampler import ShardedSampler
    from tpudp.train import Trainer

    class TinyNet(nn.Module):
        """BatchNorm-free so the trajectory is invariant to how samples
        land on devices (global-mean gradients only)."""

        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(10)(x)

    assert jax.process_count() == nproc
    mesh = make_mesh()  # all global devices
    global_batch = 8
    ds = _synthetic(32, seed=7)
    loader = DataLoader(
        ds, global_batch // nproc,
        sampler=ShardedSampler(len(ds.images), nproc, rank, shuffle=False),
        train=False, backend="numpy")

    trainer = Trainer(TinyNet(), mesh, sync, learning_rate=0.01,
                      log_every=2, log_fn=lambda s: None, seed=0)
    loss = trainer.train_epoch(loader, 0)
    # DP desync detector, exercised ACROSS the real process boundary:
    # intra-process shard comparison + cross-process fingerprints.
    from tpudp.utils.consistency import (verify_across_processes,
                                         verify_replicas)

    consistency_checked = verify_replicas({"params": trainer.state.params})
    verify_across_processes({"params": trainer.state.params})
    eval_loss, eval_acc = trainer.evaluate(loader)

    if rank == 0:
        params = [np.asarray(jax.device_get(p)).ravel().tolist()
                  for p in jax.tree.leaves(trainer.state.params)]
        with open(out_path, "w") as f:
            json.dump({"loss": loss, "eval_loss": eval_loss,
                       "eval_acc": eval_acc, "params": params,
                       "consistency_checked": consistency_checked}, f)

    if nproc > 1:
        jax.distributed.shutdown()


if __name__ == "__main__":
    main()

"""Test harness: simulate an 8-device TPU pod slice on CPU.

SURVEY.md §4: multi-"node" DP is testable on one host via
``--xla_force_host_platform_device_count=8``.  The axon sitecustomize pins
``jax_platforms`` to the TPU plugin, so we both set the env var and override
the config before any backend initialization.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
# Hermetic tests: never attempt the CIFAR-10 network fetch.
os.environ.setdefault("TPUDP_NO_DOWNLOAD", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def audit_capture():
    """ONE trace-audit capture shared by every analysis test module —
    capturing re-traces all registered step programs (~7s), so the
    suite must not pay it per module."""
    from tpudp.analysis import audit

    audit.force_smoke_backend()
    return audit.capture()


@pytest.fixture(scope="session")
def mesh8():
    from tpudp.mesh import make_mesh

    assert jax.device_count() >= 8, "virtual CPU device count not applied"
    return make_mesh(8)


@pytest.fixture(scope="session")
def mesh4():
    from tpudp.mesh import make_mesh

    return make_mesh(4)

"""Speculative decoding (tpudp.serve.speculate + the engine's verify
step): the contract is the serve engine's, extended.

  1. GREEDY PARITY — speculative output is bit-identical to standalone
     ``generate()`` AND to a non-speculative ``Engine`` for EVERY
     drafter and every k: drafts are hints, never correctness inputs
     (an adversarial drafter proposing garbage must change nothing but
     the speedup).  The per-position vmapped attention in the decode
     twins makes the k+1-token verify window bitwise-equal to k+1
     single-token steps, so this parity is structural, not a tolerance.
  2. DISTRIBUTION PRESERVATION — sampled rows use rejection sampling
     against the truncated target distribution (point-mass proposals),
     so the per-token output distribution is exactly the non-speculative
     one, and a seed fully reproduces a request's draws.
  3. STATIC SHAPES — the verify step compiles once per
     (config, num_slots, max_len, k); admission/retirement/cancellation
     churn never recompiles (TRACE_COUNTS observes this).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudp.models.generate import generate
from tpudp.models.gpt2 import gpt2_small
from tpudp.serve import DraftModelDrafter, Engine, NgramDrafter, TRACE_COUNTS
from tpudp.train import init_state, make_optimizer

TINY = dict(vocab_size=61, max_seq_len=64, num_layers=2, num_heads=2,
            d_model=32)


@pytest.fixture(scope="module")
def model_and_params():
    model = gpt2_small(**TINY)
    state = init_state(model, make_optimizer(), input_shape=(1, 8))
    return model, state.params


def _reference(model, params, prompt, n):
    return np.asarray(generate(model, params, jnp.asarray(prompt[None]), n))


class GarbageDrafter:
    """Adversarial drafter: always proposes k copies of an out-of-range
    id.  The robustness layer QUARANTINES it on first sight (out-of-
    vocab proposals are a drafter-contract violation) and the engine
    falls back to plain decode — output must be bit-identical anyway,
    with the garbage proposal charged as proposed-and-rejected."""

    def propose(self, context, k):
        return np.full(k, 10 ** 9, np.int64)


# -- drafters ----------------------------------------------------------


def test_ngram_drafter_repetitive_sequences():
    d = NgramDrafter(max_ngram=3)
    # Suffix [1, 2, 3] last occurred at the start; continuation is 4, 1, 2.
    ctx = np.array([1, 2, 3, 4, 1, 2, 3], np.int32)
    np.testing.assert_array_equal(d.propose(ctx, 3), [4, 1, 2])
    # k clamps to what the context holds after the match.
    np.testing.assert_array_equal(d.propose(ctx, 99), [4, 1, 2, 3])
    # Longest match wins: suffix [2, 9] beats the shorter [9] match.
    ctx = np.array([2, 9, 7, 9, 8, 2, 9], np.int32)
    np.testing.assert_array_equal(d.propose(ctx, 1), [7])
    # MOST RECENT match wins within one n.
    ctx = np.array([5, 1, 5, 2, 5], np.int32)
    np.testing.assert_array_equal(d.propose(ctx, 1), [2])
    # No repeated suffix -> no proposal; short contexts -> no proposal.
    assert d.propose(np.array([1, 2, 3], np.int32), 3).size == 0
    assert d.propose(np.array([7], np.int32), 3).size == 0
    assert d.propose(np.array([7, 7, 7], np.int32), 2).size == 2


def test_ngram_drafter_validation():
    with pytest.raises(ValueError, match="min_ngram"):
        NgramDrafter(min_ngram=0)
    with pytest.raises(ValueError, match="max_ngram"):
        NgramDrafter(max_ngram=1, min_ngram=2)


def test_draft_model_drafter_buckets_compile_once(model_and_params):
    """Context lengths sharing a power-of-two bucket share one compiled
    drafting program; a new bucket (or k) compiles exactly once."""
    model, params = model_and_params
    d = DraftModelDrafter(model, params)
    rng = np.random.default_rng(0)
    base = TRACE_COUNTS["draft_model"]
    for n in (5, 6, 7, 8):  # all bucket 8
        out = d.propose(rng.integers(0, 61, size=n).astype(np.int32), 3)
        assert out.shape == (3,) and out.dtype == np.int32
    assert TRACE_COUNTS["draft_model"] == base + 1
    d.propose(rng.integers(0, 61, size=9).astype(np.int32), 3)  # bucket 16
    assert TRACE_COUNTS["draft_model"] == base + 2


def test_drafter_vocab_mismatch_rejected(model_and_params):
    model, params = model_and_params
    other = gpt2_small(**{**TINY, "vocab_size": 17})
    other_params = init_state(other, make_optimizer(),
                              input_shape=(1, 8)).params
    with pytest.raises(ValueError, match="vocab"):
        Engine(model, params, num_slots=2, speculate_k=2,
               drafter=DraftModelDrafter(other, other_params))


# -- greedy parity -----------------------------------------------------


@pytest.mark.parametrize("k,drafter", [
    (1, "ngram"), (4, "ngram"), (3, "model"), (4, "garbage")])
def test_greedy_parity_speculative_staggered(model_and_params, k, drafter):
    """The serve suite's adversarial schedule — mixed prompt lengths,
    staggered admissions, retirement + slot reuse through 2 slots — with
    speculation on: every output bit-identical to generate() and to the
    non-speculative engine, for a useful drafter, a same-model drafter
    (acceptance 1), and a garbage drafter (acceptance 0)."""
    model, params = model_and_params
    drafter = {"ngram": None,
               "model": lambda: DraftModelDrafter(model, params),
               "garbage": GarbageDrafter}[drafter]
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, TINY["vocab_size"], size=n)
               .astype(np.int32) for n in (5, 19, 3, 9, 24)]
    max_new = [6, 4, 8, 5, 7]

    eng = Engine(model, params, num_slots=2, max_len=48, prefill_chunk=8,
                 speculate_k=k, drafter=drafter() if drafter else None)
    handles = [eng.submit(prompts[0], max_new[0])]
    eng.step()
    eng.step()
    handles.append(eng.submit(prompts[1], max_new[1]))
    handles.append(eng.submit(prompts[2], max_new[2]))
    eng.step()
    handles.append(eng.submit(prompts[3], max_new[3]))
    handles.append(eng.submit(prompts[4], max_new[4]))
    eng.run_until_complete()

    plain = Engine(model, params, num_slots=2, max_len=48, prefill_chunk=8)
    plain_handles = [plain.submit(p, n) for p, n in zip(prompts, max_new)]
    plain.run_until_complete()
    for p, n, h, ph in zip(prompts, max_new, handles, plain_handles):
        ref = _reference(model, params, p, n)
        got = np.concatenate([p, np.asarray(h.tokens, np.int32)])
        np.testing.assert_array_equal(ref[0], got)   # vs generate()
        assert h.tokens == ph.tokens                 # vs plain Engine
    assert eng.stats["completed"] == 5


def test_greedy_parity_eos_mid_window(model_and_params):
    """An accepted EOS mid-window retires the request AT the eos; the
    window's remaining emitted tokens are dropped (sequential decode
    would never have produced them) and the freed slot serves the queue."""
    model, params = model_and_params
    rng = np.random.default_rng(4)
    p = rng.integers(0, 61, size=5).astype(np.int32)
    ref = _reference(model, params, p, 8)[0, 5:]
    eos = int(ref[3])
    first_hit = int(np.nonzero(ref == eos)[0][0])

    eng = Engine(model, params, num_slots=1, max_len=32, prefill_chunk=8,
                 speculate_k=4)
    h = eng.submit(p, 8, eos_id=eos)
    q = eng.submit(rng.integers(0, 61, size=4).astype(np.int32), 3)
    eng.run_until_complete()
    assert h.tokens == ref[:first_hit + 1].tolist()
    assert h.done and q.done and len(q.tokens) == 3


# Demoted to slow (PR 20 durations audit): the budget-clamp edge is
# exercised fast by the remaining speculate parity tests and
# tests/test_spec_fused.py at the same k>budget geometry.
@pytest.mark.slow
def test_greedy_parity_k_longer_than_budget(model_and_params):
    """speculate_k larger than a request's whole budget: emitted tokens
    beyond max_new_tokens are dropped, the rest match exactly."""
    model, params = model_and_params
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, 61, size=n).astype(np.int32)
               for n in (4, 12, 7)]
    eng = Engine(model, params, num_slots=2, max_len=32, prefill_chunk=8,
                 speculate_k=6)
    outs = eng.generate_many(prompts, 2)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(_reference(model, params, p, 2)[0], o)


def test_submit_bound_reserves_window_scratch(model_and_params):
    """The arena reserves speculate_k positions per slot: a request that
    fits a plain engine can overflow a speculative one (the window's
    rejected tail must never wrap past max_len)."""
    model, params = model_and_params
    p = np.zeros(20, np.int32)
    Engine(model, params, num_slots=1, max_len=32,
           prefill_chunk=8).submit(p, 12)  # exactly fits
    eng = Engine(model, params, num_slots=1, max_len=32, prefill_chunk=8,
                 speculate_k=4)
    with pytest.raises(ValueError, match="speculate_k"):
        eng.submit(p, 12)
    eng.submit(p, 8)  # 20 + 8 + 4 = 32 fits
    with pytest.raises(ValueError, match="speculate_k"):
        Engine(model, params, num_slots=1, max_len=8, prefill_chunk=8,
               speculate_k=8)
    with pytest.raises(ValueError, match="drafter requires"):
        Engine(model, params, num_slots=1, drafter=NgramDrafter())


# -- sampling ----------------------------------------------------------


def test_sampled_speculation_reproducible_and_independent(model_and_params):
    """Same seed -> same draws with speculation on, regardless of
    co-residents (per-slot key chains advance once per OWN verify
    window, drafts depend only on own context)."""
    model, params = model_and_params
    rng = np.random.default_rng(5)
    p = rng.integers(0, 61, size=5).astype(np.int32)

    def tokens_of(crowded):
        eng = Engine(model, params, num_slots=3, max_len=32,
                     prefill_chunk=8, speculate_k=3)
        if crowded:
            eng.submit(rng.integers(0, 61, size=7).astype(np.int32), 9,
                       temperature=1.3, seed=99)
        h = eng.submit(p, 8, temperature=0.9, top_k=12, top_p=0.9, seed=7)
        if crowded:
            eng.submit(rng.integers(0, 61, size=3).astype(np.int32), 4)
        eng.run_until_complete()
        return list(h.tokens)

    alone = tokens_of(False)
    assert len(alone) == 8
    assert tokens_of(False) == alone
    assert tokens_of(True) == alone
    assert all(0 <= t < TINY["vocab_size"] for t in alone)


def test_verify_tokens_greedy_rule():
    """The acceptance rule directly: longest draft prefix matching the
    target argmax, plus the free correction/bonus token."""
    from tpudp.ops.sampling import verify_tokens

    v = 7
    # Row 0: targets [3, 4, 5, 6]; drafts [3, 4, 9%v] -> accept 2, emit
    # [3, 4, 5].  Row 1: n_draft=0 -> plain decode, emit [2].
    # Row 2: all 3 drafts accepted -> emit 4 incl. the bonus target.
    logits = np.full((3, 4, v), -10.0, np.float32)
    for j, t in enumerate([3, 4, 5, 6]):
        logits[0, j, t] = 0.0
    logits[1, 0, 2] = 0.0
    for j, t in enumerate([1, 2, 3, 4]):
        logits[2, j, t] = 0.0
    draft = np.array([[3, 4, 2], [0, 0, 0], [1, 2, 3]], np.int32)
    n_draft = np.array([3, 0, 3], np.int32)
    zeros = jnp.zeros(3)
    keys = jnp.zeros((3, 2), jnp.uint32)
    toks, n_emit = verify_tokens(
        jnp.asarray(logits), jnp.asarray(draft), jnp.asarray(n_draft),
        zeros, jnp.zeros(3, jnp.int32), jnp.ones(3), keys)
    toks, n_emit = np.asarray(toks), np.asarray(n_emit)
    assert n_emit.tolist() == [3, 1, 4]
    assert toks[0, :3].tolist() == [3, 4, 5]
    assert toks[1, :1].tolist() == [2]
    assert toks[2].tolist() == [1, 2, 3, 4]


def test_verify_tokens_rejection_preserves_distribution():
    """Rejection sampling with a point-mass proposal: the first emitted
    token's distribution must equal plain sampling from the target
    softmax NO MATTER what the draft proposes (here: always token 0,
    which has low probability).  Empirical check over many keys."""
    from tpudp.ops.sampling import verify_tokens

    logits = jnp.asarray(
        np.log(np.array([0.05, 0.5, 0.25, 0.15, 0.05], np.float32)))
    n = 4000
    lg = jnp.broadcast_to(logits[None, None, :], (n, 2, 5))
    draft = jnp.zeros((n, 1), jnp.int32)  # always propose token 0
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(n, dtype=jnp.uint32))
    toks, _ = verify_tokens(lg, draft, jnp.ones(n, jnp.int32),
                            jnp.ones(n), jnp.zeros(n, jnp.int32),
                            jnp.ones(n), keys)
    first = np.asarray(toks)[:, 0]
    freq = np.bincount(first, minlength=5) / n
    np.testing.assert_allclose(freq, [0.05, 0.5, 0.25, 0.15, 0.05],
                               atol=0.03)


def test_truncation_static_and_dynamic_paths_agree():
    """The dedupe satellite's referee: generate()'s static
    ``_truncate_logits`` wrapper and the serve path's traced
    ``truncate_logits`` produce bitwise-identical masks for every
    (top_k, top_p) combination — one implementation, zero drift."""
    from tpudp.models.generate import _truncate_logits
    from tpudp.ops.sampling import truncate_logits

    rng = np.random.default_rng(7)
    logits = jnp.asarray(rng.normal(size=(5, 33)), jnp.float32)
    for top_k, top_p in [(None, 0.7), (4, None), (4, 0.7), (1, 0.01),
                         (40, 1.0), (None, None)]:
        static = _truncate_logits(logits, top_k, top_p)
        dyn = truncate_logits(
            logits, jnp.full((5,), top_k or 0, jnp.int32),
            jnp.full((5,), 1.0 if top_p is None else top_p, jnp.float32))
        np.testing.assert_array_equal(np.asarray(static), np.asarray(dyn))


# -- static shapes -----------------------------------------------------


def test_verify_step_compiles_once_across_churn(model_and_params):
    """The static-shape invariant, speculation edition: one verify-step
    compile per engine geometry; admission, retirement, cancellation,
    and draft-length churn (0..k drafts per row) never recompile."""
    model, params = model_and_params
    rng = np.random.default_rng(1)
    # A geometry no other test uses (the module-level jit cache is shared).
    eng = Engine(model, params, num_slots=3, max_len=40, prefill_chunk=8,
                 speculate_k=2)
    pre_verify = TRACE_COUNTS["verify_step"]

    def churn(seed0):
        for i in range(6):
            eng.submit(rng.integers(0, 61, size=3 + 5 * (i % 3))
                       .astype(np.int32), 2 + i,
                       temperature=0.5 * (i % 2),
                       top_k=4 if i % 2 else None, seed=seed0 + i)
        eng.step()
        victim = next(r for r in eng._slots if r is not None)
        eng.cancel(victim)
        eng.run_until_complete()

    # First batch is the warmup: it exercises drafted steps (verify
    # program), no-draft steps (the fall-through decode program), both
    # sampling modes, and a cancellation — everything the engine can
    # dispatch to.  A repetitive extra prompt forces at least one
    # drafted window even if the random outputs never repeat.
    eng.submit(np.array([7, 7, 7, 7], np.int32), 4).result()
    churn(0)
    base_verify = TRACE_COUNTS["verify_step"]
    base_decode = TRACE_COUNTS["decode_step"]
    base_prefill = TRACE_COUNTS["prefill_chunk"]
    assert base_verify > pre_verify  # the repetitive prompt did speculate

    # Second batch: identical churn, zero new traces allowed.
    churn(6)
    assert TRACE_COUNTS["verify_step"] == base_verify
    assert TRACE_COUNTS["decode_step"] == base_decode
    assert TRACE_COUNTS["prefill_chunk"] == base_prefill
    assert eng.stats["cancelled"] == 2


# -- cancellation ------------------------------------------------------


def test_cancel_frees_slot_and_reuse_is_clean(model_and_params):
    """Cancelling an in-flight request frees its slot immediately; the
    next request reuses the slot with clean KV (bit-parity referee)."""
    model, params = model_and_params
    rng = np.random.default_rng(8)
    p1 = rng.integers(0, 61, size=5).astype(np.int32)
    p2 = rng.integers(0, 61, size=9).astype(np.int32)
    eng = Engine(model, params, num_slots=1, max_len=32, prefill_chunk=8)
    h1 = eng.submit(p1, 20)
    for _ in range(4):
        eng.step()
    assert not h1.done and eng.slots_in_use == 1
    emitted_before = list(h1.tokens)
    assert eng.cancel(h1) is True
    assert h1.done and h1.cancelled and eng.slots_in_use == 0
    assert h1.tokens == emitted_before  # nothing appended after cancel
    assert eng.cancel(h1) is False  # idempotent
    h2 = eng.submit(p2, 6)
    eng.run_until_complete()
    np.testing.assert_array_equal(
        _reference(model, params, p2, 6)[0, 9:], np.asarray(h2.tokens))
    # result() on a cancelled request raises (finish_reason contract);
    # the partial tokens stay on the handle.
    from tpudp.serve import FinishReason, RequestFailed

    with pytest.raises(RequestFailed, match="cancelled"):
        h1.result()
    assert h1.finish_reason is FinishReason.CANCELLED
    assert h1.tokens == emitted_before
    assert eng.stats["cancelled"] == 1 and eng.stats["completed"] == 1


def test_cancel_queued_request_never_occupies_a_slot(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(3)
    p = rng.integers(0, 61, size=4).astype(np.int32)
    eng = Engine(model, params, num_slots=1, max_len=32, prefill_chunk=8)
    h1 = eng.submit(p, 3)
    h2 = eng.submit(p, 3)
    h3 = eng.submit(p, 3)
    assert h2.cancel() is True and h2.done and h2.cancelled
    eng.run_until_complete()
    assert h1.done and h3.done and not h1.cancelled and not h3.cancelled
    assert len(h1.tokens) == 3 and len(h3.tokens) == 3 and h2.tokens == []
    assert eng.stats["admitted"] == 2  # h2 never took a slot


def test_cancel_mid_stream_iteration_terminates(model_and_params):
    """A consumer streaming a handle sees iteration end promptly after a
    cancel (no hang waiting for tokens that will never come)."""
    model, params = model_and_params
    rng = np.random.default_rng(6)
    p = rng.integers(0, 61, size=4).astype(np.int32)
    eng = Engine(model, params, num_slots=1, max_len=32, prefill_chunk=8)
    h = eng.submit(p, 10)
    got = []
    for tok in h:
        got.append(tok)
        if len(got) == 2:
            h.cancel()
    assert h.done and h.cancelled and got == h.tokens


# -- acceptance stats --------------------------------------------------


def test_acceptance_rate_stats(model_and_params):
    """Per-request and engine-wide acceptance accounting: a same-model
    drafter accepts everything, a garbage drafter nothing, and the
    engine aggregates across requests."""
    model, params = model_and_params
    rng = np.random.default_rng(11)
    p = rng.integers(0, 61, size=5).astype(np.int32)

    eng = Engine(model, params, num_slots=2, max_len=32, prefill_chunk=8,
                 speculate_k=2, drafter=DraftModelDrafter(model, params))
    h = eng.submit(p, 6)
    eng.run_until_complete()
    assert h.acceptance_rate == 1.0 and eng.acceptance_rate == 1.0
    assert h.draft_proposed > 0

    eng = Engine(model, params, num_slots=2, max_len=32, prefill_chunk=8,
                 speculate_k=2, drafter=GarbageDrafter())
    h = eng.submit(p, 6)
    eng.run_until_complete()
    assert h.acceptance_rate == 0.0 and eng.acceptance_rate == 0.0

    plain = Engine(model, params, num_slots=2, max_len=32, prefill_chunk=8)
    assert plain.acceptance_rate is None


# -- llama family ------------------------------------------------------


@pytest.mark.slow
def test_llama_family_speculative_greedy_parity():
    """The verify window's per-position attention holds for the RoPE/GQA
    lineage too: speculative llama output equals standalone generate()."""
    from tpudp.models.llama import llama_small

    model = llama_small(vocab_size=61, max_seq_len=64, num_layers=2,
                        num_heads=4, num_kv_heads=2, d_model=32)
    params = init_state(model, make_optimizer(),
                        input_shape=(1, 8)).params
    rng = np.random.default_rng(6)
    prompts = [rng.integers(0, 61, size=n).astype(np.int32)
               for n in (4, 11, 17)]
    eng = Engine(model, params, num_slots=2, max_len=32, prefill_chunk=8,
                 speculate_k=3)
    outs = eng.generate_many(prompts, 6)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(_reference(model, params, p, 6)[0], o)


# -- tooling gate ------------------------------------------------------


def test_serve_spec_bench_gap_gate(tmp_path):
    """tools/bench_gaps serve_spec stage: CPU smoke rows and error rows
    never close a k level; banked TPU rows do (the watcher's
    window-accumulation contract, same rules as the serve stage)."""
    import json
    import os

    from tools.bench_gaps import SERVE_SPEC_KS, serve_spec_missing

    d = str(tmp_path)
    assert serve_spec_missing(d) == list(SERVE_SPEC_KS)
    rows = [
        {"metric": "serve_spec_tokens_per_sec", "speculate_k": 2,
         "value": 900.0, "device_kind": "cpu"},           # smoke: no
        {"metric": "serve_spec_tokens_per_sec", "speculate_k": 4,
         "error": "relay wedged"},                        # error: no
        {"metric": "serve_spec_tokens_per_sec", "speculate_k": 8,
         "value": 9000.0, "device_kind": "TPU v5 lite"},  # real: yes
    ]
    with open(os.path.join(d, "serve_spec.jsonl"), "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    assert serve_spec_missing(d) == [2, 4]
    with open(os.path.join(d, "serve_spec.history.jsonl"), "w") as f:
        f.write(json.dumps(
            {"metric": "serve_spec_tokens_per_sec", "speculate_k": 2,
             "value": 7000.0, "device_kind": "TPU v5 lite"}) + "\n")
    assert serve_spec_missing(d) == [4]  # banked history row counts

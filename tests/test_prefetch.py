"""Background prefetcher: order/content preservation, exception propagation,
and clean worker shutdown.  Pure-Python — independent of the native library
(these tests must run even where g++ is unavailable)."""

import threading
import time

import numpy as np
import pytest

from tpudp.data.cifar10 import Dataset
from tpudp.data.loader import DataLoader
from tpudp.data.prefetch import Prefetcher


def _dataset(n=64, seed=0):
    rng = np.random.default_rng(seed)
    return Dataset(
        rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.uint8),
        rng.integers(0, 10, size=n).astype(np.int32),
    )


def test_prefetcher_preserves_batches():
    ds = _dataset(48)
    loader = DataLoader(ds, 16, train=True, seed=1)
    direct = list(loader)
    prefetched = list(Prefetcher(loader, depth=2))
    assert len(direct) == len(prefetched)
    for (xi, yi, wi), (xj, yj, wj) in zip(direct, prefetched):
        np.testing.assert_array_equal(xi, xj)
        np.testing.assert_array_equal(yi, yj)


def test_prefetcher_propagates_exceptions():
    class Boom:
        def __iter__(self):
            yield 1
            raise RuntimeError("boom")

        def __len__(self):
            return 2

    it = iter(Prefetcher(Boom(), depth=1))
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_prefetcher_early_break_stops_worker():
    ds = _dataset(64)
    loader = DataLoader(ds, 8, train=True)
    for i, _ in enumerate(Prefetcher(loader, depth=1)):
        if i == 1:
            break  # generator close -> stop event fires in the finally
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        workers = [t for t in threading.enumerate()
                   if t.name == "tpudp-prefetch" and t.is_alive()]
        if not workers:
            return
        time.sleep(0.05)
    raise AssertionError(f"prefetch worker leaked: {workers}")


def test_prefetcher_rejects_bad_depth():
    with pytest.raises(ValueError):
        Prefetcher([], depth=0)


def _no_live_workers(deadline_s=5.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        workers = [t for t in threading.enumerate()
                   if t.name == "tpudp-prefetch" and t.is_alive()]
        if not workers:
            return True
        time.sleep(0.05)
    return False


def test_prefetcher_abandoned_iteration_leaves_no_thread():
    """Supervisor restarts abandon iteration mid-epoch repeatedly: once
    the iterator is dropped (no explicit close), the worker must exit —
    no live tpudp-prefetch thread, no put() blocked on a full queue."""
    import gc

    ds = _dataset(64)
    pf = Prefetcher(DataLoader(ds, 8, train=True), depth=1)
    it = iter(pf)
    next(it)  # worker running, queue full (depth 1), put() blocking
    del it    # abandoned WITHOUT close(): generator finalizer must stop it
    gc.collect()
    assert _no_live_workers(), "prefetch worker leaked after abandonment"


def test_prefetcher_close_stops_workers_and_unblocks_put():
    """Explicit close(): the guaranteed path for consumers that cannot
    rely on GC finalizers (soak relaunch loops).  Idempotent, and the
    Prefetcher stays iterable afterwards."""
    ds = _dataset(64)
    pf = Prefetcher(DataLoader(ds, 8, train=True), depth=1)
    it = iter(pf)
    next(it)  # worker alive, blocked in put() on the full depth-1 queue
    holder = [it]  # keep a live reference so GC cannot help
    pf.close()
    assert _no_live_workers(), "close() left a live prefetch worker"
    del holder
    # reusable after close: a fresh iteration spawns a fresh worker
    assert len(list(pf)) == len(list(DataLoader(ds, 8, train=True)))
    pf.close()  # idempotent
    pf.close()


def test_prefetcher_place_hook_runs_on_worker_thread():
    """Device-side prefetch: set_place runs on the prefetch thread for every
    batch; yielded batches carry the placed result."""
    ds = _dataset(32)
    loader = DataLoader(ds, 8, train=False)
    threads = []
    pf = Prefetcher(loader, depth=2)
    pf.set_place(lambda b: (threads.append(threading.current_thread().name),
                            (b[0] + 1.0, b[1], b[2]))[1])
    direct = list(loader)
    placed = list(pf)
    assert len(placed) == len(direct)
    for (xi, _, _), (xj, _, _) in zip(direct, placed):
        np.testing.assert_allclose(np.asarray(xj), np.asarray(xi) + 1.0)
    assert threads and all(n == "tpudp-prefetch" for n in threads)


def test_trainer_device_prefetch_matches_direct(mesh8):
    """A Prefetcher-wrapped loader (Trainer installs its device_put as the
    place hook) must produce the identical loss trajectory to the direct
    loader — placement moves threads, not math."""
    from tests.small_model import SmallConv
    from tpudp.train import Trainer

    def run(wrap):
        ds = _dataset(32, seed=7)
        loader = DataLoader(ds, 16, train=True, seed=2)
        if wrap:
            loader = Prefetcher(loader, depth=2)
        # SmallConv: placement identity is model-agnostic and this test
        # jits TWO fresh Trainers (fast-tier margin, r4 #8).
        tr = Trainer(SmallConv(), mesh8, "allreduce", log_every=1)
        tr.train_epoch(loader, epoch=0)
        return float(tr.state.loss_sum)

    assert run(False) == run(True)

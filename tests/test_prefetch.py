"""Background prefetcher: order/content preservation, exception propagation,
and clean worker shutdown.  Pure-Python — independent of the native library
(these tests must run even where g++ is unavailable)."""

import threading
import time

import numpy as np
import pytest

from tpudp.data.cifar10 import Dataset
from tpudp.data.loader import DataLoader
from tpudp.data.prefetch import Prefetcher


def _dataset(n=64, seed=0):
    rng = np.random.default_rng(seed)
    return Dataset(
        rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.uint8),
        rng.integers(0, 10, size=n).astype(np.int32),
    )


def test_prefetcher_preserves_batches():
    ds = _dataset(48)
    loader = DataLoader(ds, 16, train=True, seed=1)
    direct = list(loader)
    prefetched = list(Prefetcher(loader, depth=2))
    assert len(direct) == len(prefetched)
    for (xi, yi, wi), (xj, yj, wj) in zip(direct, prefetched):
        np.testing.assert_array_equal(xi, xj)
        np.testing.assert_array_equal(yi, yj)


def test_prefetcher_propagates_exceptions():
    class Boom:
        def __iter__(self):
            yield 1
            raise RuntimeError("boom")

        def __len__(self):
            return 2

    it = iter(Prefetcher(Boom(), depth=1))
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_prefetcher_early_break_stops_worker():
    ds = _dataset(64)
    loader = DataLoader(ds, 8, train=True)
    for i, _ in enumerate(Prefetcher(loader, depth=1)):
        if i == 1:
            break  # generator close -> stop event fires in the finally
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        workers = [t for t in threading.enumerate()
                   if t.name == "tpudp-prefetch" and t.is_alive()]
        if not workers:
            return
        time.sleep(0.05)
    raise AssertionError(f"prefetch worker leaked: {workers}")


def test_prefetcher_rejects_bad_depth():
    with pytest.raises(ValueError):
        Prefetcher([], depth=0)

"""Tier-1 gates: the tree itself must satisfy its own static analysis.

Two pins (ISSUE 8 acceptance bar):

  * ``lint``: zero unsuppressed findings over tpudp/ — every sanctioned
    exception is a visible ``# tpudp: lint-ok(rule)`` in the diff, and
    a new hazard (host sync on a hot path, collective under divergent
    control flow, unregistered jit, ...) fails here before it can
    regress a pod run.
  * ``audit``: the registered step programs' jaxprs match the committed
    tools/trace_lock.json at the CPU smoke geometries — a recompile, a
    new host transfer, or a changed collective sequence in a pinned hot
    path is an explicit `audit --update` + lockfile diff, never a
    silent serve_bench regression.  Source digests must be fresh too,
    so the lock's provenance tracks every hot-path edit.
"""

import os

from tpudp.analysis import lint_paths

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOCK = os.path.join(ROOT, "tools", "trace_lock.json")


def test_lint_clean_over_tpudp():
    findings, errors = lint_paths(["tpudp"], ROOT)
    assert errors == [], errors
    assert findings == [], "\n".join(f.render() for f in findings) + (
        "\n\nfix the hazard, or justify it with an explicit "
        "`# tpudp: lint-ok(rule): why` (docs/ANALYSIS.md)")


def test_lint_clean_over_tools_and_benchmarks():
    """The gate/bench layer must hold the same bar — it drives the same
    donating programs and hot loops the package does."""
    findings, errors = lint_paths(["tools", "benchmarks"], ROOT)
    assert errors == [], errors
    assert findings == [], "\n".join(f.render() for f in findings)


def test_audit_matches_committed_lock(audit_capture):
    from tpudp.analysis import audit

    problems = audit.compare(audit.load_lock(LOCK), audit_capture)
    assert problems == [], "\n".join(problems) + (
        "\n\nif the trace change is intended: "
        "`python -m tpudp.analysis audit --update` and commit the "
        "tools/trace_lock.json diff (docs/ANALYSIS.md)")

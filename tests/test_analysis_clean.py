"""Tier-1 gates: the tree itself must satisfy its own static analysis.

Four pins (ISSUE 8 + ISSUE 12 acceptance bars):

  * ``lint``: zero unsuppressed findings over tpudp/ — every sanctioned
    exception is a visible ``# tpudp: lint-ok(rule)`` in the diff, and
    a new hazard (host sync on a hot path, collective under divergent
    control flow, unregistered jit, ...) fails here before it can
    regress a pod run.
  * ``audit``: the registered step programs' jaxprs match the committed
    tools/trace_lock.json at the CPU smoke geometries — a recompile, a
    new host transfer, or a changed collective sequence in a pinned hot
    path is an explicit `audit --update` + lockfile diff, never a
    silent serve_bench regression.  Source digests must be fresh too,
    so the lock's provenance tracks every hot-path edit.
  * ``protocol``: the cross-host protocol verifier reports zero
    unsuppressed findings over the multihost modules, and the vote
    state machine extracted from the live resilience source explores
    deadlock-free — any new per-host-guarded rendezvous divergence is
    an explicit reviewed suppression, never a latent pod deadlock.
  * ``budget``: every pinned program's resource ledger (peak live
    bytes, collective payload) is committed in the lock together with
    the capture geometry — the upcoming paged-attention/TP-serving
    work cannot silently regress HBM footprint or comms volume.
"""

import os

from tpudp.analysis import lint_paths

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOCK = os.path.join(ROOT, "tools", "trace_lock.json")


def test_lint_clean_over_tpudp():
    findings, errors = lint_paths(["tpudp"], ROOT)
    assert errors == [], errors
    assert findings == [], "\n".join(f.render() for f in findings) + (
        "\n\nfix the hazard, or justify it with an explicit "
        "`# tpudp: lint-ok(rule): why` (docs/ANALYSIS.md)")


def test_lint_clean_over_tools_and_benchmarks():
    """The gate/bench layer must hold the same bar — it drives the same
    donating programs and hot loops the package does."""
    findings, errors = lint_paths(["tools", "benchmarks"], ROOT)
    assert errors == [], errors
    assert findings == [], "\n".join(f.render() for f in findings)


def test_audit_matches_committed_lock(audit_capture):
    from tpudp.analysis import audit

    problems = audit.compare(audit.load_lock(LOCK), audit_capture)
    assert problems == [], "\n".join(problems) + (
        "\n\nif the trace change is intended: "
        "`python -m tpudp.analysis audit --update` and commit the "
        "tools/trace_lock.json diff (docs/ANALYSIS.md)")


def test_protocol_clean_over_tree():
    """Zero unsuppressed protocol findings tree-wide: every sanctioned
    divergence (bounded-vote arms, the coordinated walk's alignment
    loop, single-host-only exits) is a visible
    `# tpudp: lint-ok(protocol-*)` with its justification."""
    from tpudp.analysis.protocol import verify_paths

    findings, errors = verify_paths(["tpudp"], ROOT)
    assert errors == [], errors
    assert findings == [], "\n".join(f.render() for f in findings) + (
        "\n\nmake the rendezvous host-uniform (route the per-host fact "
        "through a vote), or justify it with an explicit "
        "`# tpudp: lint-ok(protocol-rule): why` (docs/ANALYSIS.md)")


def test_vote_machine_spec_holds():
    """The extracted vote/park spec must keep both load-bearing
    properties (completion park + bounded timeout) and explore
    deadlock-free — deleting either from resilience.py fails tier-1."""
    from tpudp.analysis.protocol import (explore_vote_machine,
                                         extract_vote_spec)

    with open(os.path.join(ROOT, "tpudp", "resilience.py")) as f:
        spec = extract_vote_spec(f.read(), n_hosts=3, max_faults=2,
                                 max_crashes=1)
    assert spec.completion_park, (
        "Supervisor.run no longer parks clean finishers at a "
        "completion vote — a late faulter would find no vote partner")
    assert spec.bounded_timeout, (
        "Supervisor._vote no longer bounds the vote wait — a dead peer "
        "would hang survivors forever")
    result = explore_vote_machine(spec)
    assert result["violations"] == [], result["violations"][:3]


def test_budget_ledgers_fresh_in_lock(audit_capture):
    """Every pinned program carries a committed resource ledger, and
    the committed ledgers equal the live capture's (the audit-compare
    gate above covers deltas; this pins PRESENCE, so a lock written by
    an old auditor cannot silently drop the budgets)."""
    import json

    with open(LOCK) as f:
        lock = json.load(f)
    assert lock.get("geometry") == audit_capture["geometry"]
    assert set(lock["programs"]) == set(audit_capture["programs"])
    for name, rec in lock["programs"].items():
        assert "budget" in rec, f"{name} has no committed budget ledger"
        assert rec["budget"] == audit_capture["programs"][name]["budget"]
